"""dptlint (distributedpytorch_tpu/analysis): mutation tests pinning the
analyzer's teeth, clean-tree passes, and the AST lint rules.

The load-bearing contract (ISSUE 5 acceptance): each seeded mutation —
a flipped 1F1B phase-B ppermute edge, a dropped DDP grad psum, a psum
guarded by a ``process_index()==0`` branch — must be flagged with an
actionable one-line diagnostic, in under 60 s, with ZERO device
execution (the ``no_compile`` fixture makes any XLA compile raise), and
the clean tree must pass every rule for every strategy × schedule combo.
"""

import json
import os
import time

import jax
import pytest

import distributedpytorch_tpu.parallel.pipeline as pipeline
from distributedpytorch_tpu.analysis import Finding, dedupe
from distributedpytorch_tpu.analysis import collectives, lint
from distributedpytorch_tpu.analysis.cli import run as analyze_cli_run

MUTATION_BUDGET_S = 60.0


@pytest.fixture
def no_compile(monkeypatch):
    """Prove zero device execution: the analyzer's trace-only path must
    never reach XLA compilation (compilation is the doorway to running
    collectives); any AOT compile during the test raises."""

    def boom(self, *a, **k):
        raise AssertionError(
            "analyzer compiled an executable during a trace-only check"
        )

    monkeypatch.setattr(jax.stages.Lowered, "compile", boom)


# ---------------------------------------------------------------------------
class TestExtraction:
    def test_1f1b_program_extracted_with_attribution(self):
        colls = collectives.extract_collectives(
            collectives.trace_train("MP", "1f1b")
        )
        pp = [c for c in colls if c.kind == "ppermute"]
        ps = [c for c in colls if c.kind == "psum"]
        assert pp and ps
        # every ppermute sits under the shard_map with 'stage' bound
        assert all("stage" in c.bound_axes for c in pp)
        # the explicit schedule's conds attribute producers AND consumers
        assert all(c.producer_stage is not None for c in pp)
        assert all(c.consumer_stages for c in pp)
        # the schedule-closing grad psum feeds the step outputs
        assert any(c.direct_output for c in ps)

    def test_gspmd_strategy_has_empty_jaxpr_program(self):
        # DP's collectives are GSPMD-inserted at compile time: the traced
        # program contains none — which is exactly why its contract lives
        # in the HLO tier
        assert collectives.extract_collectives(
            collectives.trace_train("DP")) == []


# ---------------------------------------------------------------------------
class TestCleanTree:
    def test_every_strategy_schedule_combo_passes(self, no_compile):
        findings, tags = collectives.analyze()
        assert findings == [], "\n".join(f.line for f in findings)
        assert set(tags) == {
            "DP", "SP", "TP", "FSDP", "MP/gpipe", "MP/1f1b",
            "DDP_MP/gpipe", "DDP_MP/1f1b",
        }

    def test_package_source_is_lint_clean(self):
        findings, n_files = lint.lint_package()
        assert n_files > 30  # the whole package was actually walked
        assert findings == [], "\n".join(f.line for f in findings)


# ---------------------------------------------------------------------------
class TestSeededMutations:
    """The three ISSUE-5 mutations, each: flagged, actionable, <60 s,
    no device execution."""

    def test_flipped_1f1b_phase_b_edge_deadlocks_statically(
        self, monkeypatch, no_compile
    ):
        t0 = time.monotonic()
        orig = pipeline._ppermute_edge

        def flipped(tree, axis_name, edge, reverse=False):
            # the seeded bug: cotangent edge 0 ships forward (0→1)
            # instead of reverse (1→0) — dynamically this hangs the CPU
            # rendezvous until the 300 s pytest-timeout
            if reverse and edge == 0:
                return orig(tree, axis_name, edge, reverse=False)
            return orig(tree, axis_name, edge, reverse=reverse)

        monkeypatch.setattr(pipeline, "_ppermute_edge", flipped)
        findings = collectives.analyze_combo("MP", "1f1b", rank_check=False)
        elapsed = time.monotonic() - t0
        rules = {f.rule for f in findings}
        assert "ppermute-deadlock" in rules, findings
        msgs = " | ".join(f.message for f in findings)
        assert "stage 1" in msgs and "((0, 1),)" in msgs  # actionable
        assert elapsed < MUTATION_BUDGET_S

    def test_dropped_ddp_grad_psum_breaks_contract(
        self, monkeypatch, no_compile
    ):
        t0 = time.monotonic()
        monkeypatch.setattr(
            pipeline, "_reduce_grads",
            # the seeded bug: the stage psum survives but the 'data'
            # axis — the DDP all-reduce — is dropped, so data replicas
            # would silently diverge
            lambda grads, axes: jax.lax.psum(grads, ("stage",)),
        )
        findings = collectives.analyze_combo(
            "DDP_MP", "1f1b", rank_check=False
        )
        elapsed = time.monotonic() - t0
        assert any(
            f.rule == "comms-contract" and "data" in f.message
            for f in findings
        ), findings
        assert elapsed < MUTATION_BUDGET_S

    def test_contract_checked_even_without_explicit_schedule(
        self, monkeypatch, no_compile
    ):
        # analyze_combo("DDP_MP") with no schedule traces the gpipe
        # program — the contract key must follow, or the lookup misses
        # JAXPR_CONTRACTS and the check silently passes (review
        # regression). gpipe's 'data' reduction is autodiff-inserted
        # (no mutable seam), so pin the key resolution directly: plant
        # an unsatisfiable requirement under the resolved gpipe key —
        # only a lookup that followed the traced schedule can find it.
        contracts = dict(collectives.JAXPR_CONTRACTS)
        contracts[("DDP_MP", "gpipe")] = (
            collectives.JaxprComm(
                "reduce_scatter", frozenset({"data"}),
                why="planted: the no-schedule call must resolve gpipe",
            ),
        )
        monkeypatch.setattr(collectives, "JAXPR_CONTRACTS", contracts)
        findings = collectives.analyze_combo("DDP_MP", rank_check=False)
        assert any(
            f.rule == "comms-contract" and "data" in f.message
            for f in findings
        ), findings

    def test_rank_gated_psum_breaks_uniformity(
        self, monkeypatch, no_compile
    ):
        t0 = time.monotonic()
        orig = pipeline._reduce_grads

        def gated(grads, axes):
            # the seeded bug: a collective behind a rank-dependent
            # PYTHON branch — each rank traces a different program
            if jax.process_index() == 0:
                return orig(grads, axes)
            return grads

        monkeypatch.setattr(pipeline, "_reduce_grads", gated)
        findings = collectives.analyze_combo("MP", "1f1b", rank_check=True)
        elapsed = time.monotonic() - t0
        assert any(
            f.rule == "rank-divergent-collective" for f in findings
        ), findings
        assert elapsed < MUTATION_BUDGET_S

    def test_rank_gated_collective_also_caught_by_source_lint(self):
        # the same seeded bug, at the source level (no trace needed)
        src = (
            "import jax\n"
            "def reduce_grads(grads, axes):\n"
            "    if jax.process_index() == 0:\n"
            "        return jax.lax.psum(grads, axes)\n"
            "    return grads\n"
        )
        findings = lint.lint_source(src, "pkg/bad.py")
        assert [f.rule for f in findings] == ["rank-gated-collective"]
        assert "pkg/bad.py:4" in findings[0].where


# ---------------------------------------------------------------------------
class TestCollectiveFingerprint:
    """The ``collective-fingerprint`` rule (ISSUE 10 satellite): a short
    stable hash of each combo's ORDERED collective program, compared
    across every simulated rank of the job's world size in the
    multi-process launch preflight — catching gloo desyncs the dual-rank
    (0 vs 1) re-trace cannot see, before any rank spawns."""

    def test_stable_across_retraces(self, no_compile):
        a = collectives.collective_fingerprint("MP", "1f1b")
        b = collectives.collective_fingerprint("MP", "1f1b")
        assert a == b and len(a) == 16

    def test_schedules_fingerprint_differently(self, no_compile):
        assert (collectives.collective_fingerprint("MP", "gpipe")
                != collectives.collective_fingerprint("MP", "1f1b"))

    def test_clean_tree_matches_across_world(self, no_compile):
        findings, table = collectives.fingerprint_combos(
            ["MP"], ["1f1b"], world=3
        )
        assert findings == []
        fps = table["MP/1f1b"]
        assert len(fps) == 3 and len(set(fps)) == 1

    def test_rank2_gated_collective_needs_world_3(
        self, monkeypatch, no_compile
    ):
        """The gap this rule closes: a collective gated on
        ``process_index() == 2`` traces identically on simulated ranks
        0 and 1 (both skip it), so the dual-rank fingerprint pair
        matches — only fingerprinting the job's ACTUAL world size (3)
        sees rank 2's divergent program."""
        orig = pipeline._reduce_grads

        def gated(grads, axes):
            if jax.process_index() == 2:
                return orig(grads, axes)
            return grads

        monkeypatch.setattr(pipeline, "_reduce_grads", gated)
        f2, table2 = collectives.fingerprint_combos(["MP"], ["1f1b"], 2)
        assert f2 == []  # ranks 0 and 1 agree — the old check's blind spot
        assert len(set(table2["MP/1f1b"])) == 1
        f3, table3 = collectives.fingerprint_combos(["MP"], ["1f1b"], 3)
        assert [f.rule for f in f3] == ["collective-fingerprint"]
        assert "rank(s) [2]" in f3[0].message
        assert "desync" in f3[0].message
        assert len(set(table3["MP/1f1b"])) == 2

    def test_cli_rejects_world_of_one(self):
        # a world of 1 has nothing to compare; silently skipping the
        # gate while reporting clean would be false confidence
        with pytest.raises(SystemExit):
            analyze_cli_run(["--fingerprint-world", "1"])
        with pytest.raises(SystemExit):
            analyze_cli_run(["--fingerprint-world", "-3"])

    def test_cli_rejects_fingerprint_with_lint_only_layer(self):
        # --layer lint never runs the collectives layer, so the
        # requested desync gate would silently not execute — refuse
        # (rc 2, infra) instead of reporting a false clean
        rc = analyze_cli_run(
            ["--layer", "lint", "--fingerprint-world", "2"])
        assert rc == 2

    def test_cli_reports_fingerprints(self, tmp_path):
        report = tmp_path / "report.json"
        rc = analyze_cli_run([
            "--layer", "collectives", "--strategies", "MP",
            "--schedules", "1f1b", "--no-rank-check",
            "--fingerprint-world", "2", "--json", str(report),
        ])
        assert rc == 0
        payload = json.loads(report.read_text())
        fps = payload["fingerprints"]["MP/1f1b"]
        assert len(fps) == 2 and fps[0] == fps[1]


# ---------------------------------------------------------------------------
class TestFingerprintSnapshot:
    """The ``fingerprint-snapshot`` rule (PR 19 satellite): persist each
    combo's ordered-collective fingerprint with the toolchain identity,
    and compare across jax upgrades — drift both sides of an upgrade can
    be internally consistent about, which the per-run contract check can
    therefore never see. Hybrid mesh specs ride the same surface."""

    def test_write_then_check_roundtrip_clean(self, tmp_path, no_compile):
        path = tmp_path / "snap.json"
        payload = collectives.write_fingerprint_snapshot(
            str(path), strategies=["MP", "2x2x2"], schedules=["1f1b"],
        )
        assert set(payload["fingerprints"]) == {"MP/1f1b", "2x2x2/1f1b"}
        assert payload["jax"] == jax.__version__
        loaded = collectives.load_fingerprint_snapshot(str(path))
        assert loaded == payload
        assert collectives.check_fingerprint_snapshot(loaded) == []

    def test_seeded_drift_is_flagged_with_both_versions(
        self, tmp_path, no_compile
    ):
        path = tmp_path / "snap.json"
        payload = collectives.write_fingerprint_snapshot(
            str(path), strategies=["MP"], schedules=["gpipe"],
        )
        payload["fingerprints"]["MP/gpipe"] = "0" * 16
        payload["jax"] = "0.0.1"
        findings = collectives.check_fingerprint_snapshot(payload)
        assert [f.rule for f in findings] == ["fingerprint-snapshot"]
        assert "recorded under jax 0.0.1" in findings[0].message
        assert f"current jax {jax.__version__}" in findings[0].message

    def test_vanished_combo_is_the_loudest_drift(self, no_compile):
        payload = {
            "version": collectives.SNAPSHOT_VERSION,
            "jax": "0.0.1", "jaxlib": "0.0.1",
            "fingerprints": {"1x2x2@sp/gpipe": "f" * 16},
        }
        findings = collectives.check_fingerprint_snapshot(payload)
        assert [f.rule for f in findings] == ["fingerprint-snapshot"]
        assert "no longer traces" in findings[0].message

    def test_unreadable_snapshot_is_none_never_clean(self, tmp_path):
        assert collectives.load_fingerprint_snapshot(
            str(tmp_path / "missing.json")) is None
        bad = tmp_path / "bad.json"
        bad.write_text("{not json")
        assert collectives.load_fingerprint_snapshot(str(bad)) is None
        skew = tmp_path / "skew.json"
        skew.write_text(json.dumps({"version": 999, "fingerprints": {}}))
        assert collectives.load_fingerprint_snapshot(str(skew)) is None

    def test_cli_check_flags_drift_and_missing_is_infra(self, tmp_path):
        path = tmp_path / "snap.json"
        rc = analyze_cli_run([
            "--layer", "collectives", "--strategies", "MP",
            "--schedules", "gpipe", "--no-rank-check",
            "--fingerprint-snapshot", "write", "--snapshot-path",
            str(path),
        ])
        assert rc == 0
        payload = json.loads(path.read_text())
        payload["fingerprints"]["MP/gpipe"] = "d" * 16
        path.write_text(json.dumps(payload))
        rc = analyze_cli_run([
            "--layer", "collectives", "--strategies", "MP",
            "--schedules", "gpipe", "--no-rank-check",
            "--fingerprint-snapshot", "check", "--snapshot-path",
            str(path),
        ])
        assert rc == 1
        rc = analyze_cli_run([
            "--layer", "collectives", "--strategies", "MP",
            "--schedules", "gpipe", "--no-rank-check",
            "--fingerprint-snapshot", "check", "--snapshot-path",
            str(tmp_path / "missing.json"),
        ])
        assert rc == 2
        # lint-only layer can't trace: refuse, never a false clean
        rc = analyze_cli_run([
            "--layer", "lint", "--fingerprint-snapshot", "check",
            "--snapshot-path", str(path),
        ])
        assert rc == 2


# ---------------------------------------------------------------------------
class TestContractTables:
    def test_jaxpr_contract_covers_every_analyzed_combo(self):
        for method, schedule in collectives.combos_for():
            key = (
                method,
                schedule if method in collectives.PIPELINE_STRATEGIES
                else None,
            )
            assert key in collectives.JAXPR_CONTRACTS

    def test_pipeline_contracts_require_the_ddp_all_reduce(self):
        reqs = collectives.JAXPR_CONTRACTS[("DDP_MP", "1f1b")]
        assert any(
            r.grad_output and "data" in r.axes and r.kind == "psum"
            for r in reqs
        )

    def test_hlo_table_matches_analyzed_strategies(self):
        # every GSPMD strategy is covered by the HLO tier (TP via the
        # any-of set); the table is what test_hlo_collectives imports
        assert set(collectives.EXPECTED_HLO_COLLECTIVES) >= {
            "DP", "SP", "FSDP", "MP",
        }
        assert collectives.TP_HLO_ANY_OF


# ---------------------------------------------------------------------------
class TestLintRules:
    def test_nondeterminism_inside_jitted_function(self):
        src = (
            "import time, jax\n"
            "def step(x):\n"
            "    return x * time.time()\n"
            "fast = jax.jit(step)\n"
        )
        findings = lint.lint_source(src, "m.py")
        assert [f.rule for f in findings] == ["trace-nondeterminism"]

    def test_nondeterminism_inside_make_builder_closure(self):
        src = (
            "import numpy as np\n"
            "def make_train_step(model):\n"
            "    def step(state, batch):\n"
            "        noise = np.random.rand()\n"
            "        return state, noise\n"
            "    return step\n"
        )
        findings = lint.lint_source(src, "m.py")
        assert [f.rule for f in findings] == ["trace-nondeterminism"]

    def test_scan_data_operands_are_not_marked_traced(self):
        # jax.lax.scan(f, init, xs): init/xs are DATA — a host function
        # that happens to share a data operand's name must not be
        # poisoned as "traced" (review regression)
        src = (
            "import time, jax\n"
            "def f(c, x):\n"
            "    return c, x\n"
            "def run(xs, init):\n"
            "    return jax.lax.scan(f, init, xs)\n"
            "def init(seed):\n"
            "    return time.time() + seed\n"
        )
        assert lint.lint_source(src, "m.py") == []

    def test_cond_branch_callables_are_marked_traced(self):
        src = (
            "import time, jax\n"
            "def hot(x):\n"
            "    return x * time.time()\n"
            "def cold(x):\n"
            "    return x\n"
            "def run(p, x):\n"
            "    return jax.lax.cond(p, hot, cold, x)\n"
        )
        findings = lint.lint_source(src, "m.py")
        assert [f.rule for f in findings] == ["trace-nondeterminism"]

    def test_cond_data_operand_is_not_marked_traced(self):
        # cond(pred, true_fn, false_fn, *operands): the operands are
        # DATA — a host function sharing an operand's name must not be
        # poisoned as "traced" (review regression)
        src = (
            "import time, jax\n"
            "def run(p, x, helper):\n"
            "    return jax.lax.cond(p, lambda v: v, lambda v: v, helper)\n"
            "def helper(x):\n"
            "    return time.time() + x\n"
        )
        assert lint.lint_source(src, "m.py") == []

    def test_switch_branches_list_is_marked_traced(self):
        # switch(index, branches, *operands): the branch callables
        # arrive inside a literal list (review regression — the list
        # was never unpacked, so branch bodies went unchecked)
        src = (
            "import time, jax\n"
            "def hot(x):\n"
            "    return x * time.time()\n"
            "def cold(x):\n"
            "    return x\n"
            "def run(i, x):\n"
            "    return jax.lax.switch(i, [hot, cold], x)\n"
        )
        findings = lint.lint_source(src, "m.py")
        assert [f.rule for f in findings] == ["trace-nondeterminism"]

    def test_associative_scan_fn_is_marked_traced(self):
        # review regression: the entrypoint table had the typo
        # "associated_scan", so this traced fn was never checked
        src = (
            "import time, jax\n"
            "def combine(a, b):\n"
            "    return a + b * time.time()\n"
            "def run(xs):\n"
            "    return jax.lax.associative_scan(combine, xs)\n"
        )
        findings = lint.lint_source(src, "m.py")
        assert [f.rule for f in findings] == ["trace-nondeterminism"]

    def test_host_randomness_outside_trace_is_fine(self):
        src = (
            "import time, numpy as np\n"
            "def shuffle(n, seed):\n"
            "    t0 = time.time()\n"
            "    return np.random.default_rng(seed).permutation(n), t0\n"
        )
        assert lint.lint_source(src, "m.py") == []

    def test_use_after_donation_direct_and_alias(self):
        src = (
            "def run(self, state, batch):\n"
            "    prev = self.state\n"
            "    new_state, loss = self.train_step(self.state, batch)\n"
            "    a = self.state\n"       # direct use-after-donation
            "    b = prev\n"             # alias use-after-donation
            "    return new_state\n"
        )
        findings = lint.lint_source(src, "m.py")
        assert [f.rule for f in findings] == ["use-after-donation"] * 2

    def test_rebinding_assignment_is_not_flagged(self):
        src = (
            "def run(self, batch):\n"
            "    self.state, loss = self.train_step(self.state, batch)\n"
            "    self.record(self.state, loss)\n"
        )
        assert lint.lint_source(src, "m.py") == []

    def test_line_wrapped_rebinding_is_not_flagged(self):
        # the rebind is recognized by the call node living inside the
        # assignment's value, not by line-number equality — a formatter
        # wrapping the statement must not create findings (review
        # regression)
        src = (
            "def run(self, batch):\n"
            "    self.state, loss = (\n"
            "        self.train_step(self.state, batch))\n"
            "    self.record(self.state, loss)\n"
        )
        assert lint.lint_source(src, "m.py") == []

    def test_hot_path_host_sync_flagged_and_drain_sanctioned(self):
        src = (
            "import numpy as np\n"
            "class Trainer:\n"
            "    def train(self):\n"
            "        def run_one(batch, losses):\n"
            "            host = np.asarray(losses)\n"  # hot-path sync
            "            def pull():\n"
            "                return np.asarray(losses)\n"  # sanctioned
            "            return host, pull\n"
            "        return run_one\n"
        )
        findings = lint.lint_source(
            src, "distributedpytorch_tpu/train/loop.py"
        )
        assert [f.rule for f in findings] == ["host-sync-hot-path"]
        assert findings[0].where.endswith(":5")

    def test_item_flagged_package_wide_but_not_in_drain_modules(self):
        src = "def f(loss):\n    return loss.item()\n"
        assert [f.rule for f in lint.lint_source(src, "pkg/train/x.py")] == [
            "host-sync-hot-path"
        ]
        assert lint.lint_source(
            src, "distributedpytorch_tpu/utils/metrics.py") == []

    def test_block_until_ready_flagged_in_both_forms(self):
        # the function form jax.block_until_ready(x) syncs exactly like
        # the method form and must not slip through (review regression)
        for src in (
            "def f(x):\n    return x.block_until_ready()\n",
            "import jax\ndef f(x):\n    return jax.block_until_ready(x)\n",
        ):
            findings = lint.lint_source(src, "pkg/train/x.py")
            assert [f.rule for f in findings] == ["host-sync-hot-path"], src

    def test_inline_suppression(self):
        src = (
            "import time, jax\n"
            "def step(x):\n"
            "    return x * time.time()  "
            "# dptlint: disable=trace-nondeterminism — test seam\n"
            "fast = jax.jit(step)\n"
        )
        assert lint.lint_source(src, "m.py") == []

    def test_suppression_list_with_spaces_covers_every_rule(self):
        # "disable=a, b" (natural comma+space style) must suppress BOTH
        # rules — the regex stopping at whitespace silently dropped the
        # second one (review regression). The listed rule that fires is
        # absorbed; the listed rule that does NOT fire on this line is
        # reported by the hygiene pass as stale — never re-surfaced as
        # the rule itself.
        src = (
            "import time, jax\n"
            "def step(x):\n"
            "    return x * time.time()  "
            "# dptlint: disable=host-sync-hot-path, trace-nondeterminism\n"
            "fast = jax.jit(step)\n"
        )
        findings = lint.lint_source(src, "m.py")
        assert [f.rule for f in findings] == ["stale-suppression"]
        assert "host-sync-hot-path" in findings[0].message

    def test_unknown_rule_suppression_does_not_mask(self):
        # the typo'd rule suppresses nothing — the real finding still
        # fires, and the hygiene pass names the typo itself
        src = (
            "import time, jax\n"
            "def step(x):\n"
            "    return x * time.time()  # dptlint: disable=other-rule\n"
            "fast = jax.jit(step)\n"
        )
        findings = lint.lint_source(src, "m.py")
        assert sorted(f.rule for f in findings) == [
            "trace-nondeterminism", "unknown-suppression",
        ]

    def test_dedupe_collapses_identical_findings(self):
        f = Finding(rule="r", where="w", message="m", layer="lint")
        out = dedupe([f, f, f])
        assert len(out) == 1 and out[0].count == 3
        assert "[x3]" in out[0].line


# ---------------------------------------------------------------------------
class TestObsHotPathRule:
    """The telemetry layer's hot-path contract (ISSUE 7): obs record
    paths never block or grow without bound, and telemetry calls never
    land inside traced functions (docs/ANALYSIS.md row, docs/
    OBSERVABILITY.md contract)."""

    OBS_PATH = "distributedpytorch_tpu/obs/x.py"

    def test_blocking_sync_in_record_path_flagged(self):
        src = (
            "import numpy as np\n"
            "class R:\n"
            "    def record(self, x):\n"
            "        return np.asarray(x)\n"
        )
        findings = lint.lint_source(src, self.OBS_PATH)
        assert "obs-hot-path" in [f.rule for f in findings]

    def test_unbounded_append_in_record_path_flagged(self):
        src = (
            "class R:\n"
            "    def __init__(self):\n"
            "        self._events = []\n"
            "    def record(self, x):\n"
            "        self._events.append(x)\n"
        )
        findings = lint.lint_source(src, self.OBS_PATH)
        assert [f.rule for f in findings] == ["obs-hot-path"]
        assert "deque(maxlen" in findings[0].message

    def test_deque_maxlen_ring_append_is_sanctioned(self):
        src = (
            "import collections\n"
            "class R:\n"
            "    def __init__(self):\n"
            "        self._events = collections.deque(maxlen=8)\n"
            "    def record(self, x):\n"
            "        self._events.append(x)\n"
        )
        assert lint.lint_source(src, self.OBS_PATH) == []

    def test_annotated_deque_assignment_is_recognized(self):
        # flight.py's own idiom: an AnnAssign-constructed ring
        src = (
            "import collections\n"
            "class R:\n"
            "    def __init__(self):\n"
            "        self._events: collections.deque = "
            "collections.deque(maxlen=8)\n"
            "    def record_span(self, x):\n"
            "        self._events.append(x)\n"
        )
        assert lint.lint_source(src, self.OBS_PATH) == []

    def test_append_outside_record_path_not_flagged(self):
        src = (
            "class R:\n"
            "    def expose(self):\n"
            "        lines = []\n"
            "        lines.append('x')\n"
            "        return lines\n"
        )
        assert lint.lint_source(src, self.OBS_PATH) == []

    def test_append_outside_obs_module_not_flagged(self):
        src = (
            "class R:\n"
            "    def record(self, x):\n"
            "        self._events.append(x)\n"
        )
        assert lint.lint_source(src, "pkg/serve/x.py") == []

    def test_obs_call_inside_traced_function_flagged(self):
        src = (
            "import jax\n"
            "from distributedpytorch_tpu.obs import flight\n"
            "from distributedpytorch_tpu.obs import defs as obsm\n"
            "def make_step():\n"
            "    def step(s, b):\n"
            "        flight.record('step', step=1)\n"
            "        obsm.TRAIN_STEPS.inc()\n"
            "        return s\n"
            "    return jax.jit(step)\n"
        )
        findings = [
            f for f in lint.lint_source(src, "pkg/train/x.py")
            if f.rule == "obs-hot-path"
        ]
        assert len(findings) == 2
        assert all("trace time" in f.message for f in findings)

    def test_obs_call_on_host_loop_is_fine(self):
        src = (
            "from distributedpytorch_tpu.obs import flight\n"
            "def train_loop(batches):\n"
            "    for b in batches:\n"
            "        flight.record('step')\n"
        )
        assert lint.lint_source(src, "pkg/train/x.py") == []

    def test_mark_fn_unbounded_append_flagged(self):
        """ISSUE 13: the rule reaches obs/reqtrace.py's request-trace
        lifecycle — ``mark_*`` stamps ride the serve dispatch hot path
        and ``complete`` appends ledgers, so both are record scope."""
        src = (
            "class T:\n"
            "    def __init__(self):\n"
            "        self._spans = []\n"
            "    def mark_flushed(self, t):\n"
            "        self._spans.append(t)\n"
        )
        findings = lint.lint_source(
            src, "distributedpytorch_tpu/obs/reqtrace.py"
        )
        assert [f.rule for f in findings] == ["obs-hot-path"]
        assert "deque(maxlen" in findings[0].message

    def test_complete_fn_blocking_sync_flagged(self):
        src = (
            "import numpy as np\n"
            "class T:\n"
            "    def complete(self, out):\n"
            "        return np.asarray(out)\n"
        )
        findings = lint.lint_source(
            src, "distributedpytorch_tpu/obs/reqtrace.py"
        )
        assert "obs-hot-path" in [f.rule for f in findings]

    def test_shipped_reqtrace_module_is_clean(self):
        """The real obs/reqtrace.py under the extended rule: ledger and
        profile appends are deque(maxlen=...) rings, nothing blocks."""
        import distributedpytorch_tpu.obs.reqtrace as reqtrace_mod

        path = reqtrace_mod.__file__
        findings = lint.lint_file(
            path,
            root=os.path.dirname(os.path.dirname(os.path.dirname(path))),
        )
        assert findings == [], findings

    def test_shipped_obs_package_is_clean(self):
        import distributedpytorch_tpu.obs as obs_pkg

        root = os.path.dirname(obs_pkg.__file__)
        for fname in sorted(os.listdir(root)):
            if not fname.endswith(".py"):
                continue
            findings = lint.lint_file(
                os.path.join(root, fname),
                root=os.path.dirname(os.path.dirname(root)),
            )
            assert findings == [], (fname, findings)


# ---------------------------------------------------------------------------
class TestServeHotPathRule:
    """The serve-tier twin of host-sync-hot-path (ISSUE 6): blocking
    host syncs inside the serve dispatch pipeline (serve/server.py's
    ``_bucket_stream``/``_place``/``_dispatch_loop``) stall every
    in-flight request on every replica; the completion drain (``pull``)
    is the sanctioned exemption, mirroring the train rule's mechanism."""

    SERVE_PATH = "distributedpytorch_tpu/serve/server.py"

    def test_sync_in_dispatch_loop_flagged(self):
        src = (
            "import numpy as np\n"
            "class Server:\n"
            "    def _dispatch_loop(self):\n"
            "        for item in self.stream:\n"
            "            out = self.engine.run(item)\n"
            "            return np.asarray(out)\n"
        )
        findings = lint.lint_source(src, self.SERVE_PATH)
        assert [f.rule for f in findings] == ["serve-hot-path"]
        assert findings[0].where.endswith(":6")

    def test_item_and_block_until_ready_flagged_in_serve_scope(self):
        src = (
            "def _place(self, kind, payload):\n"
            "    x = self.engine.place(payload)\n"
            "    x.block_until_ready()\n"
            "    return x.item()\n"
        )
        rules = [f.rule for f in lint.lint_source(src, self.SERVE_PATH)]
        # both calls also trip the package-wide blocking rule — the
        # serve rule must ADD its scope-specific findings, not replace it
        assert rules.count("serve-hot-path") == 2
        assert rules.count("host-sync-hot-path") == 2

    def test_pull_is_the_sanctioned_drain(self):
        # the real architecture: np.asarray lives in the completion
        # drain — both as a module-level fn and nested inside the loop
        for src in (
            "import numpy as np\n"
            "def pull(server, out):\n"
            "    return np.asarray(out)\n",
            "import numpy as np\n"
            "class Server:\n"
            "    def _dispatch_loop(self):\n"
            "        def pull(out):\n"
            "            return np.asarray(out)\n"
            "        return pull\n",
        ):
            assert [
                f for f in lint.lint_source(src, self.SERVE_PATH)
                if f.rule == "serve-hot-path"
            ] == [], src

    def test_scope_is_serve_server_only(self):
        # same source outside serve/server.py (or outside the scoped
        # functions inside it): the serve rule stays silent
        src = (
            "import numpy as np\n"
            "class Server:\n"
            "    def _dispatch_loop(self):\n"
            "        return np.asarray(self.out)\n"
        )
        assert [
            f for f in lint.lint_source(
                src, "distributedpytorch_tpu/serve/engine.py")
            if f.rule == "serve-hot-path"
        ] == []
        ingress = (
            "import numpy as np\n"
            "class Server:\n"
            "    def submit(self, images):\n"
            "        return np.asarray(images)\n"  # ingress may block
        )
        assert [
            f for f in lint.lint_source(ingress, self.SERVE_PATH)
            if f.rule == "serve-hot-path"
        ] == []

    def test_inline_suppression(self):
        src = (
            "import numpy as np\n"
            "class Server:\n"
            "    def _dispatch_loop(self):\n"
            "        return np.asarray(self.out)  "
            "# dptlint: disable=serve-hot-path — drained at shutdown\n"
        )
        assert [
            f for f in lint.lint_source(src, self.SERVE_PATH)
            if f.rule == "serve-hot-path"
        ] == []

    def test_shipped_server_module_is_clean(self):
        import distributedpytorch_tpu.serve.server as server_mod

        path = server_mod.__file__
        findings = lint.lint_file(
            path, root=os.path.dirname(
                os.path.dirname(os.path.dirname(path)))
        )
        assert findings == [], findings


# ---------------------------------------------------------------------------
class TestCli:
    def test_lint_layer_runs_clean_and_writes_report(self, tmp_path):
        report = tmp_path / "report.json"
        rc = analyze_cli_run(["--layer", "lint", "--json", str(report)])
        assert rc == 0
        payload = json.loads(report.read_text())
        assert payload["clean"] is True
        assert payload["lint_files"] > 30

    def test_findings_exit_code_and_report(self, tmp_path, monkeypatch):
        # a lint root containing one bad file → rc 1 + findings in JSON
        bad = tmp_path / "pkg"
        bad.mkdir()
        (bad / "bad.py").write_text(
            "import jax\n"
            "def f(g, axes):\n"
            "    if jax.process_index() == 0:\n"
            "        return jax.lax.psum(g, axes)\n"
            "    return g\n"
        )
        report = tmp_path / "report.json"
        rc = analyze_cli_run([
            "--layer", "lint", "--lint-root", str(bad),
            "--json", str(report),
        ])
        assert rc == 1
        payload = json.loads(report.read_text())
        assert payload["clean"] is False
        assert payload["findings"][0]["rule"] == "rank-gated-collective"


class TestDtypePolicyRule:
    """The mixed-precision cast-boundary rule (ops/precision.py,
    docs/PERFORMANCE.md "Precision"): bare f32 spellings in traced code
    are upcasts the --dtype policy cannot see. The ROADMAP's
    "dtype-policy rule once bf16 lands" item."""

    def test_bare_f32_literal_in_make_builder_flagged(self):
        src = (
            "import jax.numpy as jnp\n"
            "def make_train_step(model):\n"
            "    def step(state, batch):\n"
            "        g = batch['x'].astype(jnp.float32)\n"
            "        z = jnp.zeros((4,), jnp.float32)\n"
            "        return g, z\n"
            "    return step\n"
        )
        findings = lint.lint_source(src, "train/steps.py")
        assert [f.rule for f in findings] == ["dtype-policy", "dtype-policy"]

    def test_string_f32_spellings_flagged(self):
        src = (
            "def make_step(model):\n"
            "    def step(x):\n"
            "        a = x.astype('float32')\n"
            "        import jax.numpy as jnp\n"
            "        b = jnp.zeros((2,), dtype='float32')\n"
            "        return a, b\n"
            "    return step\n"
        )
        findings = lint.lint_source(src, "train/steps.py")
        assert [f.rule for f in findings] == ["dtype-policy", "dtype-policy"]

    def test_named_contract_constant_is_the_sanctioned_spelling(self):
        src = (
            "from distributedpytorch_tpu.ops.precision import WGRAD_DTYPE\n"
            "import jax.numpy as jnp\n"
            "def make_step(model):\n"
            "    def step(x):\n"
            "        return jnp.zeros((4,), WGRAD_DTYPE)\n"
            "    return step\n"
        )
        assert lint.lint_source(src, "train/steps.py") == []

    def test_host_code_not_flagged(self):
        src = (
            "import jax.numpy as jnp\n"
            "def host_prep(x):\n"
            "    return x.astype(jnp.float32)\n"
        )
        assert lint.lint_source(src, "train/loop.py") == []

    def test_sanctioned_loss_modules_exempt(self):
        src = (
            "import jax.numpy as jnp\n"
            "def make_stats(model):\n"
            "    def stats(x):\n"
            "        return x.astype(jnp.float32).sum()\n"
            "    return stats\n"
        )
        for mod in ("ops/losses.py", "ops/precision.py", "ops/quant.py"):
            assert lint.lint_source(src, mod) == [], mod

    def test_kernel_modules_no_longer_blanket_exempt(self):
        """ISSUE 11: the Pallas kernel modules comply with the named
        constants, so the blanket ops/ exemption is dropped — a bare f32
        regression there is drift again."""
        src = (
            "import jax.numpy as jnp\n"
            "def make_stats(model):\n"
            "    def stats(x):\n"
            "        return x.astype(jnp.float32).sum()\n"
            "    return stats\n"
        )
        for mod in ("ops/pallas_kernels.py", "ops/wgrad_pallas.py",
                    "ops/fused_loss.py", "ops/kernels.py"):
            findings = lint.lint_source(src, mod)
            assert [f.rule for f in findings] == ["dtype-policy"], mod

    def test_pallas_kernel_body_is_a_traced_scope(self):
        """The rule reaches kernel bodies: a function handed to
        ``pallas_call`` is traced, so its bare f32 accumulator flags."""
        src = (
            "import jax\n"
            "import jax.numpy as jnp\n"
            "from jax.experimental import pallas as pl\n"
            "def _kernel(x_ref, o_ref):\n"
            "    o_ref[0, 0] += jnp.sum(x_ref[:].astype(jnp.float32))\n"
            "def run(x):\n"
            "    return pl.pallas_call(\n"
            "        _kernel,\n"
            "        out_shape=jax.ShapeDtypeStruct((1, 1), x.dtype),\n"
            "    )(x)\n"
        )
        findings = lint.lint_source(src, "ops/my_kernel.py")
        assert [f.rule for f in findings] == ["dtype-policy"]

    def test_defvjp_bodies_are_traced_scopes(self):
        """...and so are hand-written custom-VJP forward/backward
        bodies registered through ``defvjp``."""
        src = (
            "import jax\n"
            "import jax.numpy as jnp\n"
            "@jax.custom_vjp\n"
            "def op(x):\n"
            "    return x\n"
            "def _fwd(x):\n"
            "    return x, x\n"
            "def _bwd(res, g):\n"
            "    return (g.astype(jnp.float32),)\n"
            "op.defvjp(_fwd, _bwd)\n"
        )
        findings = lint.lint_source(src, "ops/my_kernel.py")
        assert [f.rule for f in findings] == ["dtype-policy"]

    def test_kernel_body_spelling_the_contract_constant_is_clean(self):
        src = (
            "import jax\n"
            "import jax.numpy as jnp\n"
            "from jax.experimental import pallas as pl\n"
            "from distributedpytorch_tpu.ops.precision import WGRAD_DTYPE\n"
            "def _kernel(x_ref, o_ref):\n"
            "    o_ref[0, 0] += jnp.sum(x_ref[:].astype(WGRAD_DTYPE))\n"
            "def run(x):\n"
            "    return pl.pallas_call(\n"
            "        _kernel,\n"
            "        out_shape=jax.ShapeDtypeStruct((1, 1), x.dtype),\n"
            "    )(x)\n"
        )
        assert lint.lint_source(src, "ops/my_kernel.py") == []

    def test_shipped_kernel_modules_lint_clean(self):
        """The real kernel modules under the extended rule: their
        accumulators spell LOSS/WGRAD/NORM_DTYPE, so dropping the
        exemption flags nothing."""
        import pathlib

        root = pathlib.Path(lint.__file__).resolve().parents[1]
        for mod in ("ops/pallas_kernels.py", "ops/wgrad_pallas.py",
                    "ops/fused_loss.py", "ops/kernels.py"):
            path = root / mod
            findings = lint.lint_source(path.read_text(), mod)
            assert findings == [], (mod, findings)

    def test_inline_suppression(self):
        src = (
            "import jax.numpy as jnp\n"
            "def make_step(model):\n"
            "    def step(x):\n"
            "        return x.astype(jnp.float32)  "
            "# dptlint: disable=dtype-policy — measured exact seam\n"
            "    return step\n"
        )
        assert lint.lint_source(src, "train/steps.py") == []


class TestCkptDtypeDriftRule:
    """Restores must route through the precision restore seams
    (ensure_restored_dtypes / convert_checkpoint_state) — a drifted-dtype
    restore otherwise silently retraces the donated-buffer step."""

    def test_naked_restore_flagged(self):
        src = (
            "def restore(path, template):\n"
            "    out = load_checkpoint(path, template)\n"
            "    return out['params']\n"
        )
        findings = lint.lint_source(src, "train/loop.py")
        assert [f.rule for f in findings] == ["ckpt-dtype-drift"]

    def test_naked_load_weights_flagged(self):
        src = (
            "def restore(path, template):\n"
            "    return load_weights(path, template)\n"
        )
        findings = lint.lint_source(src, "serve/infer.py")
        assert [f.rule for f in findings] == ["ckpt-dtype-drift"]

    def test_seam_in_enclosing_function_sanctions(self):
        for seam in ("ensure_restored_dtypes", "convert_checkpoint_state"):
            src = (
                "def restore(path, template, policy):\n"
                "    out = load_checkpoint(path, template)\n"
                f"    return {seam}(out, policy, 'restore')\n"
            )
            assert lint.lint_source(src, "train/loop.py") == [], seam

    def test_checkpoint_module_itself_exempt(self):
        src = (
            "def load_weights(path, template):\n"
            "    return load_checkpoint(path, template, None)['params']\n"
        )
        assert lint.lint_source(src, "checkpoint.py") == []

    def test_shipped_restore_paths_are_clean(self):
        # the trainer's _restore and the serve loader both carry the seam
        import distributedpytorch_tpu.serve.infer as infer_mod
        import distributedpytorch_tpu.train.loop as loop_mod

        for mod in (loop_mod, infer_mod):
            findings = [
                f for f in lint.lint_file(mod.__file__)
                if f.rule == "ckpt-dtype-drift"
            ]
            assert findings == [], (mod.__name__, findings)
