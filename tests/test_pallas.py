"""Pallas fused loss-stats kernel vs the XLA reference implementation
(ops/pallas_kernels.py vs ops/losses.py) — interpret mode on the CPU mesh;
the same test runs in real mode when a TPU is attached."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributedpytorch_tpu.ops.losses import bce_dice_loss, bce_dice_stats
from distributedpytorch_tpu.ops.pallas_kernels import (
    bce_dice_loss_pallas,
    bce_dice_stats_pallas,
)

def _case(shape, seed=0, hard=False):
    rng = np.random.default_rng(seed)
    p = rng.random(shape, dtype=np.float32)
    if hard:  # exact 0/1 probabilities exercise the torch log clamp
        p = np.where(p < 0.25, 0.0, np.where(p > 0.75, 1.0, p)).astype(np.float32)
    t = (rng.random(shape) > 0.5).astype(np.float32)
    return jnp.asarray(p), jnp.asarray(t)


@pytest.mark.parametrize(
    "shape",
    [
        (4, 64, 96, 1),  # 24,576 elements: one partial (512,128) tile
        (2, 33, 47, 1),  # ragged: exercises the zero-contribution padding
        (1, 1, 5, 1),  # tiny: single partial tile
        (4, 320, 240, 1),  # 307,200 elements = 5 grid blocks: exercises the
        # cross-block SMEM accumulation (init at program 0, += thereafter)
    ],
)
def test_stats_match_xla(shape):
    p, t = _case(shape)
    ref = np.asarray(bce_dice_stats(p, t))
    got = np.asarray(bce_dice_stats_pallas(p, t))
    # relative tolerance: multi-block sums accumulate in different orders
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-3)


def test_loss_matches_including_log_clamp():
    p, t = _case((4, 64, 96, 1), seed=1, hard=True)
    ref = float(bce_dice_loss(p, t))
    got = float(bce_dice_loss_pallas(p, t))
    np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-5)


def test_eval_metrics_parity():
    """The fused eval path ({'loss','dice'} from one kernel pass) matches
    losses.py bce_dice_loss + dice_coefficient."""
    from distributedpytorch_tpu.ops.losses import dice_coefficient
    from distributedpytorch_tpu.ops.pallas_kernels import eval_metrics_pallas

    p, t = _case((4, 320, 240, 1), seed=3)  # 5 grid blocks
    got = eval_metrics_pallas(p, t)
    np.testing.assert_allclose(
        float(got["loss"]), float(bce_dice_loss(p, t)), rtol=1e-5
    )
    np.testing.assert_allclose(
        float(got["dice"]), float(dice_coefficient(p, t)), rtol=1e-5
    )


def test_binarization_parity():
    """Targets with values outside {0,1} binarize via == 1 (reference
    utils.py:16), in kernel and reference alike."""
    rng = np.random.default_rng(2)
    p = jnp.asarray(rng.random((2, 16, 128, 1), dtype=np.float32))
    t = jnp.asarray(rng.integers(0, 4, (2, 16, 128, 1)).astype(np.float32))
    ref = np.asarray(bce_dice_stats(p, t))
    got = np.asarray(bce_dice_stats_pallas(p, t))
    np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-4)


class TestFusedTrainingLoss:
    """The custom-VJP fused loss (ops/fused_loss.py) on the TRAINING path:
    value ≈ XLA loss (summation-order tolerance), gradient == jax.grad of
    the XLA loss to float tolerance — including the saturated-pixel zero-
    gradient contract from the round-3 NaN fix."""

    def _pair(self, shape=(2, 32, 128, 1), seed=0):
        rng = np.random.default_rng(seed)
        o = rng.random(shape, dtype=np.float32)
        t = (rng.random(shape) > 0.5).astype(np.float32)
        return jnp.asarray(o), jnp.asarray(t)

    def test_value_and_grad_match_xla(self):
        from distributedpytorch_tpu.ops.fused_loss import fused_bce_dice_loss

        o, t = self._pair()
        ref_loss, ref_grad = jax.value_and_grad(bce_dice_loss)(o, t)
        got_loss, got_grad = jax.jit(jax.value_and_grad(fused_bce_dice_loss))(o, t)
        np.testing.assert_allclose(float(got_loss), float(ref_loss), rtol=2e-5)
        np.testing.assert_allclose(
            np.asarray(got_grad), np.asarray(ref_grad), rtol=1e-5, atol=1e-7
        )

    def test_saturated_pixels_zero_grad(self):
        """o ∈ {0, 1} pixels: finite loss, exactly zero gradient there —
        maximum(log(x), -100) alone would NaN the whole batch."""
        from distributedpytorch_tpu.ops.fused_loss import fused_bce_dice_loss

        o, t = self._pair()
        o = o.at[0, 0, :4, 0].set(0.0).at[0, 1, :4, 0].set(1.0)
        ref_loss, ref_grad = jax.value_and_grad(bce_dice_loss)(o, t)
        got_loss, got_grad = jax.jit(jax.value_and_grad(fused_bce_dice_loss))(o, t)
        assert np.isfinite(float(got_loss))
        np.testing.assert_allclose(float(got_loss), float(ref_loss), rtol=2e-5)
        assert not np.any(np.isnan(np.asarray(got_grad)))
        np.testing.assert_allclose(
            np.asarray(got_grad), np.asarray(ref_grad), rtol=1e-5, atol=1e-7
        )

    def test_empty_intersection_grad(self):
        """t all zero → dice = 0 → clamped log: dice contributes zero
        gradient, BCE part still flows."""
        from distributedpytorch_tpu.ops.fused_loss import fused_bce_dice_loss

        o, _ = self._pair()
        t = jnp.zeros_like(o)
        ref_loss, ref_grad = jax.value_and_grad(bce_dice_loss)(o, t)
        got_loss, got_grad = jax.jit(jax.value_and_grad(fused_bce_dice_loss))(o, t)
        np.testing.assert_allclose(float(got_loss), float(ref_loss), rtol=2e-5)
        np.testing.assert_allclose(
            np.asarray(got_grad), np.asarray(ref_grad), rtol=1e-5, atol=1e-7
        )

    def test_sharded_fused_loss_matches(self):
        """The shard_map wrapper over an 8-device data mesh: same value and
        gradient as the unsharded XLA loss."""
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from distributedpytorch_tpu.ops.fused_loss import (
            make_sharded_fused_loss,
            spec_axes,
        )

        o, t = self._pair(shape=(8, 16, 128, 1))
        mesh = Mesh(np.array(jax.devices()), ("data",))
        spec = P("data")
        loss = make_sharded_fused_loss(mesh, spec, spec_axes(spec))
        sharding = NamedSharding(mesh, spec)
        o_s = jax.device_put(o, sharding)
        t_s = jax.device_put(t, sharding)
        ref_loss, ref_grad = jax.value_and_grad(bce_dice_loss)(o, t)
        got_loss, got_grad = jax.jit(jax.value_and_grad(loss))(o_s, t_s)
        np.testing.assert_allclose(float(got_loss), float(ref_loss), rtol=2e-5)
        np.testing.assert_allclose(
            np.asarray(got_grad), np.asarray(ref_grad), rtol=1e-5, atol=1e-7
        )
