"""The fully-overlapped step pipeline (ISSUE 1 tentpole): exact equivalence
with the synchronous loop, epoch-persistent sample cache, placement overlap
via the step-timeline tracer, and non-blocking checkpoints.

Everything runs on the CPU backend: the pipeline only moves WHERE work
happens (worker threads, background writer, deferred drains) — never WHAT
is computed — so the per-step loss sequence must be bit-identical to the
inline baseline, and that is the core assertion here.
"""

import json
import os
import time

import numpy as np
import pandas as pd
import pytest

from distributedpytorch_tpu.config import TrainConfig
from distributedpytorch_tpu.data import SampleCache, SyntheticSegmentationDataset
from distributedpytorch_tpu.data.loader import DataLoader
from distributedpytorch_tpu.train import Trainer
from distributedpytorch_tpu.utils.prefetch import (
    pipelined_placement,
    stacked_work,
)
from distributedpytorch_tpu.utils.trace import (
    StepTimeline,
    load_events,
    summarize_timeline,
)

H, W = 32, 48
WIDTHS = (8, 16)


def _config(tmp_path, **kw):
    defaults = dict(
        train_method="singleGPU",
        epochs=2,
        batch_size=8,
        learning_rate=3e-4,
        val_percent=25.0,
        seed=42,
        compute_dtype="float32",
        image_size=(W, H),
        model_widths=WIDTHS,
        synthetic_samples=32,
        checkpoint_dir=str(tmp_path / "checkpoints"),
        log_dir=str(tmp_path / "logs"),
        loss_dir=str(tmp_path / "loss"),
        metric_every_steps=2,
        num_workers=0,
    )
    defaults.update(kw)
    return TrainConfig(**defaults)


class CountingDataset(SyntheticSegmentationDataset):
    """Synthetic dataset that counts decode (__getitem__) calls."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.decodes = 0

    def __getitem__(self, idx):
        self.decodes += 1
        return super().__getitem__(idx)


# ---------------------------------------------------------------------------
# Equivalence: async pipeline == synchronous baseline, bit for bit
# ---------------------------------------------------------------------------


def _train_and_read(tmp_path, tag, **kw):
    import jax

    cfg = _config(tmp_path / tag, **kw)
    Trainer(cfg).train()
    df = pd.read_pickle(tmp_path / tag / "loss" / "singleGPU" / "train_loss.pkl")
    t = Trainer(_config(tmp_path / tag, checkpoint_name="singleGPU", **kw))
    params = [np.asarray(p) for p in jax.tree.leaves(jax.device_get(t.state.params))]
    return df["Loss"].to_numpy(), params


def test_async_pipeline_matches_synchronous_exactly(tmp_path):
    """prefetch depth 2 + host cache + deferred metric drains vs the fully
    inline depth-0/uncached loop: SAME seed must give the IDENTICAL float
    sequence (not allclose — the pipeline must not change the computation)
    and identical final params."""
    sync_losses, sync_params = _train_and_read(
        tmp_path, "sync", prefetch_batches=0, host_cache_mb=0
    )
    async_losses, async_params = _train_and_read(
        tmp_path, "async", prefetch_batches=2, host_cache_mb=64
    )
    np.testing.assert_array_equal(sync_losses, async_losses)
    for p_sync, p_async in zip(sync_params, async_params):
        np.testing.assert_array_equal(p_sync, p_async)


def test_async_pipeline_matches_synchronous_stacked(tmp_path):
    """Same equivalence with K=2 fused dispatches: the K-stack np.stack +
    placement now run on the worker thread, and must still reproduce the
    inline stacked loop exactly (including the ragged-tail fallback:
    batch 5 over 24 train samples)."""
    kw = dict(
        steps_per_dispatch=2, batch_size=5, epochs=1, model_widths=(8,),
        image_size=(16, 16),
    )
    sync_losses, sync_params = _train_and_read(
        tmp_path, "sync", prefetch_batches=0, host_cache_mb=0, **kw
    )
    async_losses, async_params = _train_and_read(
        tmp_path, "async", prefetch_batches=2, host_cache_mb=64, **kw
    )
    np.testing.assert_array_equal(sync_losses, async_losses)
    for p_sync, p_async in zip(sync_params, async_params):
        np.testing.assert_array_equal(p_sync, p_async)


# ---------------------------------------------------------------------------
# Epoch-persistent sample cache
# ---------------------------------------------------------------------------


class TestSampleCache:
    def test_epoch_two_serves_from_cache(self):
        """Epoch 2 must not decode at all when the budget holds the set."""
        ds = CountingDataset(length=12, newsize=(16, 16), seed=0)
        cache = SampleCache(budget_bytes=64 * 2**20)
        loader = DataLoader(ds, batch_size=4, shuffle=True, cache=cache)
        list(loader.epoch_batches(0))
        assert ds.decodes == 12
        list(loader.epoch_batches(1))  # reshuffled order, same sample set
        assert ds.decodes == 12, "epoch 2 decoded despite a warm cache"
        assert cache.hits == 12 and cache.misses == 12

    def test_budget_is_respected_and_degrades_gracefully(self):
        """A budget smaller than the set caches only what fits — correct
        batches either way, bounded memory, partial decode on epoch 2."""
        ds = CountingDataset(length=8, newsize=(16, 16), seed=0)
        item_bytes = SampleCache._nbytes(ds[0])
        ds.decodes = 0
        cache = SampleCache(budget_bytes=3 * item_bytes)
        loader = DataLoader(ds, batch_size=4, cache=cache)
        b0 = list(loader.epoch_batches(0))
        assert cache.used_bytes <= cache.budget_bytes
        assert len(cache) == 3
        assert ds.decodes == 8
        # cached items must OWN their data: a row view would pin the whole
        # decoded parent batch, blowing the budget by the back door
        for it in cache._items.values():
            assert it["image"].base is None and it["mask"].base is None
        b1 = list(loader.epoch_batches(0))
        assert ds.decodes == 8 + 5  # only the 5 uncached re-decode
        for a, b in zip(b0, b1):
            np.testing.assert_array_equal(a["image"], b["image"])
            np.testing.assert_array_equal(a["mask"], b["mask"])

    def test_trainer_epochs_decode_once(self, tmp_path):
        """End to end: a 2-epoch Trainer run decodes each sample exactly
        once (train + val share the cache; epoch-2 train AND per-epoch
        val re-reads all hit)."""
        ds = CountingDataset(length=32, newsize=(W, H), seed=42)
        cfg = _config(tmp_path, host_cache_mb=256)
        Trainer(cfg, dataset=ds).train()
        assert ds.decodes == 32


# ---------------------------------------------------------------------------
# Overlap, demonstrated through the step-timeline tracer
# ---------------------------------------------------------------------------


def test_placement_overlaps_consumption(tmp_path):
    """Placement of batch N+1 begins BEFORE batch N's results are consumed:
    the scheduler test pins this deterministically — a depth-2 pipeline
    over a deliberately slow consumer must show the h2d span of item 1
    opening inside the consumer's dispatch span of item 0."""
    tracer = StepTimeline(str(tmp_path / "timeline.jsonl"))
    batches = [{"image": np.zeros((4, 8, 8, 3), np.float32)} for _ in range(6)]

    def place(kind, payload):
        time.sleep(0.01)  # a nonzero transfer, so spans have width
        return payload

    pipe = pipelined_placement(
        stacked_work(iter(batches), 1, 4), place, depth=2, tracer=tracer
    )
    for i, ((kind, payload), placed) in enumerate(pipe):
        with tracer.span("dispatch", step=i):
            time.sleep(0.05)  # the "executing scan" the H2D should hide under
    tracer.flush()

    events = load_events(str(tmp_path / "timeline.jsonl"))
    h2d = {e["seq"]: e for e in events if e["phase"] == "h2d"}
    dispatch = {e["step"]: e for e in events if e["phase"] == "dispatch"}
    assert len(h2d) == 6 and len(dispatch) == 6
    overlapped = [
        n for n in range(5) if h2d[n + 1]["t0"] < dispatch[n]["t1"]
    ]
    assert overlapped, (
        "no h2d(N+1) span opened before dispatch(N) closed — placement is "
        "not running ahead of consumption"
    )
    # and in steady state it should overlap nearly every step
    assert len(overlapped) >= 3, overlapped


def test_depth_zero_is_inline(tmp_path):
    """The synchronous baseline: depth 0 must place on the consumer thread,
    strictly between consumptions (no overlap), preserving the closing()
    contract."""
    import contextlib
    import threading

    placed_on = []

    def place(kind, payload):
        placed_on.append(threading.current_thread().name)
        return payload

    batches = [{"image": np.zeros((2, 4, 4, 3), np.float32)} for _ in range(3)]
    pipe = pipelined_placement(stacked_work(iter(batches), 1, 2), place, depth=0)
    with contextlib.closing(pipe):
        out = list(pipe)
    assert len(out) == 3
    assert set(placed_on) == {threading.current_thread().name}


def test_trainer_writes_timeline_jsonl(tmp_path):
    """--trace-timeline end to end: the JSONL lands, carries every pipeline
    phase, and summarize_timeline (what bench.py emits) reads it back."""
    path = tmp_path / "timeline.jsonl"
    cfg = _config(tmp_path, timeline_path=str(path), prefetch_batches=2)
    Trainer(cfg).train()
    assert path.exists()
    phases = {e["phase"] for e in map(json.loads, open(path)) if e}
    assert {"decode", "h2d", "dispatch", "readback"} <= phases
    summary = summarize_timeline(str(path))
    for phase in ("decode", "h2d", "dispatch", "readback"):
        assert summary[phase]["count"] > 0
        assert summary[phase]["total_ms"] >= 0.0
    # 2 epochs x 3 steps: every step dispatched under a span
    assert summary["dispatch"]["count"] == 6


# ---------------------------------------------------------------------------
# Non-blocking checkpoints
# ---------------------------------------------------------------------------


class TestAsyncCheckpoint:
    def test_async_save_roundtrip(self, tmp_path):
        from distributedpytorch_tpu.checkpoint import (
            load_checkpoint,
            save_checkpoint_async,
        )

        params = {"w": np.arange(6, dtype=np.float32).reshape(2, 3)}
        path = str(tmp_path / "a.ckpt")
        fut = save_checkpoint_async(path, params, step=7, epoch=3)
        assert fut.result(timeout=30) == path
        restored = load_checkpoint(path, params)
        np.testing.assert_array_equal(restored["params"]["w"], params["w"])
        assert restored["step"] == 7 and restored["epoch"] == 3

    def test_queued_saves_apply_in_order(self, tmp_path):
        """Two async saves of the SAME path: the file must end at the
        newest snapshot (one writer thread, submission order)."""
        from distributedpytorch_tpu.checkpoint import (
            load_checkpoint,
            save_checkpoint_async,
        )

        path = str(tmp_path / "b.ckpt")
        params = {"w": np.zeros((4,), np.float32)}
        f1 = save_checkpoint_async(path, params, epoch=1)
        f2 = save_checkpoint_async(
            path, {"w": np.ones((4,), np.float32)}, epoch=2
        )
        f1.result(timeout=30)
        f2.result(timeout=30)
        restored = load_checkpoint(path, params)
        assert restored["epoch"] == 2
        np.testing.assert_array_equal(restored["params"]["w"], np.ones((4,)))

    def test_mid_run_save_is_durable_after_fit(self, tmp_path):
        """A save issued mid-epoch (signal stop) must be complete and
        loadable by the time train() returns — the drain in train()'s
        finally is what guarantees a restart never reads a torn file."""
        import signal

        from distributedpytorch_tpu.checkpoint import load_checkpoint

        cfg = _config(tmp_path, epochs=50)
        trainer = Trainer(cfg)
        assert cfg.async_checkpoint  # the default under test
        orig = trainer._record
        fired = {}

        def record_then_signal(*a, **kw):
            orig(*a, **kw)
            if not fired:
                fired["x"] = True
                signal.raise_signal(signal.SIGTERM)

        trainer._record = record_then_signal
        trainer.train()
        assert not trainer._ckpt_futures  # drained, not abandoned
        path = tmp_path / "checkpoints" / "singleGPU.ckpt"
        assert path.exists()
        restored = load_checkpoint(
            str(path), trainer.state.params, trainer.state.opt_state
        )
        assert restored["epoch"] == 0  # interrupted epoch will be redone
        resumed = Trainer(_config(tmp_path, epochs=50, checkpoint_name="singleGPU"))
        assert resumed.start_epoch == 0

    def test_write_failure_surfaces(self, tmp_path, monkeypatch):
        """A failed background write must raise out of train(), not pass
        silently (the save "succeeded" from the step loop's view)."""
        import distributedpytorch_tpu.checkpoint as ckpt_mod
        import distributedpytorch_tpu.train.loop as loop_mod

        def bad_write(path, payload, keep=1):
            raise OSError("disk full")

        monkeypatch.setattr(ckpt_mod, "_write_payload", bad_write)
        # loop.py binds save_checkpoint_async at import; the patched
        # _write_payload is read through the module at call time, so the
        # async path picks it up unmodified
        cfg = _config(tmp_path, epochs=1)
        with pytest.raises(OSError, match="disk full"):
            loop_mod.Trainer(cfg).train()

    def test_last_save_failure_surfaces_at_final_drain(self, tmp_path,
                                                       monkeypatch):
        """A write failure on the FINAL save has no 'next save' to surface
        it — the drain in train()'s finally is the only boundary left and
        must raise it as a hard error (earlier saves all succeed, so this
        pins the final-drain path specifically, not the surface-at-next-
        save path)."""
        import distributedpytorch_tpu.checkpoint as ckpt_mod

        real_write = ckpt_mod._write_payload
        calls = {"n": 0}

        def fail_final_only(path, payload, keep=1):
            calls["n"] += 1
            if payload["epoch"] >= 2:  # only the end-of-run save fails
                raise OSError("disk full on the final save")
            return real_write(path, payload, keep=keep)

        monkeypatch.setattr(ckpt_mod, "_write_payload", fail_final_only)
        cfg = _config(tmp_path, epochs=2, checkpoint_every_epochs=0)
        with pytest.raises(OSError, match="final save"):
            Trainer(cfg).train()
        assert calls["n"] >= 1

    def test_sync_mode_still_works(self, tmp_path):
        from distributedpytorch_tpu.checkpoint import load_checkpoint

        cfg = _config(tmp_path, epochs=1, async_checkpoint=False)
        trainer = Trainer(cfg)
        trainer.train()
        restored = load_checkpoint(
            str(tmp_path / "checkpoints" / "singleGPU.ckpt"),
            trainer.state.params,
        )
        assert restored["epoch"] == 1
