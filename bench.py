#!/usr/bin/env python3
"""Benchmark harness: UNet training throughput on the available hardware.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "imgs/sec", "vs_baseline": N, ...}

Measured config = the reference's measured config (reference train.py:18-24:
batch 4, 3×640×960, Adam 1e-4, BCE−log-dice), single chip, bf16 compute.

Honest accounting (VERDICT.md round 2 item 3):
  * FLOPs come from XLA's own cost analysis of the compiled train step,
    with an analytic fallback (~0.257 TFLOP forward/img, ~3× that for the
    full step at 640×960 — per-conv 2·K²·Cin·Cout·H·W summed over the
    UNet; the round-1 "7.3 TFLOP/img" figure was ~10× wrong).
  * `mfu` is measured FLOP/s over the detected chip's bf16 peak.
  * Timing excludes compile: warmup steps run (and are synced) first.
  * Any failure still emits a parseable JSON line with an "error" field.

``vs_baseline``: the reference publishes no throughput numbers (SURVEY.md
§6); BASELINE.md's operational target is its 2×GPU DDP config. Until a
measured GPU number exists we normalize against an estimated 2×RTX-3090-class
DDP throughput for this exact model/shape, carried as a BOUNDED RANGE
(VERDICT r04 next-4), derivation:

  * Work: ~0.77 TFLOP logical per image per train step (same analytic conv
    sum as the TPU side, ANALYTIC_STEP_FLOPS_PER_IMG).
  * Peak: RTX 3090 / GA102 = 35.6 TFLOP/s fp32 FFMA; the TF32 tensor-core
    dense rate on GeForce Ampere is the same 35.6 TFLOP/s (NVIDIA
    "GA102 whitepaper", shading/tensor performance tables). The reference
    trains fp32 with no AMP (reference train.py has no autocast), but
    PyTorch runs cuDNN convs in TF32 by default on Ampere
    (torch.backends.cudnn.allow_tf32=True — PyTorch docs, "CUDA semantics:
    TensorFloat-32"), so both paths share the same peak and differ in
    achievable utilization.
  * Utilization bracket for large-image UNet convs: ~20% of peak on the
    fp32 FFMA path (consistent with classic public fp32 ResNet-50 numbers,
    e.g. ~360 imgs/s on V100 ≈ 18% of its 15.7 TFLOP/s peak) up to ~55%
    for well-tiled TF32 tensor-core convs (cuDNN benchmark-mode heuristics,
    reference train_utils sets torch.backends.cudnn.benchmark).
  * Per GPU: 0.20·35.6/0.77 ≈ 9 imgs/s … 0.55·35.6/0.77 ≈ 25 imgs/s;
    ×2 GPUs at 0.90-0.97 DDP scaling → PAIR RANGE ≈ 17-49 imgs/s.
    Central point stays 28 (the round-1..4 estimate, mid-range).

Explicit and revisable, recorded here so the denominator is never
fabricated; carried in-band as ``baseline_source: "estimate"`` with
``baseline_range`` and worst/best-case ``vs_baseline_vs_high`` /
``vs_baseline_vs_low`` alongside the central ``vs_baseline``.

Exit codes: 0 = measured number; 2 = preflight never reached a live
runtime (JSON carries the staged probe history — and when a same-session
watcher-fired measurement exists, it is PROMOTED to the top-level
metric/value with ``provenance: "watcher_session"`` so the channel never
reports 0.0 for a round that actually measured); 3 = watchdog fired
mid-run. The JSON line is emitted in every case.
"""

import json
import os
import subprocess
import sys
import time

# Estimated reference DDP (2 GPU) throughput for batch 4 @ 3x640x960 —
# derivation in the module docstring; revise when a measured number lands.
# ``baseline_source: "estimate"`` rides in the JSON so consumers see the
# caveat in-band, not only here (VERDICT r03 weak-9). The range bounds the
# utilization bracket (fp32-FFMA floor … TF32-tensor-core ceiling);
# the central point is the original mid-range estimate (VERDICT r04 next-4).
BASELINE_IMGS_PER_SEC = 28.0
BASELINE_RANGE = (17.0, 49.0)
BASELINE_SOURCE = "estimate"


def _baseline_fields(imgs_per_sec: float) -> dict:
    """The denominator block every bench JSON carries in-band: central
    normalization plus worst/best-case against the bounded range."""
    return {
        "vs_baseline": round(imgs_per_sec / BASELINE_IMGS_PER_SEC, 3),
        "baseline_imgs_per_sec": BASELINE_IMGS_PER_SEC,
        "baseline_range_imgs_per_sec": list(BASELINE_RANGE),
        "vs_baseline_vs_high": round(imgs_per_sec / BASELINE_RANGE[1], 3),
        "vs_baseline_vs_low": round(imgs_per_sec / BASELINE_RANGE[0], 3),
        "baseline_source": BASELINE_SOURCE,
    }

# Wall-clock origin for the compile-budget check in run() — module import
# happens within the first second of the process either way.
_START = time.monotonic()

BATCH = int(os.environ.get("BENCH_BATCH", 4))
H = int(os.environ.get("BENCH_H", 640))
W = int(os.environ.get("BENCH_W", 960))
# Validated at module load so a typo'd arch fails loudly instead of
# benching the unet under a mislabeled metric name; ARCH also names the
# error/timeout/preflight metric series so a milesial run's failure is
# never misfiled into the unet series.
ARCH = os.environ.get("BENCH_ARCH", "unet")
if ARCH not in ("unet", "milesial"):
    raise SystemExit(f"BENCH_ARCH={ARCH!r}: expected 'unet' or 'milesial'")
WARMUP_STEPS = 3
MEASURE_STEPS = int(os.environ.get("BENCH_STEPS", 20))
# Steps fused per dispatch for the headline number (the trainer's
# --steps-per-dispatch path): on a remote/tunneled PJRT runtime per-dispatch
# latency (~50 ms measured here) otherwise dominates the ~chip-time step.
# Overridable for quick CPU smoke runs (the K-step scan dominates compile).
FUSED_STEPS = int(os.environ.get("BENCH_FUSED_STEPS", 10))

# Analytic per-image LOGICAL (pixel-domain) FLOPs at 640×960: forward = sum
# of 2·K²·Cin·Cout·Hout·Wout over every conv/deconv in the 4-level UNet
# ≈ 0.257 TFLOP; backward ≈ 2× forward. Scales linearly in H·W (every conv's
# spatial extent does), which run() uses for non-default BENCH_H/BENCH_W.
ANALYTIC_FWD_FLOPS_PER_IMG = 0.257e12
ANALYTIC_STEP_FLOPS_PER_IMG = 3.0 * ANALYTIC_FWD_FLOPS_PER_IMG

# bf16 peak FLOP/s by TPU generation (device_kind substring, lowercase).
PEAK_BF16_FLOPS = [
    ("v6", 918e12),
    ("v5p", 459e12),
    ("v5 lite", 197e12),
    ("v5e", 197e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
]


def chip_peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    if "tpu" in kind or device.platform == "tpu":
        for key, peak in PEAK_BF16_FLOPS:
            if key in kind:
                return peak
        return 275e12  # unknown TPU: assume v4-class
    return 0.0  # CPU/GPU: no meaningful MFU denominator here


def xla_step_flops(compiled) -> float:
    """Total FLOPs per executed step per XLA's cost analysis (0 if absent)."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        return float(ca.get("flops", 0.0))
    except Exception:
        return 0.0


# ---------------------------------------------------------------------------
# Pre-flight: prove the runtime is alive with a trivial computation BEFORE
# spending minutes compiling (VERDICT r03 next-1a). A wedged/unreachable
# tunneled runtime hangs *inside native code* — `import jax` itself can hang
# dialing the PJRT relay — so the probe must live in a subprocess the parent
# can outwait. Three rounds of empty BENCH artifacts trace to exactly this:
# the expensive path was entered blind and the watchdog fired at 900 s.

_PROBE_SRC = """
import json, sys, time
t0 = time.time()
import jax
import jax.numpy as jnp
dev = jax.devices()[0]
y = float((jnp.ones((8,)) * 2.0).sum())
print(json.dumps({
    "ok": y == 16.0,
    "platform": dev.platform,
    "device_kind": getattr(dev, "device_kind", ""),
    "secs": round(time.time() - t0, 1),
}))
"""


# -- cooperative single-client lock ------------------------------------------
# The tunneled runtime tolerates ONE client at a time; the two foreseeable
# colliders are the standing watcher's probes (tools/tpu_watch.py) and the
# driver's round-end `python bench.py` capture. This advisory lockfile lets
# them take turns: the watcher holds it around each probe, the capture waits
# (bounded) for a probe in flight to finish instead of dialing alongside it.
# Best-effort by design — a SIGKILLed holder leaves a stale file, which the
# next acquirer detects (dead pid) and removes; it is collision AVOIDANCE
# for minutes-long overlaps, not a correctness mutex.
_CLIENT_LOCK_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".tpu_client.lock")
# Longest legitimate hold: bench_multi keeps the lock for its whole
# program (worst case ~2.75 h of per-config watchdog budgets). Beyond
# this age a lock is stale regardless of pid liveness — pid-existence
# alone cannot distinguish a live holder from a recycled pid (reboot,
# wraparound), which would otherwise hold the watcher off forever.
_CLIENT_LOCK_MAX_AGE_S = 4.0 * 3600.0


def _read_lock_raw() -> bytes | None:
    try:
        with open(_CLIENT_LOCK_PATH, "rb") as f:
            return f.read()
    except OSError:
        return None


def _client_lock_holder() -> dict | None:
    """The live holder of the client lock, or None (absent/stale/torn)."""
    raw = _read_lock_raw()
    if raw is None:
        return None
    try:
        d = json.loads(raw)
    except ValueError:
        return None
    if not isinstance(d, dict) or not isinstance(d.get("pid"), int):
        return None
    ts = d.get("ts")
    if not isinstance(ts, (int, float)) \
            or time.time() - ts > _CLIENT_LOCK_MAX_AGE_S:
        return None  # older than any legitimate hold — stale
    try:
        os.kill(d["pid"], 0)
    except ProcessLookupError:
        return None  # holder died without releasing — stale
    except PermissionError:
        pass
    return d


def acquire_client_lock(tag: str, wait_secs: float = 0.0,
                        poll_secs: float = 10.0) -> bool:
    """Try to take the single-client lock, waiting up to wait_secs for a
    live holder to release. Returns False if still held at timeout."""
    deadline = time.monotonic() + wait_secs
    while True:
        try:
            fd = os.open(_CLIENT_LOCK_PATH,
                         os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            stale_raw = _read_lock_raw()
            holder = _client_lock_holder()
            if holder is None:
                # Stale or torn. Remove ONLY if the file still holds the
                # content we judged stale — a rival waiter may have
                # reclaimed and written ITS lock in between, and blindly
                # removing that would let two clients through (the
                # reclaim TOCTOU). After a successful remove, retry the
                # O_EXCL create immediately (a zero-wait caller must
                # still win a reclaim): exactly one racer wins; the
                # loser sees the winner as a live holder next pass.
                if stale_raw is not None \
                        and _read_lock_raw() == stale_raw:
                    try:
                        os.remove(_CLIENT_LOCK_PATH)
                    except OSError:
                        pass
                    else:
                        continue
                elif stale_raw is None \
                        and not os.path.lexists(_CLIENT_LOCK_PATH):
                    continue  # vanished between create and read — retry
                # an unremovable path (directory, permissions) must not
                # spin at 100% CPU forever: honor the same deadline and
                # pacing as the live-holder branch
                if time.monotonic() >= deadline:
                    return False
                time.sleep(min(1.0, poll_secs))
                continue
            if holder.get("pid") == os.getpid():
                return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(min(poll_secs,
                           max(0.1, deadline - time.monotonic())))
            continue
        with os.fdopen(fd, "w") as f:
            json.dump({"pid": os.getpid(), "tag": tag,
                       "ts": time.time()}, f)
        return True


def release_client_lock() -> None:
    holder = _client_lock_holder()
    if holder is not None and holder.get("pid") == os.getpid():
        try:
            os.remove(_CLIENT_LOCK_PATH)
        except OSError:
            pass


def transfer_client_lock(pid: int, tag: str) -> None:
    """Re-point the lock we hold at another live process (the watcher's
    orphaned probe child: the parent's lock must outlive the parent and
    expire with the ORPHAN, or a bench capture would dial alongside
    it). Caller must currently hold the lock."""
    tmp = _CLIENT_LOCK_PATH + f".{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump({"pid": pid, "tag": tag, "ts": time.time()}, f)
    os.replace(tmp, _CLIENT_LOCK_PATH)


def _probe_once(timeout: float) -> dict:
    """One health probe in a fresh subprocess, bounded by `timeout`.

    On timeout the child gets SIGTERM and a 30 s grace — NEVER SIGKILL: a
    hard kill of a process mid-dispatch is precisely what wedges the relay
    for hours (observed round 3). A child that ignores SIGTERM (hung in
    native init, signals pending forever) is left running and reported as
    orphaned rather than killed into a worse state.
    """
    proc = subprocess.Popen(
        [sys.executable, "-u", "-c", _PROBE_SRC],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
    )
    try:
        out, err = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.terminate()
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            return {
                "ok": False,
                "error": f"probe hung {timeout:.0f}s, ignored SIGTERM "
                         f"(left running, pid {proc.pid})",
            }
        return {"ok": False, "error": f"probe timeout after {timeout:.0f}s"}
    line = out.strip().splitlines()[-1] if out and out.strip() else ""
    try:
        return json.loads(line)
    except (ValueError, IndexError):
        # a FAST failure is an environment bug, not a wedged runtime —
        # surface the child's actual traceback so the artifact can tell
        # the two apart
        return {
            "ok": False,
            "error": f"probe rc={proc.returncode}, unparseable output "
                     f"{line[:120]!r}",
            "stderr_tail": (err or "").strip()[-400:],
        }


def _preflight(deadline: float) -> tuple:
    """Staged claim: probe, and on failure retry on a schedule spanning
    MINUTES (a wedged runtime recovers on relay timescales, not a 60 s
    nap — the round-3 single retry could never outlast one). Growing
    per-probe timeouts: short probes killed mid-init can prolong a wedge,
    so later attempts wait longer before giving up. Returns
    ``(ok, history)``; stops when a probe succeeds or `deadline` passes.
    """
    timeouts = (120, 180, 240, 300)
    sleeps = (20, 40, 60, 90)
    history = []
    attempt = 0
    while True:
        remaining = deadline - time.monotonic()
        if remaining < 30:
            return False, history
        result = _probe_once(min(timeouts[min(attempt, len(timeouts) - 1)], remaining))
        history.append(result)
        if result.get("ok"):
            return True, history
        print(f"bench preflight attempt {attempt + 1}: "
              f"{result.get('error', 'failed')}", file=sys.stderr)
        attempt += 1
        nap = min(
            sleeps[min(attempt - 1, len(sleeps) - 1)],
            max(0.0, deadline - time.monotonic() - 30),
        )
        if nap <= 0:
            return False, history
        time.sleep(nap)


def _poll_ledger_summary(
    path: str = "logs/tpu_poll_r05.jsonl",
) -> dict:
    """Compress the standing watcher's poll ledger (tools/tpu_watch.py)
    into a few fields for in-band reporting: how often the runtime was
    probed this session and whether it EVER answered. Malformed lines
    are SKIPPED, not fatal — the watcher appends all session, so a
    concurrent read can catch a partial final line, and one bad line
    must not collapse a session of evidence into 'not tried'."""
    if not os.path.isabs(path):
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)), path)
    records = []
    try:
        with open(path) as f:
            for line in f:
                if not line.strip():
                    continue
                try:
                    records.append(json.loads(line))
                except ValueError:
                    continue
    except OSError:
        return {"available": False, "path": path}
    probes = [r for r in records if r.get("event") == "probe"]
    ok = [r for r in probes if r.get("ok")]
    return {
        "available": True,
        "path": path,
        "probes": len(probes),
        "probes_ok": len(ok),
        "first_ts": probes[0]["ts"] if probes else None,
        "last_ts": probes[-1]["ts"] if probes else None,
        "first_ok_ts": ok[0]["ts"] if ok else None,
    }


def _session_measurement(
    paths: tuple = (".perf_r05/bench_default.json",
                    ".perf_r05/bench_multi.jsonl"),
) -> dict | None:
    """The standing watcher (tools/tpu_watch.py) fires the measurement
    program on the first healthy probe of the session — possibly hours
    before the driver's round-end capture runs. If the runtime is dead
    by capture time, the capture must still carry that session
    measurement in-band: a 0.0-valued error line that HIDES a real
    same-session, same-code, same-chip number would read as 'no number
    this round' (the exact failure mode of rounds 1-4). Returns the
    best successful headline-config result found, stamped with its
    artifact mtime, or None."""
    best = None
    for rel in paths:
        path = rel
        if not os.path.isabs(path):
            path = os.path.join(
                os.path.dirname(os.path.abspath(__file__)), rel)
        try:
            with open(path) as f:
                lines = [ln for ln in f if ln.strip()]
            mtime = os.path.getmtime(path)
        except OSError:
            continue
        for ln in lines:
            # the artifacts are appended concurrently (the watcher's
            # program may be running): a torn line that still parses —
            # or parses to a non-dict, or carries a non-numeric value —
            # must be skipped, never collapse the scan
            try:
                d = json.loads(ln)
            except ValueError:
                continue
            if not isinstance(d, dict):
                continue
            value = d.get("value")
            if d.get("error") or not isinstance(value, (int, float)) \
                    or not value:
                continue
            # only the shipping headline config competes (bench_multi
            # rows carry a "config" tag; the default-config artifact
            # has none)
            if d.get("config") not in (None, "default"):
                continue
            if best is None or value > best["value"]:
                best = {**d, "artifact": rel,
                        "artifact_mtime": int(mtime)}
    return best


def run() -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    # Persistent XLA compile cache (the CLI's helper, same dir): keeps
    # time-to-first-JSON low — the two bench executables reload from disk
    # instead of recompiling ~2-3 minutes over the tunnel.
    from distributedpytorch_tpu.cli import _enable_compilation_cache

    _enable_compilation_cache()

    from distributedpytorch_tpu.models.unet import UNet, init_unet_params
    from distributedpytorch_tpu.train.steps import (
        create_train_state,
        make_multi_train_step,
        make_train_step,
    )

    # A/B levers for on-chip experiments (default = shipping config):
    #   BENCH_WGRAD_TAPS=1    9-tap-matmul conv weight gradients
    #   BENCH_S2D_LEVELS=N    force space-to-depth depth (-1 = auto)
    #   BENCH_ARCH=milesial   the 31M-param public-upstream family
    #   BENCH_PALLAS_LOSS=1   fused one-pass Pallas training loss
    arch = ARCH
    wgrad_taps = os.environ.get("BENCH_WGRAD_TAPS") == "1"
    s2d_levels = int(os.environ.get("BENCH_S2D_LEVELS", "-1"))
    if arch == "milesial":
        from distributedpytorch_tpu.models.milesial import (
            MilesialUNet,
            init_milesial,
        )

        model = MilesialUNet(
            dtype=jnp.bfloat16, s2d_levels=s2d_levels, wgrad_taps=wgrad_taps
        )
        params, model_state = init_milesial(
            model, jax.random.key(0), input_hw=(H, W)
        )
    else:
        model = UNet(
            dtype=jnp.bfloat16, s2d_levels=s2d_levels, wgrad_taps=wgrad_taps
        )
        params = init_unet_params(model, jax.random.key(0), input_hw=(H, W))
        model_state = None
    state, tx = create_train_state(params, 1e-4, model_state=model_state)
    loss_impl = None
    if os.environ.get("BENCH_PALLAS_LOSS") == "1":
        from distributedpytorch_tpu.ops.fused_loss import fused_bce_dice_loss

        loss_impl = fused_bce_dice_loss

    # per-phase host-span tracer (utils/trace.py): the same decode/stack/
    # h2d/dispatch/readback phases the trainer's --trace-timeline records,
    # measured inline here so every bench row carries an attribution
    # breakdown next to its imgs/sec (in-memory; summarized at the end)
    from distributedpytorch_tpu.utils.trace import StepTimeline

    timeline = StepTimeline(enabled=True)

    rng = np.random.default_rng(0)
    dev = jax.devices()[0]
    host_batch = {
        "image": rng.random((BATCH, H, W, 3), dtype=np.float32),
        "mask": (rng.random((BATCH, H, W)) > 0.5).astype(np.int32),
    }
    batch = {k: jax.device_put(v, dev) for k, v in host_batch.items()}
    # the fused executable scans over K stacked (identical) batches — what
    # the trainer dispatches under --steps-per-dispatch K
    stacked = {
        k: jax.device_put(jnp.broadcast_to(v, (FUSED_STEPS,) + v.shape), dev)
        for k, v in batch.items()
    }
    state = jax.device_put(state, dev)

    # AOT-compile once; the same executables are what we time (no hidden
    # recompiles, and cost_analysis reads the very computation measured).
    step_fn = make_train_step(model, tx, batch_size=BATCH, loss_impl=loss_impl)
    t_compile0 = time.monotonic()
    compiled = (
        jax.jit(step_fn, donate_argnums=(0,)).lower(state, batch).compile()
    )
    if os.environ.get("BENCH_COMPILE_ONLY") == "1":
        # Compile-only probe (VERDICT r05 next-8): prove this config's
        # train-step executable lowers + compiles on the live runtime
        # without spending a measurement window — bench_multi records
        # compiled-or-rejected in its ledger (a compile failure raises
        # out of run() and is classified there; a wedge trips the
        # config's own 30 s watchdog).
        return {
            "compile_only": True,
            "compiled": True,
            "compile_s": round(time.monotonic() - t_compile0, 3),
            "platform": jax.default_backend(),
        }
    # The fused K-step executable is the bigger compile; on a slow-but-
    # alive runtime, skip it rather than let the watchdog kill the run
    # with NO number — the single-dispatch figure is a valid (lower-bound)
    # headline (VERDICT r03: three rounds of empty artifacts).
    budget = float(os.environ.get("BENCH_WATCHDOG_SECS", 900))
    if time.monotonic() - _START < 0.5 * budget:
        multi = (
            jax.jit(make_multi_train_step(step_fn), donate_argnums=(0,))
            .lower(state, stacked)
            .compile()
        )
    else:
        print(
            "bench: skipping the fused-dispatch executable "
            f"({time.monotonic() - _START:.0f}s elapsed of {budget:.0f}s "
            "budget) — headline falls back to single-dispatch",
            file=sys.stderr,
        )
        multi = None
    # Executed FLOPs (XLA cost analysis of the compiled step). With the
    # default space-to-depth execution mode this EXCEEDS the model's logical
    # FLOPs — the structured dense kernels multiply by zeros the MXU schedule
    # anyway — so MFU is defined on the logical (pixel-domain) count and the
    # executed count is reported separately as hardware utilization. The
    # logical count comes from ONE source in every mode — the analytic conv
    # sum, which scales linearly with H·W — so MFU ratios between execution
    # modes always track measured imgs/sec ratios.
    # The analytic conv sum is the 7.76M-param UNet's; it must never fill
    # a milesial row (≈4× the params — the FLOP fields would be silently
    # ~4× off under a milesial_... metric name). milesial rows without
    # cost_analysis report their FLOP-derived fields as null instead.
    flops_executed = xla_step_flops(compiled)
    flops_source = "xla_cost_analysis"
    if flops_executed <= 0:
        if arch == "unet":
            flops_executed = (
                ANALYTIC_STEP_FLOPS_PER_IMG * BATCH * (H * W) / (640 * 960)
            )
            flops_source = "analytic"
        else:
            flops_executed = None
            flops_source = "unavailable"
    if arch == "unet":
        flops_logical = ANALYTIC_STEP_FLOPS_PER_IMG * BATCH * (H * W) / (640 * 960)
    else:
        flops_logical = None

    # -- unfused: one dispatch per step --------------------------------------
    for _ in range(WARMUP_STEPS):
        state, loss = compiled(state, batch)
    float(loss)  # device→host transfer: a hard sync even over a PJRT relay
    # (block_until_ready alone does not force execution on tunneled devices)

    # H2D phase: place the full host batch (what one pipeline payload
    # costs), synced so the span covers the transfer, not just the enqueue
    for _ in range(3):
        with timeline.span("h2d"):
            placed = {k: jax.device_put(v, dev) for k, v in host_batch.items()}
            jax.block_until_ready(placed)
    del placed

    t0 = time.perf_counter()
    for _ in range(MEASURE_STEPS):
        # dispatch spans are the host-side enqueue cost; the final
        # readback span absorbs the queued device time — together they
        # bound where a throughput delta lives (host vs chip vs transfer)
        with timeline.span("dispatch"):
            state, loss = compiled(state, batch)
    with timeline.span("readback"):
        float(loss)  # forces the whole dependency chain of donated states
    dt_unfused = time.perf_counter() - t0
    unfused_per_step = dt_unfused / MEASURE_STEPS

    # -- fused: K steps per dispatch (headline) ------------------------------
    # symmetric methodology on a per-STEP basis: one warmup dispatch already
    # runs FUSED_STEPS (=10) warmup steps vs the unfused path's 3, and the
    # measured window is ≥3 dispatches / ≥30 steps vs the unfused 20 — so
    # min() below compares like with like instead of letting one lucky
    # 2-dispatch window pick the headline
    if multi is not None:
        state, losses = multi(state, stacked)
        float(losses[-1])
        reps = max(3, MEASURE_STEPS // FUSED_STEPS)
        t0 = time.perf_counter()
        for _ in range(reps):
            state, losses = multi(state, stacked)
        float(losses[-1])
        dt_fused = time.perf_counter() - t0
        fused_per_step = dt_fused / (reps * FUSED_STEPS)
    else:
        fused_per_step = float("inf")

    per_step = min(fused_per_step, unfused_per_step)
    imgs_per_sec = BATCH / per_step
    peak = chip_peak_flops(dev)
    # per-phase attribution: the inline spans above, plus (when
    # BENCH_TIMELINE_JSONL names a trainer-written --trace-timeline file)
    # the real end-to-end pipeline's phases including decode. The spans
    # are recorded on the SINGLE-DISPATCH loop; when the fused K-step
    # executable wins the headline, `headline_loop` flags that the phase
    # timings come from a different executable (per-dispatch granularity
    # differs), so a reader never attributes a fused-path delta to them.
    timeline_summary = {
        "source": "bench_inline",
        "loop": "single_dispatch",
        "headline_loop": (
            "fused" if per_step == fused_per_step else "single_dispatch"
        ),
        **timeline.summary(),
    }
    trainer_jsonl = os.environ.get("BENCH_TIMELINE_JSONL")
    timeline_trainer = None
    if trainer_jsonl and os.path.exists(trainer_jsonl):
        from distributedpytorch_tpu.utils.trace import summarize_timeline

        timeline_trainer = {
            "source": trainer_jsonl,
            **summarize_timeline(trainer_jsonl),
        }
    return {
        "metric": f"{arch}_train_imgs_per_sec_b{BATCH}_{H}x{W}_{dev.platform}",
        "value": round(imgs_per_sec, 2),
        "unit": "imgs/sec",
        **_baseline_fields(imgs_per_sec),
        "step_time_ms": round(1e3 * per_step, 2),
        "steps_per_dispatch": FUSED_STEPS if per_step == fused_per_step else 1,
        "imgs_per_sec_single_dispatch": round(BATCH / unfused_per_step, 2),
        # logical = pixel-domain model FLOPs (the work a user asked for);
        # executed = what the compiled s2d computation runs (incl. its
        # structural zeros). MFU uses logical; hw_utilization uses executed.
        "flops_per_img": (
            round(flops_logical / BATCH / 1e9, 2)  # GFLOP
            if flops_logical is not None else None
        ),
        "flops_per_img_executed": (
            round(flops_executed / BATCH / 1e9, 2)
            if flops_executed is not None else None
        ),
        "flops_source": flops_source,
        "achieved_tflops": (
            round(flops_executed / per_step / 1e12, 2)
            if flops_executed is not None else None
        ),
        "mfu": (
            round(flops_logical / per_step / peak, 4)
            if peak > 0 and flops_logical is not None else None
        ),
        "hw_utilization": (
            round(flops_executed / per_step / peak, 4)
            if peak > 0 and flops_executed is not None else None
        ),
        "device_kind": getattr(dev, "device_kind", dev.platform),
        "timeline": timeline_summary,
        "timeline_trainer": timeline_trainer,
    }


def _preflight_failure_payload(preflight_error: str, history: list) -> dict:
    """The artifact line for a dead-at-capture runtime.

    If the standing watcher landed a real same-session, same-code,
    same-chip measurement earlier, promote it to the TOP-LEVEL
    metric/value (VERDICT r05 item 2) instead of reporting 0.0 — the
    preflight failure rides alongside, and ``provenance:
    "watcher_session"`` marks the number as the watcher's, not this
    capture's. Otherwise the classic 0.0 error line with the full
    evidence block."""
    session = None
    try:
        session = _session_measurement()
    except Exception:  # noqa: BLE001 — promotion must not be fatal
        pass
    if session is not None:
        return {
            **{k: v for k, v in session.items()
               if k not in ("artifact", "artifact_mtime")},
            **_baseline_fields(float(session["value"])),
            "provenance": "watcher_session",
            "session_artifact": session.get("artifact"),
            "session_artifact_mtime": session.get("artifact_mtime"),
            "preflight_error": preflight_error,
            "preflight_history": history,
            "poll_ledger": _poll_ledger_summary(),
        }
    return {
        "metric": f"{ARCH}_train_imgs_per_sec_b{BATCH}_{H}x{W}_preflight",
        "value": 0.0,
        "unit": "imgs/sec",
        **_baseline_fields(0.0),
        "error": preflight_error,
        "preflight_history": history,
        # the standing watcher's session-long evidence (VERDICT r04
        # next-1: distinguishes "channel dead all round" from "not
        # tried")
        **_failure_evidence(),
    }


def _failure_evidence() -> dict:
    """The two in-band evidence fields every failure JSON carries.
    Guarded: these run inside the watchdog's timer thread and the
    last-resort except block — an exception HERE would kill the very
    code whose job is to guarantee a parseable artifact."""
    try:
        return {
            "poll_ledger": _poll_ledger_summary(),
            "session_measurement": _session_measurement(),
        }
    except Exception as exc:  # noqa: BLE001 — evidence must not be fatal
        return {"evidence_error": f"{type(exc).__name__}: {exc}"}


def _arm_watchdog(seconds: float) -> None:
    """Emit an error JSON and hard-exit if the bench wedges.

    A wedged/unreachable TPU runtime hangs INSIDE native backend-init or
    compile calls — no exception ever fires, so without this the artifact
    would be empty when the driver's own timeout kills us. A daemon timer
    cannot be blocked by the GIL-released native call; it prints the JSON
    line and _exits. Default 900 s: a healthy run (2 compiles + 2 measured
    windows) finishes in ~4-6 minutes even with cold compiles over a
    tunneled runtime, and the watchdog must beat the harness's own kill
    timeout or the artifact ends up empty anyway."""
    import threading

    def fire():
        print(json.dumps({
            "metric": f"{ARCH}_train_imgs_per_sec_b{BATCH}_{H}x{W}_timeout",
            "value": 0.0,
            "unit": "imgs/sec",
            **_baseline_fields(0.0),
            "error": f"watchdog: no result after {seconds:.0f}s "
                     "(TPU runtime unreachable or wedged)",
            **_failure_evidence(),
        }))
        sys.stdout.flush()
        os._exit(3)

    t = threading.Timer(seconds, fire)
    t.daemon = True
    t.start()


def main():
    watchdog_secs = float(os.environ.get("BENCH_WATCHDOG_SECS", 900))
    _arm_watchdog(watchdog_secs)
    t0 = time.monotonic()

    # Take (or wait for) the single-client lock: if the standing
    # watcher has a probe in flight, dialing alongside it is the
    # two-client wedge. Bounded — a capture must degrade to "proceed
    # and hope" rather than never run; a stale lock (dead holder) is
    # reclaimed inside acquire_client_lock.
    if not acquire_client_lock(
            "bench-capture", wait_secs=min(300.0, 0.3 * watchdog_secs)):
        print("bench: client lock still held after wait "
              f"({_client_lock_holder()}); proceeding anyway",
              file=sys.stderr)
    import atexit

    atexit.register(release_client_lock)

    # Pre-flight (skippable for CPU-only dev runs where dialing a TPU is
    # not even attempted): prove the runtime answers a trivial computation
    # before entering the multi-minute compile path. The staged schedule
    # gets at most 60% of the watchdog budget so a late success still
    # leaves room for the (cache-warmed) bench itself.
    preflight_info = None
    if os.environ.get("BENCH_SKIP_PREFLIGHT") != "1":
        ok, history = _preflight(t0 + 0.6 * watchdog_secs)
        preflight_info = {
            "attempts": len(history),
            "secs": round(time.monotonic() - t0, 1),
            "platform": history[-1].get("platform") if history else None,
        }
        if not ok:
            preflight_error = (
                "preflight: runtime never answered a trivial "
                f"probe in {len(history)} staged attempts over "
                f"{time.monotonic() - t0:.0f}s"
            )
            print(json.dumps(
                _preflight_failure_payload(preflight_error, history)))
            sys.stdout.flush()
            sys.exit(2)

    try:
        result = run()
        if preflight_info is not None:
            result["preflight"] = preflight_info
    except Exception as exc:
        # One retry IN A FRESH PROCESS: jax caches backend-init results
        # process-wide, so an in-process retry after a failed TPU claim
        # would silently fall back to the cached CPU backend instead of
        # re-attempting the claim. exec() replaces this process; the
        # child's JSON line becomes the artifact (and the child runs the
        # full preflight again). Only runtime/backend errors warrant it —
        # deterministic failures (ImportError, bad config) would just
        # fail again after a futile wait.
        retryable = isinstance(
            exc, (RuntimeError, OSError, ConnectionError, TimeoutError)
        )
        if retryable and os.environ.get("_DPT_BENCH_RETRY") != "1":
            print(
                f"bench: {type(exc).__name__}: {exc}; retrying in a fresh "
                "process after 30s",
                file=sys.stderr,
            )
            time.sleep(30)
            env = dict(os.environ)
            env["_DPT_BENCH_RETRY"] = "1"
            sys.stderr.flush()
            sys.stdout.flush()
            os.execve(sys.executable,
                      [sys.executable, os.path.abspath(__file__)], env)
        result = {  # the artifact must never be empty/unparseable
            "metric": f"{ARCH}_train_imgs_per_sec_b{BATCH}_{H}x{W}_error",
            "value": 0.0,
            "unit": "imgs/sec",
            **_baseline_fields(0.0),
            "error": f"{type(exc).__name__}: {exc}",
            **_failure_evidence(),
        }
    print(json.dumps(result))
    sys.stdout.flush()


if __name__ == "__main__":
    main()
