#!/usr/bin/env python3
"""Benchmark harness: UNet training throughput on the available hardware.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "imgs/sec", "vs_baseline": N}

Measured config = the reference's measured config (reference train.py:18-24:
batch 4, 3×640×960, Adam 1e-4, BCE−log-dice), single chip, bf16 compute.

``vs_baseline``: the reference publishes no throughput numbers (SURVEY.md
§6); BASELINE.md's operational target is the 2×GPU DDP config. Until a
measured GPU number exists we normalize against an estimated 2×RTX-3090-class
DDP throughput for this exact model/shape (≈17 imgs/sec: ~7.3 TFLOP/img
forward+backward at ~30% utilization per GPU, README-era hardware), recorded
here so the denominator is explicit and revisable.
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

# Estimated reference DDP (2 GPU) throughput for batch 4 @ 3x640x960 —
# see module docstring; revise when a measured number lands in BASELINE.md.
BASELINE_IMGS_PER_SEC = 17.0

BATCH = 4
H, W = 640, 960
WARMUP_STEPS = 3
MEASURE_STEPS = 20


def main():
    from distributedpytorch_tpu.models.unet import UNet, init_unet_params
    from distributedpytorch_tpu.train.steps import create_train_state, make_train_step

    model = UNet(dtype=jnp.bfloat16)
    params = init_unet_params(model, jax.random.key(0), input_hw=(H, W))
    state, tx = create_train_state(params, 1e-4)
    step = jax.jit(make_train_step(model, tx, batch_size=BATCH), donate_argnums=(0,))

    rng = np.random.default_rng(0)
    dev = jax.devices()[0]
    batch = {
        "image": jax.device_put(rng.random((BATCH, H, W, 3), dtype=np.float32), dev),
        "mask": jax.device_put(
            (rng.random((BATCH, H, W)) > 0.5).astype(np.int32), dev
        ),
    }
    state = jax.device_put(state, dev)

    for _ in range(WARMUP_STEPS):
        state, loss = step(state, batch)
    float(loss)  # device→host transfer: a hard sync even over a PJRT relay
    # (block_until_ready alone does not force execution on tunneled devices)

    t0 = time.perf_counter()
    for _ in range(MEASURE_STEPS):
        state, loss = step(state, batch)
    float(loss)  # forces the whole dependency chain of donated states
    dt = time.perf_counter() - t0

    imgs_per_sec = MEASURE_STEPS * BATCH / dt
    platform = dev.platform
    print(
        json.dumps(
            {
                "metric": f"unet_train_imgs_per_sec_b{BATCH}_{H}x{W}_{platform}",
                "value": round(imgs_per_sec, 2),
                "unit": "imgs/sec",
                "vs_baseline": round(imgs_per_sec / BASELINE_IMGS_PER_SEC, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
