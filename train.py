#!/usr/bin/env python3
"""Train UNet on images and target masks — TPU-native CLI.

Thin launcher preserving the reference's entry surface (reference
README.md:25-44): ``python3 train.py [-t ...]`` or
``torchrun --standalone --nnodes=1 --nproc_per_node=2 train.py -t DDP``.
The implementation lives in distributedpytorch_tpu/cli.py, which is also
installed as the ``dpt-train`` console script.
"""

from distributedpytorch_tpu.cli import main

if __name__ == "__main__":
    main()
