// dpt_data — native data-loading runtime for distributedpytorch_tpu.
//
// The reference feeds its trainer through PIL + torch DataLoader worker
// processes (reference utils/dataloading.py:44-52, utils/train_utils.py:40).
// This library is the TPU framework's native equivalent: JPEG/PNG/GIF decode,
// PIL-compatible BICUBIC/NEAREST resizing (reference dataloading.py:31),
// /255 float normalization into NHWC batch buffers (dataloading.py:39-40),
// and a std::thread pool that assembles whole batches in one C call —
// feeding a ~50 imgs/sec TPU train step without Python in the per-image loop.
//
// Exposed via ctypes (see data/native.py): plain C ABI, caller owns buffers.
//
// Resize parity notes: BICUBIC is Pillow's two-pass separable resampling
// with the Catmull-Rom-like cubic (a = -0.5) and support scaled by the
// downscale ratio, intermediate rows rounded to u8 per pass like Pillow's
// 8-bit path (≤1 LSB differences from Pillow's fixed-point arithmetic).
// NEAREST matches Pillow's affine floor sampling exactly.

#include <algorithm>
#include <atomic>
#include <cmath>
#include <csetjmp>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

// libjpeg's header needs stdio/stddef types declared first
#include <jpeglib.h>
#include <png.h>

namespace {

struct Image {
  int w = 0, h = 0, channels = 0;  // channels: 1 (gray/palette) or 3 (RGB)
  std::vector<uint8_t> pix;        // HWC, u8
};

// ---------------------------------------------------------------- JPEG ----
bool decode_jpeg(FILE* f, Image& out) {
  jpeg_decompress_struct cinfo;
  jpeg_error_mgr jerr;
  cinfo.err = jpeg_std_error(&jerr);
  jerr.error_exit = [](j_common_ptr ci) { longjmp(*(jmp_buf*)ci->client_data, 1); };
  jmp_buf env;
  cinfo.client_data = &env;
  if (setjmp(env)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_stdio_src(&cinfo, f);
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = cinfo.num_components == 1 ? JCS_GRAYSCALE : JCS_RGB;
  jpeg_start_decompress(&cinfo);
  out.w = cinfo.output_width;
  out.h = cinfo.output_height;
  out.channels = cinfo.output_components;
  out.pix.resize(size_t(out.w) * out.h * out.channels);
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = out.pix.data() + size_t(cinfo.output_scanline) * out.w * out.channels;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

// ----------------------------------------------------------------- PNG ----
bool decode_png(FILE* f, Image& out) {
  png_structp png = png_create_read_struct(PNG_LIBPNG_VER_STRING, nullptr, nullptr, nullptr);
  if (!png) return false;
  png_infop info = png_create_info_struct(png);
  if (!info) {
    png_destroy_read_struct(&png, nullptr, nullptr);
    return false;
  }
  if (setjmp(png_jmpbuf(png))) {
    png_destroy_read_struct(&png, &info, nullptr);
    return false;
  }
  png_init_io(png, f);
  png_read_info(png, info);
  png_uint_32 w, h;
  int bit_depth, color_type;
  png_get_IHDR(png, info, &w, &h, &bit_depth, &color_type, nullptr, nullptr, nullptr);
  if (bit_depth == 16) png_set_strip_16(png);
  if (color_type == PNG_COLOR_TYPE_PALETTE) png_set_palette_to_rgb(png);
  if (color_type == PNG_COLOR_TYPE_GRAY && bit_depth < 8) png_set_expand_gray_1_2_4_to_8(png);
  if (color_type & PNG_COLOR_MASK_ALPHA) png_set_strip_alpha(png);
  png_read_update_info(png, info);
  out.w = w;
  out.h = h;
  out.channels = png_get_channels(png, info);
  out.pix.resize(size_t(w) * h * out.channels);
  std::vector<png_bytep> rows(h);
  for (png_uint_32 y = 0; y < h; y++)
    rows[y] = out.pix.data() + size_t(y) * w * out.channels;
  png_read_image(png, rows.data());
  png_destroy_read_struct(&png, &info, nullptr);
  return true;
}

// ----------------------------------------------------------------- GIF ----
// Minimal single-frame GIF87a/89a decoder (LZW). Carvana masks are 1-frame
// palette GIFs with values {0,1} (SURVEY.md §2 quirk 3); emitted as the
// palette INDEX when the palette is a binary mask palette, else as grayscale
// luminance — matching what PIL's 'P'-mode → numpy conversion yields for
// these files (the raw index).
struct ByteReader {
  const uint8_t* p;
  size_t n, off = 0;
  bool read(void* dst, size_t k) {
    if (off + k > n) return false;
    memcpy(dst, p + off, k);
    off += k;
    return true;
  }
  int u8() {
    if (off >= n) return -1;
    return p[off++];
  }
  int u16() {
    int a = u8(), b = u8();
    return (a < 0 || b < 0) ? -1 : a | (b << 8);
  }
};

bool decode_gif(const std::vector<uint8_t>& buf, Image& out) {
  ByteReader r{buf.data(), buf.size()};
  char sig[6];
  if (!r.read(sig, 6) || strncmp(sig, "GIF", 3) != 0) return false;
  int sw = r.u16(), sh = r.u16();
  int flags = r.u8();
  r.u8();  // background color index
  r.u8();  // aspect
  if (sw <= 0 || sh <= 0) return false;
  std::vector<uint8_t> gct;  // global color table, RGB triples
  if (flags & 0x80) {
    int sz = 2 << (flags & 7);
    gct.resize(sz * 3);
    if (!r.read(gct.data(), gct.size())) return false;
  }
  // skip extensions until an image descriptor
  for (;;) {
    int block = r.u8();
    if (block < 0) return false;
    if (block == 0x3B) return false;  // trailer before any image
    if (block == 0x21) {              // extension: label + sub-blocks
      r.u8();
      for (;;) {
        int len = r.u8();
        if (len < 0) return false;
        if (len == 0) break;
        r.off += len;
      }
      continue;
    }
    if (block == 0x2C) break;  // image descriptor
    return false;
  }
  r.u16();  // left
  r.u16();  // top
  int iw = r.u16(), ih = r.u16();
  int iflags = r.u8();
  if (iw <= 0 || ih <= 0) return false;
  std::vector<uint8_t> lct = gct;
  if (iflags & 0x80) {
    int sz = 2 << (iflags & 7);
    lct.resize(sz * 3);
    if (!r.read(lct.data(), lct.size())) return false;
  }
  bool interlaced = iflags & 0x40;

  // LZW decode
  int min_code_size = r.u8();
  if (min_code_size < 2 || min_code_size > 11) return false;
  std::vector<uint8_t> data;  // concatenated sub-blocks
  for (;;) {
    int len = r.u8();
    if (len < 0) return false;
    if (len == 0) break;
    size_t start = data.size();
    data.resize(start + len);
    if (!r.read(data.data() + start, len)) return false;
  }
  const int clear_code = 1 << min_code_size;
  const int end_code = clear_code + 1;
  struct Entry {
    int16_t prefix;
    uint8_t suffix;
    uint16_t len;
  };
  std::vector<Entry> table(4096);
  std::vector<uint8_t> indices;
  indices.reserve(size_t(iw) * ih);
  int code_size = min_code_size + 1, next_code = end_code + 1, prev = -1;
  uint32_t bits = 0;
  int nbits = 0;
  for (int i = 0; i < clear_code; i++) table[i] = {-1, uint8_t(i), 1};
  std::vector<uint8_t> scratch;
  for (size_t pos = 0; pos <= data.size();) {
    while (nbits < code_size && pos < data.size()) {
      bits |= uint32_t(data[pos++]) << nbits;
      nbits += 8;
    }
    if (nbits < code_size) break;
    int code = bits & ((1 << code_size) - 1);
    bits >>= code_size;
    nbits -= code_size;
    if (code == clear_code) {
      code_size = min_code_size + 1;
      next_code = end_code + 1;
      prev = -1;
      continue;
    }
    if (code == end_code) break;
    if (code > next_code || (code == next_code && prev < 0)) return false;
    // expand code (or prev + first(prev) for the not-yet-defined code)
    int expand = code == next_code ? prev : code;
    scratch.clear();
    for (int c = expand; c >= 0; c = table[c].prefix) scratch.push_back(table[c].suffix);
    std::reverse(scratch.begin(), scratch.end());
    if (code == next_code) scratch.push_back(scratch[0]);
    indices.insert(indices.end(), scratch.begin(), scratch.end());
    if (prev >= 0 && next_code < 4096) {
      table[next_code] = {int16_t(prev), scratch[0], uint16_t(table[prev].len + 1)};
      next_code++;
      if (next_code == (1 << code_size) && code_size < 12) code_size++;
    }
    prev = code;
    if (indices.size() >= size_t(iw) * ih) break;
  }
  if (indices.size() < size_t(iw) * ih) return false;

  out.w = iw;
  out.h = ih;
  out.channels = 1;
  out.pix.resize(size_t(iw) * ih);
  // de-interlace if needed
  if (interlaced) {
    static const int start[4] = {0, 4, 2, 1}, step[4] = {8, 8, 4, 2};
    size_t src = 0;
    for (int pass = 0; pass < 4; pass++)
      for (int y = start[pass]; y < ih; y += step[pass], src++)
        memcpy(out.pix.data() + size_t(y) * iw, indices.data() + src * iw, iw);
  } else {
    memcpy(out.pix.data(), indices.data(), size_t(iw) * ih);
  }
  // PIL 'P'-mode → numpy yields raw palette indices; keep them.
  return true;
}

// --------------------------------------------------------------- decode ----
bool ends_with(const std::string& s, const char* suf) {
  std::string l = s;
  std::transform(l.begin(), l.end(), l.begin(), ::tolower);
  size_t n = strlen(suf);
  return l.size() >= n && l.compare(l.size() - n, n, suf) == 0;
}

bool decode_file(const char* path, Image& out) {
  std::string p(path);
  if (ends_with(p, ".gif")) {
    FILE* f = fopen(path, "rb");
    if (!f) return false;
    fseek(f, 0, SEEK_END);
    long sz = ftell(f);
    fseek(f, 0, SEEK_SET);
    std::vector<uint8_t> buf(sz);
    bool ok = fread(buf.data(), 1, sz, f) == size_t(sz);
    fclose(f);
    return ok && decode_gif(buf, out);
  }
  FILE* f = fopen(path, "rb");
  if (!f) return false;
  bool ok = false;
  if (ends_with(p, ".png")) {
    ok = decode_png(f, out);
  } else if (ends_with(p, ".jpg") || ends_with(p, ".jpeg")) {
    ok = decode_jpeg(f, out);
  }
  fclose(f);
  return ok;
}

// --------------------------------------------------------------- resize ----
// Pillow-compatible separable resampling, 8-bit path (cubic a=-0.5).
double cubic_filter(double x) {
  constexpr double a = -0.5;
  x = std::abs(x);
  if (x < 1.0) return ((a + 2.0) * x - (a + 3.0)) * x * x + 1.0;
  if (x < 2.0) return (((x - 5.0) * x + 8.0) * x - 4.0) * a;
  return 0.0;
}

struct FilterBank {
  int ksize;                 // max taps per output pixel
  std::vector<int> bounds;   // per out pixel: (xmin, taps)
  std::vector<float> coefs;  // ksize per out pixel, normalized
};

FilterBank precompute(int in_size, int out_size, double support) {
  FilterBank fb;
  double scale = double(in_size) / out_size;
  double filterscale = std::max(scale, 1.0);
  double sup = support * filterscale;
  fb.ksize = int(ceil(sup)) * 2 + 1;
  fb.bounds.resize(out_size * 2);
  fb.coefs.resize(size_t(out_size) * fb.ksize);
  for (int xx = 0; xx < out_size; xx++) {
    double center = (xx + 0.5) * scale;
    int xmin = std::max(0, int(center - sup + 0.5));
    int xmax = std::min(in_size, int(center + sup + 0.5)) - xmin;
    float* k = fb.coefs.data() + size_t(xx) * fb.ksize;
    double ww = 0.0;
    std::vector<double> w64(xmax);
    for (int x = 0; x < xmax; x++) {
      w64[x] = cubic_filter((x + xmin - center + 0.5) / filterscale);
      ww += w64[x];
    }
    for (int x = 0; x < xmax; x++) k[x] = float(ww != 0.0 ? w64[x] / ww : w64[x]);
    for (int x = xmax; x < fb.ksize; x++) k[x] = 0.0f;
    fb.bounds[xx * 2] = xmin;
    fb.bounds[xx * 2 + 1] = xmax;
  }
  return fb;
}

inline uint8_t clip8(float v) {
  int iv = int(v + 0.5f);
  return uint8_t(std::min(255, std::max(0, iv)));
}

void resize_bicubic(const Image& in, int out_w, int out_h, Image& out) {
  FilterBank fh = precompute(in.w, out_w, 2.0);
  FilterBank fv = precompute(in.h, out_h, 2.0);
  const int C = in.channels;
  // horizontal pass (rounded to u8 like Pillow's 8-bit pipeline); all three
  // channels accumulate per tap so the inner loop walks src contiguously
  Image tmp;
  tmp.w = out_w;
  tmp.h = in.h;
  tmp.channels = C;
  tmp.pix.resize(size_t(out_w) * in.h * C);
  for (int y = 0; y < in.h; y++) {
    const uint8_t* src = in.pix.data() + size_t(y) * in.w * C;
    uint8_t* dst = tmp.pix.data() + size_t(y) * out_w * C;
    if (C == 3) {
      for (int xx = 0; xx < out_w; xx++) {
        const int xmin = fh.bounds[xx * 2], taps = fh.bounds[xx * 2 + 1];
        const float* k = fh.coefs.data() + size_t(xx) * fh.ksize;
        float a0 = 0.f, a1 = 0.f, a2 = 0.f;
        const uint8_t* s = src + xmin * 3;
        for (int x = 0; x < taps; x++) {
          const float w = k[x];
          a0 += s[x * 3] * w;
          a1 += s[x * 3 + 1] * w;
          a2 += s[x * 3 + 2] * w;
        }
        dst[xx * 3] = clip8(a0);
        dst[xx * 3 + 1] = clip8(a1);
        dst[xx * 3 + 2] = clip8(a2);
      }
    } else {
      for (int xx = 0; xx < out_w; xx++) {
        const int xmin = fh.bounds[xx * 2], taps = fh.bounds[xx * 2 + 1];
        const float* k = fh.coefs.data() + size_t(xx) * fh.ksize;
        for (int c = 0; c < C; c++) {
          float acc = 0.f;
          for (int x = 0; x < taps; x++) acc += src[(xmin + x) * C + c] * k[x];
          dst[xx * C + c] = clip8(acc);
        }
      }
    }
  }
  // vertical pass: accumulate a whole output row at once (unit-stride over
  // the row for every tap → vectorizable)
  out.w = out_w;
  out.h = out_h;
  out.channels = C;
  out.pix.resize(size_t(out_w) * out_h * C);
  const int row = out_w * C;
  std::vector<float> acc(row);
  for (int yy = 0; yy < out_h; yy++) {
    const int ymin = fv.bounds[yy * 2], taps = fv.bounds[yy * 2 + 1];
    const float* k = fv.coefs.data() + size_t(yy) * fv.ksize;
    std::fill(acc.begin(), acc.end(), 0.f);
    for (int y = 0; y < taps; y++) {
      const float w = k[y];
      const uint8_t* srow = tmp.pix.data() + size_t(ymin + y) * row;
      for (int xx = 0; xx < row; xx++) acc[xx] += srow[xx] * w;
    }
    uint8_t* dst = out.pix.data() + size_t(yy) * row;
    for (int xx = 0; xx < row; xx++) dst[xx] = clip8(acc[xx]);
  }
}

void resize_nearest(const Image& in, int out_w, int out_h, Image& out) {
  out.w = out_w;
  out.h = out_h;
  out.channels = in.channels;
  out.pix.resize(size_t(out_w) * out_h * in.channels);
  int C = in.channels;
  // Pillow NEAREST resize = ImagingTransformAffine: source coordinates are
  // produced by REPEATED ADDITION of the scale from a half-pixel origin
  // (xx = scale/2; per pixel: src = int(xx); xx += scale), then truncated.
  // The floating-point drift of that accumulation is observable in Pillow's
  // output on upscales (e.g. 4→7 picks index 1 where direct multiplication
  // gives exactly 2.0), so a closed-form src = int((dst+0.5)*in/out) is NOT
  // Pillow-exact. Replicate the accumulation bit-for-bit.
  const double xscale = double(in.w) / out_w;
  const double yscale = double(in.h) / out_h;
  std::vector<int> xmap(out_w);
  double xx = xscale * 0.5;
  for (int x = 0; x < out_w; x++) {
    xmap[x] = std::min(in.w - 1, int(xx));
    xx += xscale;
  }
  double yy = yscale * 0.5;
  for (int y = 0; y < out_h; y++) {
    int sy = std::min(in.h - 1, int(yy));
    yy += yscale;
    const uint8_t* srow = in.pix.data() + size_t(sy) * in.w * C;
    uint8_t* drow = out.pix.data() + size_t(y) * out_w * C;
    if (C == 1) {
      for (int x = 0; x < out_w; x++) drow[x] = srow[xmap[x]];
    } else {
      for (int x = 0; x < out_w; x++)
        memcpy(drow + size_t(x) * C, srow + size_t(xmap[x]) * C, C);
    }
  }
}

// one item: decode + resize + normalize into caller buffers
int load_one(const char* img_path, const char* mask_path, int out_w, int out_h,
             float* img_out /* H*W*3 */, int32_t* mask_out /* H*W */) {
  if (img_path) {
    Image raw, res;
    if (!decode_file(img_path, raw)) return 1;
    resize_bicubic(raw, out_w, out_h, res);
    size_t n = size_t(out_w) * out_h;
    if (res.channels == 3) {
      for (size_t i = 0; i < n * 3; i++) img_out[i] = res.pix[i] / 255.0f;
    } else {  // grayscale → replicate like PIL convert would; reference keeps
              // 1 channel (dataloading.py:34-35) but the model wants 3 — the
              // python wrapper only uses this path for 3-channel data.
      for (size_t i = 0; i < n; i++) {
        float v = res.pix[i] / 255.0f;
        img_out[i * 3] = img_out[i * 3 + 1] = img_out[i * 3 + 2] = v;
      }
    }
  }
  if (mask_path) {
    Image raw, res;
    if (!decode_file(mask_path, raw)) return 2;
    if (raw.channels != 1) {  // take first channel (masks are palette/gray)
      Image g;
      g.w = raw.w;
      g.h = raw.h;
      g.channels = 1;
      g.pix.resize(size_t(raw.w) * raw.h);
      for (size_t i = 0; i < g.pix.size(); i++) g.pix[i] = raw.pix[i * raw.channels];
      raw = std::move(g);
    }
    resize_nearest(raw, out_w, out_h, res);
    size_t n = size_t(out_w) * out_h;
    for (size_t i = 0; i < n; i++) mask_out[i] = res.pix[i];
  }
  return 0;
}

}  // namespace

extern "C" {

// Decode+preprocess one image/mask pair. Either path may be null. Returns 0
// on success, 1 on image failure, 2 on mask failure.
int dpt_load_item(const char* img_path, const char* mask_path, int out_w,
                  int out_h, float* img_out, int32_t* mask_out) {
  return load_one(img_path, mask_path, out_w, out_h, img_out, mask_out);
}

// Assemble a full batch with a thread pool. imgs/masks are arrays of n paths
// (either array may be null). Outputs are contiguous NHWC float32 /
// NHW int32. Returns 0 on success, else 100+i for the first failed item i.
int dpt_load_batch(const char** img_paths, const char** mask_paths, int n,
                   int out_w, int out_h, int n_threads, float* imgs_out,
                   int32_t* masks_out) {
  std::atomic<int> next(0), err(-1);
  size_t img_stride = size_t(out_w) * out_h * 3;
  size_t mask_stride = size_t(out_w) * out_h;
  auto worker = [&]() {
    for (;;) {
      int i = next.fetch_add(1);
      if (i >= n || err.load() >= 0) return;
      int rc = load_one(img_paths ? img_paths[i] : nullptr,
                        mask_paths ? mask_paths[i] : nullptr, out_w, out_h,
                        imgs_out ? imgs_out + img_stride * i : nullptr,
                        masks_out ? masks_out + mask_stride * i : nullptr);
      if (rc != 0) err.store(100 + i);
    }
  };
  int k = std::max(1, std::min(n_threads, n));
  std::vector<std::thread> threads;
  for (int t = 0; t < k - 1; t++) threads.emplace_back(worker);
  worker();
  for (auto& t : threads) t.join();
  return err.load() >= 0 ? err.load() : 0;
}

const char* dpt_version() { return "dpt_data 0.1.0"; }
}
