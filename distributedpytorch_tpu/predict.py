"""Inference: masks from a trained checkpoint — the batch-offline CLI.

The reference ships `plot_img_and_mask` (reference utils/utils.py:38-51)
but no code path that ever produces a predicted mask to plot — inference
is a hole in its surface. This module closes it TPU-style: ONE jitted
batched forward reused across the run, images streamed batch-by-batch
(memory stays O(batch_size), not O(dataset)), masks thresholded at 0.5
and written as {0,255} PNGs.

Every inference-semantics piece — preprocessing (BICUBIC resize, /255,
NHWC, forced RGB), the eval forward, checkpoint loading, mask
thresholding — lives in ``serve/infer.py`` and is SHARED with the
serving tier (``python -m distributedpytorch_tpu serve``): this CLI and
the server run the same functions, and tests/test_serve.py pins their
outputs bit-identical. This module only adds the offline concerns:
directory walking, output naming, PNG writing.

CLI:  dpt-predict -c singleGPU -i ./data/test_hq -o ./predictions
      (or: python -m distributedpytorch_tpu.predict ...)
"""

from __future__ import annotations

import argparse
import logging
import os
from typing import Iterable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from distributedpytorch_tpu.serve.infer import (
    bundle_variables,
    load_inference_bundle,
    load_params_for_inference,  # noqa: F401 — re-export (historical home)
    make_forward,
    postprocess_mask,
    preprocess_image,
)

logger = logging.getLogger(__name__)


def predict_batches(
    params,
    model,
    images: Iterable[np.ndarray],
    batch_size: int = 4,
    model_state=None,
    quantized: bool = False,
) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Stream (probs (b,H,W), inputs (b,H,W,3)) pairs over an iterable of
    (H,W,3) float32 arrays. One jit compile for full batches (plus at most
    one for a ragged final batch). Stateful models (milesial BatchNorm)
    pass their running statistics as `model_state` and apply in eval mode.

    The forward is ``serve/infer.make_forward`` — the function the
    serving tier AOT-compiles per bucket; here it jit-compiles lazily at
    the offline CLI's two shapes. ``quantized`` must mirror the bundle's
    flag when ``params`` is an int8 weights-only tree (ops/quant.py) —
    the forward then dequantizes in-trace, exactly like serving."""
    import jax
    import jax.numpy as jnp

    variables = bundle_variables(model, params, model_state)
    forward = jax.jit(make_forward(model, quantized=quantized))

    buf: List[np.ndarray] = []

    def flush(buf):
        batch = np.stack(buf)
        probs = forward(variables, jnp.asarray(batch))
        return np.asarray(probs), batch

    for arr in images:
        buf.append(arr)
        if len(buf) == batch_size:
            yield flush(buf)
            buf = []
    if buf:
        yield flush(buf)


def run_prediction(
    checkpoint: str,
    input_dir: str,
    output_dir: str,
    image_size: Sequence[int] = (960, 640),
    batch_size: int = 4,
    threshold: float = 0.5,
    save_viz: bool = False,
    checkpoint_dir: str = "./checkpoints",
    model_widths: Optional[Sequence[int]] = None,
    model_arch: str = "unet",
    s2d_levels: int = -1,
) -> List[str]:
    """Predict masks for every image in `input_dir`; returns written paths.

    `model_arch`/`model_widths` must match the trained checkpoint's
    architecture (TrainConfig.model_arch / model_widths). ``s2d_levels``
    follows TrainConfig (-1 = auto); sizes the space-to-depth mode cannot
    express (H or W not divisible by 2**levels) auto-fall-back to the
    pixel path — checkpoints are identical across execution modes, so
    this changes speed, never results (ADVICE r03: there was previously
    no inference-side workaround at all).
    """
    from PIL import Image

    from distributedpytorch_tpu.data.dataset import BasicDataset

    bundle = load_inference_bundle(
        checkpoint,
        checkpoint_dir=checkpoint_dir,
        image_size=image_size,
        model_arch=model_arch,
        model_widths=model_widths,
        s2d_levels=s2d_levels,
    )
    w, h = int(image_size[0]), int(image_size[1])

    files = sorted(
        f
        for f in os.listdir(input_dir)
        if not f.startswith(".")
        and os.path.splitext(f)[1].lower() in (".jpg", ".jpeg", ".png", ".gif")
    )
    if not files:
        raise RuntimeError(f"No input images found in {input_dir}")
    os.makedirs(output_dir, exist_ok=True)

    # Output names: stem-based, but inputs differing only by extension
    # (car1.jpg + car1.png) must not clobber each other's masks — such
    # stems keep their extension in the output name.
    stem_counts: dict = {}
    for f in files:
        stem_counts[os.path.splitext(f)[0]] = (
            stem_counts.get(os.path.splitext(f)[0], 0) + 1
        )

    def out_stem(fname: str) -> str:
        stem, ext = os.path.splitext(fname)
        return stem if stem_counts[stem] == 1 else f"{stem}_{ext.lstrip('.')}"

    def load_stream() -> Iterator[np.ndarray]:
        for f in files:
            yield preprocess_image(
                BasicDataset.load(os.path.join(input_dir, f)), (w, h)
            )

    written: List[str] = []
    idx = 0
    for probs, inputs in predict_batches(
        bundle.params, bundle.model, load_stream(), batch_size,
        model_state=bundle.model_state, quantized=bundle.quantized,
    ):
        for prob, inp in zip(probs, inputs):
            stem = out_stem(files[idx])
            mask = postprocess_mask(prob, threshold)
            out_path = os.path.join(output_dir, f"{stem}_mask.png")
            Image.fromarray(mask).save(out_path)
            written.append(out_path)
            if save_viz:
                from distributedpytorch_tpu.utils.plotting import plot_img_and_mask

                plot_img_and_mask(
                    inp,
                    mask,
                    out_path=os.path.join(output_dir, f"{stem}_viz.png"),
                )
            idx += 1
    logger.info("Wrote %d masks to %s", len(written), output_dir)
    return written


def main():
    parser = argparse.ArgumentParser(description="Predict masks from input images")
    parser.add_argument("--checkpoint", "-c", required=True,
                        help="Checkpoint name (e.g. singleGPU) or path (.ckpt/.pth)")
    parser.add_argument("--input", "-i", required=True, help="Directory of images")
    parser.add_argument("--output", "-o", default="./predictions",
                        help="Output directory for predicted masks")
    parser.add_argument("--image-size", type=int, nargs=2, default=(960, 640),
                        metavar=("W", "H"))
    parser.add_argument("--batch-size", "-b", type=int, default=4)
    parser.add_argument("--threshold", "-t", type=float, default=0.5)
    parser.add_argument("--viz", action="store_true",
                        help="Also save image+mask side-by-side panels")
    parser.add_argument("--checkpoint-dir", default="./checkpoints")
    parser.add_argument("--model-widths", type=int, nargs="+", default=None,
                        help="Encoder widths if the checkpoint was trained "
                             "with non-default TrainConfig.model_widths")
    parser.add_argument("--model", dest="model_arch", type=str, default="unet",
                        choices=["unet", "milesial"],
                        help="Model family the checkpoint was trained with")
    parser.add_argument("--s2d-levels", type=int, default=-1,
                        help="Space-to-depth execution levels (-1 auto, "
                             "0 pixel path); non-divisible image sizes "
                             "fall back to 0 automatically")
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO, format="%(message)s")
    run_prediction(
        args.checkpoint,
        args.input,
        args.output,
        image_size=args.image_size,
        batch_size=args.batch_size,
        threshold=args.threshold,
        save_viz=args.viz,
        checkpoint_dir=args.checkpoint_dir,
        model_widths=args.model_widths,
        model_arch=args.model_arch,
        s2d_levels=args.s2d_levels,
    )


if __name__ == "__main__":
    main()
