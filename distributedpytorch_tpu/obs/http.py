"""Metrics HTTP endpoint for processes that have no HTTP front of
their own (training ranks via ``--metrics-port``, the elastic
supervisor). The serve tier mounts the same handlers on its existing
``ThreadingHTTPServer`` (serve/cli.py) instead of opening a second
port.

Stdlib ``ThreadingHTTPServer`` on a daemon thread:

* ``GET /metrics``  — Prometheus text exposition of the process-wide
  registry;
* ``GET /healthz``  — liveness JSON: ``status``, ``uptime_s``, and the
  build/config fingerprint (:func:`build_fingerprint`).

Port 0 binds an ephemeral port (tests); read it back from ``.port``.
"""

from __future__ import annotations

import hashlib
import json
import sys
import threading
import time
from typing import Optional

from distributedpytorch_tpu.obs.registry import CONTENT_TYPE, REGISTRY


def build_fingerprint(config=None) -> dict:
    """What build+configuration produced this process's numbers — the
    thing a post-incident reader needs to reproduce them. ``config``
    may be any dataclass-like object with ``__dict__``/asdict, or a
    plain dict."""
    from distributedpytorch_tpu import __version__

    fp = {
        "package": "distributedpytorch_tpu",
        "version": __version__,
        "python": sys.version.split()[0],
    }
    if config is not None:
        if hasattr(config, "__dataclass_fields__"):
            import dataclasses

            items = dataclasses.asdict(config)
        elif isinstance(config, dict):
            items = config
        else:
            items = dict(vars(config))
        blob = json.dumps(items, sort_keys=True, default=str)
        fp["config_sha"] = hashlib.sha256(blob.encode()).hexdigest()[:12]
    return fp


def metrics_response(registry=None):
    """``(body_bytes, content_type)`` of a /metrics scrape — THE
    exposition write, shared by this module's server and the serve
    front's handler (serve/cli.py) so the two cannot drift."""
    return (registry or REGISTRY).expose().encode(), CONTENT_TYPE


def healthz_payload(started_t: float, fingerprint: dict,
                    ready: bool = True, **extra) -> dict:
    """The /healthz JSON body (status + uptime + fingerprint), shared
    the same way; ``extra`` carries endpoint-specific inventory (the
    serve front adds its bucket/replica fields). ``ready`` is the
    liveness-vs-readiness split (docs/SERVING.md): a live process that
    should not receive traffic right now (dispatch core relaunching,
    rollout canary in flight) answers ``ready: false`` — the serve
    front pairs that with HTTP 503 so load balancers act on the status
    code alone."""
    payload = {
        "status": "ok" if ready else "unready",
        "ready": bool(ready),
        "uptime_s": round(time.monotonic() - started_t, 3),
        "fingerprint": fingerprint,
    }
    payload.update(extra)
    return payload


class MetricsServer:
    """A started /metrics + /healthz endpoint; ``close()`` to stop.

    ``expose_text_fn`` overrides what a /metrics scrape returns (still
    the Prometheus text format) — the elastic serve supervisor passes a
    closure that merges its own registry with the scraped, worker-
    labeled fleet expositions (``registry.merge_expositions``)."""

    def __init__(self, port: int, host: str = "127.0.0.1",
                 registry=None, fingerprint: Optional[dict] = None,
                 expose_text_fn=None):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        fingerprint = fingerprint or build_fingerprint()
        started_t = time.monotonic()

        class Handler(BaseHTTPRequestHandler):
            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 — http.server's contract
                if self.path == "/metrics":
                    if expose_text_fn is not None:
                        self._send(200, expose_text_fn().encode(),
                                   CONTENT_TYPE)
                    else:
                        self._send(200, *metrics_response(registry))
                elif self.path == "/healthz":
                    self._send(200, json.dumps(
                        healthz_payload(started_t, fingerprint)
                    ).encode(), "application/json")
                else:
                    self._send(404, json.dumps(
                        {"error": f"no route {self.path}"}
                    ).encode(), "application/json")

            def log_message(self, fmt, *args):  # keep scrapes off stderr
                pass

        self._httpd = ThreadingHTTPServer((host, int(port)), Handler)
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="dpt-metrics-http",
        )
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


def start_metrics_server(port: int, host: str = "127.0.0.1",
                         registry=None,
                         fingerprint: Optional[dict] = None,
                         expose_text_fn=None) -> MetricsServer:
    return MetricsServer(port, host=host, registry=registry,
                         fingerprint=fingerprint,
                         expose_text_fn=expose_text_fn)
