"""Unified telemetry layer: metrics registry + Prometheus exposition,
Perfetto trace export, and a crash-dumping flight recorder.

Until this subsystem existed, the repo's observability was three
non-composing fragments: ``utils/trace.py`` wrote step spans only under
``--trace-timeline``, ``serve/metrics.py`` was a serve-private
snapshot, and ``dist/health.py`` beat files were supervisor-internal —
so a dead or stalled run left no artifact saying *where* (chip windows
r03–r05, ROADMAP "Recent"). In the spirit of Dapper-style always-on
tracing and MLPerf-logging-style standardized run records, telemetry is
now a first-class subsystem every run carries by default:

* :mod:`~distributedpytorch_tpu.obs.registry` — the process-wide
  metrics registry (counters / gauges / bounded-window histograms,
  labels, lock-cheap updates) with Prometheus text exposition and a
  strict format checker. Train, serve, and supervisor families are
  cataloged in :mod:`~distributedpytorch_tpu.obs.defs` (import it as
  ``obsm``). Served at ``GET /metrics`` on the serve HTTP front and on
  ``--metrics-port`` training runs (:mod:`~distributedpytorch_tpu.obs.http`).
* :mod:`~distributedpytorch_tpu.obs.trace_hub` — rank-tagged step-span
  events exported as Perfetto/Chrome trace JSON, merged across ranks
  by the elastic supervisor; device profiles via the trainer's
  ``--profile-steps N:M``.
* :mod:`~distributedpytorch_tpu.obs.flight` — the always-on bounded
  ring buffer of recent events, dumped to a JSON post-mortem artifact
  on watchdog timeout, dispatch-loop death, non-finite-loss abort,
  SIGTERM, and unhandled exit, and referenced from bench_multi
  poison/provenance lines.

Hot-path contract (enforced by dptlint's ``obs-hot-path`` rule,
docs/ANALYSIS.md): nothing in a record path blocks on a device value or
grows without bound, and no ``obs``/``obsm``/``flight`` call appears
inside a jit/shard_map-traced function. ``DPT_OBS=0`` disables flight
recording (the overhead A/B lever; measured < 1% in
docs/OBSERVABILITY.md). The whole package is stdlib-only and jax-free —
the elastic supervisor imports it before any backend exists.
"""

from distributedpytorch_tpu.obs import defs  # noqa: F401 — eager catalog
from distributedpytorch_tpu.obs import flight  # noqa: F401
from distributedpytorch_tpu.obs.registry import (  # noqa: F401
    CONTENT_TYPE,
    REGISTRY,
    MetricsRegistry,
    get_registry,
    validate_exposition,
)
