"""Flight recorder: an always-on bounded ring buffer of recent events,
dumped to a JSON artifact when the process dies badly.

Chip windows r03–r05 died without a single artifact saying *where*
(ROADMAP "Recent"): a watchdogged step, a wedged compile, a dead
dispatch loop each left only an absence of output. The flight recorder
is the black box for that failure class — cheap enough to leave on for
every run (one ``deque.append`` of a small dict per event; the deque's
``maxlen`` bounds memory by construction), and dumped by the code paths
that already know the run is dying:

* the trainer's dispatch watchdog (``train/loop.py``),
* the non-finite-loss ``abort`` policy,
* the SIGTERM/SIGINT checkpoint-and-stop handler,
* the serve dispatch loop's death path (``serve/server.py``),
* ``bench_multi``'s poison/dead-probe marks (``tools/bench_multi.py``),
* an optional unhandled-exception hook (:func:`install_excepthook`).

What flows in (always-on, no flags): step-timeline spans
(``utils/trace.py`` routes every span here even when JSONL tracing is
off), queue flush/shed decisions and placement/dispatch transitions
(serve tier), fault injections (``utils/faults.py``), and
collective-phase markers (epoch/eval/checkpoint boundaries). The tail
of the ring therefore identifies the phase a dead run was in.

Hot-path contract (enforced by dptlint's ``obs-hot-path`` rule):
``record`` never blocks on a device value and allocates nothing beyond
the ring slot — ``deque.append`` with ``maxlen`` is atomic under the
GIL, so the record path takes **no lock**.

``DPT_OBS=0`` disables recording (the overhead A/B lever used for the
numbers in docs/OBSERVABILITY.md). Dump-path precedence:
:func:`set_dump_path` (explicit caller, e.g. bench_multi per leg) >
``$DPT_FLIGHT_PATH`` > ``$DPT_FLIGHT_DIR``/flight_rank<R>.json >
the default installed by the owning subsystem (trainer: under its log
dir) > ``./logs/flight_rank<R>.json``.

Stdlib-only and jax-free, like the rest of ``obs/``.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import sys
import threading
import time
from typing import List, Optional

logger = logging.getLogger(__name__)

#: Ring capacity: enough to hold several steps' worth of spans plus the
#: surrounding phase markers — the post-mortem needs the tail, not the run.
DEFAULT_CAPACITY = 512


def _obs_enabled() -> bool:
    return os.environ.get("DPT_OBS", "1").lower() not in ("0", "off", "false")


class FlightRecorder:
    """See module docstring. One per process (:func:`get`)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._events: collections.deque = collections.deque(maxlen=capacity)
        self.enabled = _obs_enabled()
        self.rank = 0
        self._explicit_path: Optional[str] = None
        self._default_path: Optional[str] = None
        self._dump_lock = threading.Lock()
        self.last_dump_path: Optional[str] = None
        self._hook_installed = False

    # -- recording (hot-path safe: no locks, bounded allocation) ------------
    def record(self, kind: str, **fields) -> None:
        if not self.enabled:
            return
        fields["t"] = round(time.time(), 6)
        fields["kind"] = kind
        self._events.append(fields)

    def record_span(self, phase: str, t0: float, t1: float, **tags) -> None:
        """A timed phase span (the step-timeline tracer's feed)."""
        if not self.enabled:
            return
        tags["t"] = round(time.time(), 6)
        tags["kind"] = "span"
        tags["phase"] = phase
        tags["dur_ms"] = round((t1 - t0) * 1e3, 3)
        self._events.append(tags)

    # -- configuration -------------------------------------------------------
    def set_dump_path(self, path: Optional[str]) -> None:
        """Explicit dump path — wins over the env vars and defaults."""
        self._explicit_path = path

    def set_default_dump_path(self, path: str) -> None:
        """Subsystem-installed default (trainer/server): used only when
        neither :func:`set_dump_path` nor the env vars name a path."""
        self._default_path = path

    def resolve_dump_path(self) -> str:
        if self._explicit_path:
            return self._explicit_path
        env_path = os.environ.get("DPT_FLIGHT_PATH")
        if env_path:
            return env_path
        env_dir = os.environ.get("DPT_FLIGHT_DIR")
        if env_dir:
            return os.path.join(env_dir, f"flight_rank{self.rank}.json")
        if self._default_path:
            return self._default_path
        return os.path.join("./logs", f"flight_rank{self.rank}.json")

    # -- inspection (tests / exporters) --------------------------------------
    def snapshot(self) -> List[dict]:
        return list(self._events)

    def clear(self) -> None:
        self._events.clear()
        self.last_dump_path = None

    def __len__(self) -> int:
        return len(self._events)

    # -- the dump ------------------------------------------------------------
    def dump(self, reason: str, path: Optional[str] = None,
             extra: Optional[dict] = None) -> Optional[str]:
        """Write the ring to a JSON artifact. NEVER raises — every
        caller is already on a dying path where a secondary I/O error
        must not mask the primary failure. Returns the artifact path
        (or None when recording is disabled / the write failed)."""
        if not self.enabled:
            return None
        try:
            out = path or self.resolve_dump_path()
            payload = {
                "reason": reason,
                "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                "pid": os.getpid(),
                "rank": self.rank,
                "events": self.snapshot(),
            }
            if extra:
                payload["extra"] = extra
            # non-blocking: dump() is called from SIGNAL HANDLERS, which
            # Python runs on the main thread — a handler that fires while
            # this same thread is mid-dump would deadlock on a blocking
            # acquire of its own lock. If a dump is already in progress,
            # the post-mortem is being written; skip this one.
            if not self._dump_lock.acquire(blocking=False):
                return None
            try:
                d = os.path.dirname(os.path.abspath(out))
                os.makedirs(d, exist_ok=True)
                tmp = f"{out}.tmp.{os.getpid()}"
                with open(tmp, "w") as f:
                    json.dump(payload, f)
                os.replace(tmp, out)
                self.last_dump_path = out
            finally:
                self._dump_lock.release()
            logger.error("flight recorder: dumped %d event(s) to %s (%s)",
                         len(payload["events"]), out, reason)
            try:  # lazy: defs pulls in the registry, which dump paths
                # must not depend on to write the artifact itself
                from distributedpytorch_tpu.obs import defs as obsm

                obsm.FLIGHT_DUMPS.labels(
                    reason_class=reason.split(":", 1)[0].strip()
                ).inc()
            except Exception:  # noqa: BLE001 — accounting only
                pass
            return out
        except Exception:  # noqa: BLE001 — see docstring
            logger.exception("flight recorder dump failed")
            return None

    # -- unhandled-exit hook -------------------------------------------------
    def install_excepthook(self) -> None:
        """Dump the ring on an unhandled exception (then defer to the
        previous hook). Idempotent."""
        if self._hook_installed:
            return
        self._hook_installed = True
        prev = sys.excepthook

        def hook(exc_type, exc, tb):
            self.dump(f"unhandled_exception: {exc_type.__name__}: "
                      f"{str(exc)[:200]}")
            prev(exc_type, exc, tb)

        sys.excepthook = hook


_RECORDER = FlightRecorder()


def get() -> FlightRecorder:
    return _RECORDER


def record(kind: str, **fields) -> None:
    _RECORDER.record(kind, **fields)


def record_span(phase: str, t0: float, t1: float, **tags) -> None:
    _RECORDER.record_span(phase, t0, t1, **tags)


def dump(reason: str, path: Optional[str] = None,
         extra: Optional[dict] = None) -> Optional[str]:
    return _RECORDER.dump(reason, path=path, extra=extra)


def set_dump_path(path: Optional[str]) -> None:
    _RECORDER.set_dump_path(path)


def set_default_dump_path(path: str) -> None:
    _RECORDER.set_default_dump_path(path)


def set_rank(rank: int) -> None:
    _RECORDER.rank = int(rank)


def install_excepthook() -> None:
    _RECORDER.install_excepthook()
