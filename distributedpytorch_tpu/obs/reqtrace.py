"""Request-scoped tracing: one span ledger per served request, with
derived per-phase tail-latency attribution.

The serve tier (serve/) can say *that* a p99 request was slow but not
*why* — queue wait under a burst? pad-heavy bucket? a wedged replica
stalling the dispatch loop? a drain pool ceiling? Dapper-style
request-scoped tracing is the standard answer: an id is assigned at
ingress (W3C ``traceparent`` accepted, ``X-Request-Id`` echoed), every
lifecycle transition stamps a timestamp onto the request, and the
completion drain derives a contiguous span ledger whose durations sum
to the request's end-to-end latency *by construction*:

    ========== ===================================================
    span       boundary (consecutive lifecycle marks)
    ========== ===================================================
    decode     ingress → admitted (decode/preprocess + admission)
    queue_wait admitted → flushed (batching wait; tagged with the
               flush reason: full/deadline/eager/shed)
    placement  flushed → placed (slot-claim backpressure + stack/pad
               + H2D on the placement worker)
    dispatch_wait placed → dispatched (buffered behind the dispatch
               loop — a wedged replica/predecessor shows up HERE)
    device_exec dispatched → device result on host (the honest
               host-observed service time per bucket)
    drain      device result → future resolved (slice/threshold/
               per-request fan-out)
    ========== ===================================================

On top of the ledger this module derives the aggregate views:

* **per-phase attribution** (``snapshot_attribution``): p50/p95/p99 per
  span over a bounded ring of completed ledgers — the ``/stats``
  ``attribution`` block;
* **SLO burn-rate gauges**: rolling error-budget burn over a fast and a
  slow window (the Google-SRE multi-window pattern; burn 1.0 = spending
  exactly the budget, >1 = on track to exhaust it);
* **slow-request structured log**: any request above the threshold logs
  ONE JSON line with its id and full ledger (and lands in the flight
  ring), so the p99 tail is attributable post-hoc without a debugger;
* **per-bucket service-time profiles**: device-exec histograms +
  pad-ratio + flush-reason mix per bucket size, persisted as a
  versioned ``dpt_serve_profile`` v1 artifact — the calibration input
  the ROADMAP's ``plan-serve`` discrete-event capacity planner needs
  (measured service times per bucket are exactly what a queue
  simulation replays arrival traces against).

Hot-path contract (dptlint ``obs-hot-path``, like the rest of ``obs/``):
``mark_*`` calls on the dispatch path are attribute/dict assignments
only; ``record_*``/``complete`` run on completion workers (the
sanctioned drain context) and append only to bounded rings. ``DPT_OBS=0``
disables request tracing entirely (the overhead A/B lever —
docs/OBSERVABILITY.md states the measured delta).

Stdlib-only and jax-free, like the rest of ``obs/``.
"""

from __future__ import annotations

import collections
import itertools
import json
import logging
import os
import re
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from distributedpytorch_tpu.obs.registry import nearest_rank

logger = logging.getLogger(__name__)

#: Artifact identity (the planner-file idiom, analysis/planner.py):
#: consumers refuse anything else — a stale or foreign file must never
#: silently calibrate a capacity plan.
PROFILE_KIND = "dpt_serve_profile"
PROFILE_VERSION = 1


class ProfileMismatchError(ValueError):
    """A ``dpt_serve_profile`` that loaded fine but was measured against
    a DIFFERENT serving configuration (bucket ladder or engine/model
    identity) than the one being planned for. Deliberately loud — the
    missing/corrupt case degrades to None, but a *mismatched* profile
    would calibrate a plan with numbers from the wrong engine, which is
    worse than no plan at all."""

#: Lifecycle marks, in order. A span is the gap between two consecutive
#: PRESENT marks, named after the LATER mark's phase (table below) — so
#: the ledger is contiguous and its durations sum to resolved − ingress
#: exactly, whatever subset of marks a request collected.
EVENTS = ("ingress", "enqueued", "flushed", "placed", "dispatched",
          "device_done", "resolved")

#: Span name for the gap that ENDS at each mark.
PHASE_FOR_EVENT = {
    "enqueued": "decode",
    "flushed": "queue_wait",
    "placed": "placement",
    "dispatched": "dispatch_wait",
    "device_done": "device_exec",
    "resolved": "drain",
}

PHASES = ("decode", "queue_wait", "placement", "dispatch_wait",
          "device_exec", "drain")

#: Device-exec histogram ladder for the per-bucket profiles: serving
#: service times live well under the generic registry ladder's tail.
SERVICE_TIME_BOUNDS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0,
)

_TRACEPARENT_RE = re.compile(
    r"^[0-9a-f]{2}-([0-9a-f]{32})-[0-9a-f]{16}-[0-9a-f]{2}$"
)
#: Accepted shape for a client-supplied ``X-Request-Id``: the id is
#: echoed back as a response HEADER and written into grep-able logs and
#: flight-ring records, so anything outside this charset (CR/LF above
#: all — header injection) is refused and a server-assigned id used.
_REQUEST_ID_RE = re.compile(r"^[A-Za-z0-9._:-]{1,128}$")

_REQ_SEQ = itertools.count()
#: Per-process id prefix so ids stay unique across a fleet of workers
#: (two workers' counters would otherwise collide in one merged pane).
_REQ_PREFIX = f"{os.getpid():x}-{int(time.time() * 1e3) & 0xFFFFFF:x}"


def _obs_enabled() -> bool:
    return os.environ.get("DPT_OBS", "1").lower() not in ("0", "off", "false")


def new_request_id() -> str:
    """A fleet-unique request id: process prefix + per-process counter
    (no RNG on the ingress path; ids only need uniqueness, not
    unpredictability)."""
    return f"{_REQ_PREFIX}-{next(_REQ_SEQ):06x}"


def parse_traceparent(header: Optional[str]) -> Optional[str]:
    """The trace-id of a W3C ``traceparent`` header
    (``00-<32hex>-<16hex>-<2hex>``), or None when absent/malformed —
    a bad header must not reject the request, only lose the caller's
    correlation."""
    if not header:
        return None
    m = _TRACEPARENT_RE.match(header.strip().lower())
    return m.group(1) if m else None


def request_id_from_headers(headers) -> Optional[str]:
    """Ingress id resolution: an inbound W3C ``traceparent`` trace-id
    wins (cross-service correlation), else an explicit ``X-Request-Id``
    — accepted only when it matches the safe-id charset (the id is
    echoed back as a response header and logged verbatim; a CR/LF or
    control character would be header/log injection) — else None (the
    server assigns). ``headers`` is any .get-able."""
    rid = parse_traceparent(headers.get("traceparent"))
    if rid:
        return rid
    rid = headers.get("X-Request-Id")
    if rid:
        rid = str(rid).strip()
        if not _REQUEST_ID_RE.match(rid):
            return None
    return rid or None


class RequestTrace:
    """One request's span ledger: lifecycle marks stamped by the serve
    pipeline (attribute/dict assignment only — safe on the dispatch hot
    path), spans derived at completion. All timestamps come from the
    server's injectable clock, so fake-clock tests pin attribution
    deterministically."""

    __slots__ = ("request_id", "marks", "flush_reason", "bucket", "status")

    def __init__(self, request_id: str, t_ingress: float):
        self.request_id = request_id
        self.marks: Dict[str, float] = {"ingress": float(t_ingress)}
        self.flush_reason: Optional[str] = None
        self.bucket: Optional[int] = None
        self.status: Optional[str] = None

    # -- lifecycle marks (hot-path safe: assignments only) -------------------
    def mark(self, event: str, t: float) -> None:
        self.marks[event] = float(t)

    def mark_flushed(self, t: float, reason: str, bucket: int) -> None:
        self.marks["flushed"] = float(t)
        self.flush_reason = reason
        self.bucket = int(bucket)

    # -- derivation ----------------------------------------------------------
    def spans(self) -> Dict[str, float]:
        """Contiguous per-phase durations (seconds). Present marks only;
        sums to ``resolved − ingress`` exactly when both exist."""
        out: Dict[str, float] = {}
        prev_t = self.marks.get("ingress")
        if prev_t is None:
            return out
        for event in EVENTS[1:]:
            t = self.marks.get(event)
            if t is None:
                continue
            out[PHASE_FOR_EVENT[event]] = max(0.0, t - prev_t)
            prev_t = t
        return out

    def latency_s(self) -> Optional[float]:
        t0 = self.marks.get("ingress")
        t1 = self.marks.get("resolved")
        if t0 is None or t1 is None:
            return None
        return max(0.0, t1 - t0)

    def ledger(self, spans: Optional[Dict[str, float]] = None,
               latency_s: Optional[float] = None) -> dict:
        """The completed-request record the ring keeps (and the slow-
        request log emits): id, status, flush provenance, span ms.
        ``spans``/``latency_s`` accept precomputed values so the
        completion path derives them exactly once."""
        spans = self.spans() if spans is None else spans
        lat = self.latency_s() if latency_s is None else latency_s
        return {
            "request_id": self.request_id,
            "status": self.status,
            "flush": self.flush_reason,
            "bucket": self.bucket,
            "latency_ms": round(lat * 1e3, 3) if lat is not None else None,
            "spans_ms": {k: round(v * 1e3, 3) for k, v in spans.items()},
        }


class _BurnWindow:
    """O(1) rolling good/bad counts over the last ``window_s`` seconds:
    one ring bucket per second, expired buckets zeroed as the clock
    advances — no per-request allocation, fake-clock friendly (every
    timestamp is passed in)."""

    __slots__ = ("window_s", "_good", "_bad", "_sec", "good", "bad")

    def __init__(self, window_s: float):
        n = max(1, int(window_s))
        self.window_s = float(n)
        self._good = [0] * n
        self._bad = [0] * n
        self._sec: Optional[int] = None  # current second, or None
        self.good = 0
        self.bad = 0

    def _advance(self, t: float) -> None:
        sec = int(t)
        n = len(self._good)
        if self._sec is None:
            self._sec = sec
            return
        if sec <= self._sec:
            return  # same second (or a fake clock standing still)
        steps = min(sec - self._sec, n)
        for k in range(1, steps + 1):
            i = (self._sec + k) % n
            self.good -= self._good[i]
            self.bad -= self._bad[i]
            self._good[i] = 0
            self._bad[i] = 0
        self._sec = sec

    def add(self, t: float, bad: bool) -> None:
        self._advance(t)
        i = int(t) % len(self._good)
        if bad:
            self._bad[i] += 1
            self.bad += 1
        else:
            self._good[i] += 1
            self.good += 1

    def error_fraction(self, t: float) -> Optional[float]:
        self._advance(t)
        total = self.good + self.bad
        if total == 0:
            return None
        return self.bad / total


class _BucketProfile:
    """Per-bucket service-time accumulator: exact cumulative device-exec
    histogram + pad accounting + flush-reason mix, plus a bounded
    quantile window (the registry-histogram discipline)."""

    __slots__ = ("bounds", "counts", "sum_s", "count", "window",
                 "real_rows", "pad_rows", "flush_reasons")

    def __init__(self, window: int = 512):
        self.bounds = SERVICE_TIME_BOUNDS
        self.counts = [0] * (len(self.bounds) + 1)  # last = +Inf
        self.sum_s = 0.0
        self.count = 0
        self.window: collections.deque = collections.deque(maxlen=window)
        self.real_rows = 0
        self.pad_rows = 0
        # bounded by construction: the four flush regimes
        self.flush_reasons: Dict[str, int] = {}

    def record(self, device_exec_s: float, bucket: int, real_rows: int,
               flush_reason: Optional[str]) -> None:
        v = float(device_exec_s)
        i = 0
        for i, bound in enumerate(self.bounds):  # noqa: B007 — tiny ladder
            if v <= bound:
                break
        else:
            i = len(self.bounds)
        self.counts[i] += 1
        self.sum_s += v
        self.count += 1
        self.window.append(v)
        self.real_rows += int(real_rows)
        self.pad_rows += max(0, int(bucket) - int(real_rows))
        if flush_reason:
            self.flush_reasons[flush_reason] = (
                self.flush_reasons.get(flush_reason, 0) + 1
            )

    def _quantile(self, q: float) -> Optional[float]:
        window = sorted(self.window)
        if not window:
            return None
        return nearest_rank(window, q)

    def payload(self) -> dict:
        dispatched = self.real_rows + self.pad_rows
        cumulative: List[List[float]] = []
        running = 0
        for bound, c in zip(self.bounds, self.counts[:-1]):
            running += c
            cumulative.append([bound, running])
        cumulative.append(["+Inf", running + self.counts[-1]])
        return {
            "dispatches": self.count,
            "device_exec_s": {
                "count": self.count,
                "sum": round(self.sum_s, 6),
                "mean": round(self.sum_s / self.count, 6) if self.count else None,
                "p50": self._quantile(50),
                "p99": self._quantile(99),
                "cumulative_buckets": cumulative,
            },
            "real_rows": self.real_rows,
            "pad_rows": self.pad_rows,
            "pad_ratio": (
                round(self.pad_rows / dispatched, 4) if dispatched else 0.0
            ),
            "flush_reasons": dict(sorted(self.flush_reasons.items())),
        }


def _percentile(values: List[float], q: float) -> Optional[float]:
    return nearest_rank(sorted(values), q) if values else None


class ReqTracer:
    """Per-server request-trace aggregator (the ``ServeMetrics`` shape:
    one per Server object, recording from completion workers and the
    ingress path — never the dispatch loop).

    ``latency_slo_s`` is the end-to-end "good request" bound the burn
    windows judge against (default 2× the batching SLO: the batching
    wait plus a comparable service allowance); ``slow_s`` is the
    structured-log threshold (default 2× ``latency_slo_s``).
    ``slo_target`` is the availability objective — burn rate =
    error_fraction / (1 − slo_target).
    """

    def __init__(
        self,
        slo_s: float = 0.05,
        latency_slo_s: Optional[float] = None,
        slow_s: Optional[float] = None,
        slo_target: float = 0.99,
        clock: Callable[[], float] = time.monotonic,
        window: int = 2048,
        fast_window_s: float = 60.0,
        slow_window_s: float = 600.0,
        timeline=None,
    ):
        self.enabled = _obs_enabled()
        # label children resolved ONCE (a .labels() lookup per phase per
        # request would dominate the record cost at serving rates)
        if self.enabled:
            from distributedpytorch_tpu.obs import defs as obsm

            self._phase_obs = {
                p: obsm.SERVE_PHASE_SECONDS.labels(phase=p) for p in PHASES
            }
            self._burn_fast_gauge = obsm.SERVE_SLO_BURN_FAST
            self._burn_slow_gauge = obsm.SERVE_SLO_BURN_SLOW
            self._slow_counter = obsm.SERVE_SLOW_REQUESTS
            self._exec_obs: Dict[int, object] = {}
        self.slo_s = float(slo_s)
        self.latency_slo_s = (
            float(latency_slo_s) if latency_slo_s is not None
            else 2.0 * self.slo_s
        )
        self.slow_s = (
            float(slow_s) if slow_s is not None else 2.0 * self.latency_slo_s
        )
        self.slo_target = min(max(float(slo_target), 0.0), 0.9999)
        self.clock = clock
        self.timeline = timeline  # utils/trace.StepTimeline or None
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(maxlen=window)
        self._slow = 0
        self._completed = 0
        self._fast = _BurnWindow(fast_window_s)
        self._slow_win = _BurnWindow(slow_window_s)
        self._profiles: Dict[int, _BucketProfile] = {}

    # -- ingress -------------------------------------------------------------
    def begin(self, request_id: Optional[str] = None,
              t: Optional[float] = None) -> Optional[RequestTrace]:
        """A new per-request trace, or None when tracing is disabled
        (``DPT_OBS=0``) — every downstream mark site guards on None."""
        if not self.enabled:
            return None
        return RequestTrace(
            request_id or new_request_id(),
            self.clock() if t is None else t,
        )

    # -- completion (completion workers / ingress rejection paths) -----------
    def complete(self, trace: Optional[RequestTrace], status: str,
                 t: Optional[float] = None) -> None:
        """Close a trace: derive its ledger, feed the attribution ring,
        the burn windows, the per-phase registry histograms, the slow-
        request log, and (when armed) the timeline JSONL."""
        if trace is None or not self.enabled:
            return
        now = self.clock() if t is None else t
        if "resolved" not in trace.marks:
            trace.mark("resolved", now)
        trace.status = status
        spans = trace.spans()
        latency = trace.latency_s() or 0.0
        served = status == "ok"
        bad = status in ("error", "rejected") or (
            served and latency > self.latency_slo_s
        )
        slow = served and latency >= self.slow_s
        if not served and "device_done" not in trace.marks:
            # an unserved request's trailing gap (ingress/admit →
            # rejection/error resolve) must not masquerade as a `drain`
            # span — a shed storm would read as a slice/threshold
            # bottleneck in the ring and on the timeline
            if "drain" in spans:
                spans["unserved"] = spans.pop("drain")
        ledger = trace.ledger(spans=spans, latency_s=latency)
        with self._lock:
            self._ring.append(ledger)
            self._completed += 1
            if slow:
                self._slow += 1
            self._fast.add(now, bad)
            self._slow_win.add(now, bad)
            budget = 1.0 - self.slo_target
            fast_frac = self._fast.error_fraction(now)
            slow_frac = self._slow_win.error_fraction(now)
        if served:
            phase_obs = self._phase_obs
            for phase, dur in spans.items():
                phase_obs[phase].observe(dur)
        if fast_frac is not None:
            self._burn_fast_gauge.set(fast_frac / budget)
        if slow_frac is not None:
            self._burn_slow_gauge.set(slow_frac / budget)
        if slow:
            self._slow_counter.inc()
            # ONE structured line per slow request: grep-able, and the
            # flight ring keeps the tail for post-mortems
            logger.warning("slow request: %s", json.dumps(ledger))
            from distributedpytorch_tpu.obs import flight

            flight.record("slow_request", **ledger)
        if served:
            # only served requests export phase spans to the timeline:
            # a shed's pseudo-span on the Perfetto pane would point the
            # post-mortem at the wrong phase (its story is the
            # request_reject flight record instead)
            self._export_spans(trace)

    def _export_spans(self, trace: RequestTrace) -> None:
        """Feed the armed timeline (Perfetto via obs/trace_hub.py): one
        span per phase, wall-anchored backwards from now so phases of
        one request line up contiguously on the fleet timeline."""
        timeline = self.timeline
        if timeline is None or not trace.marks.get("resolved"):
            return
        wall_now = time.time()
        t_res = trace.marks["resolved"]
        prev = trace.marks.get("ingress")
        for event in EVENTS[1:]:
            t = trace.marks.get(event)
            if t is None or prev is None:
                continue
            timeline.record(
                PHASE_FOR_EVENT[event], prev, t,
                wall=wall_now - (t_res - t),
                request_id=trace.request_id,
                **({"flush": trace.flush_reason, "bucket": trace.bucket}
                   if event == "flushed" else {}),
            )
            prev = t

    def reject(self, trace: Optional[RequestTrace], reason: str,
               request_id: str = "", t: Optional[float] = None,
               **fields) -> None:
        """A shed/rejected request: stamp id + reason into the flight
        ring (the post-mortem can then name WHICH requests were shed and
        why — counters alone cannot) and burn error budget."""
        from distributedpytorch_tpu.obs import flight

        rid = trace.request_id if trace is not None else request_id
        flight.record("request_reject", request_id=rid, reason=reason,
                      **fields)
        self.complete(trace, "rejected", t=t)

    def record_dispatch(self, bucket: int, real_rows: int,
                        device_exec_s: float,
                        flush_reason: Optional[str]) -> None:
        """One dispatched group's service-time observation (called from
        the completion drain, once per bucket execution)."""
        if not self.enabled:
            return
        b = int(bucket)
        with self._lock:
            prof = self._profiles.get(b)
            if prof is None:
                # bounded by construction: one entry per ladder bucket
                prof = self._profiles[b] = _BucketProfile()
            prof.record(device_exec_s, b, real_rows, flush_reason)
        child = self._exec_obs.get(b)
        if child is None:
            from distributedpytorch_tpu.obs import defs as obsm

            # setdefault: _exec_obs is read OUTSIDE the lock, so a racing
            # first dispatch on this bucket must not drop a child
            child = self._exec_obs.setdefault(
                b, obsm.SERVE_DEVICE_EXEC.labels(bucket=str(b))
            )
        child.observe(float(device_exec_s))

    def refresh_burn_gauges(self, t: Optional[float] = None) -> None:
        """Re-derive the burn gauges from the CURRENT window contents.
        ``complete()`` updates them per request, which means they would
        freeze at the last computed value once traffic stops (an error
        burst's 5.0 burn would page forever after the LB drains the
        worker) — the serve front calls this on every ``/metrics`` and
        ``/stats`` read so scraped values decay with the windows."""
        if not self.enabled:
            return
        now = self.clock() if t is None else t
        with self._lock:
            budget = 1.0 - self.slo_target
            fast_frac = self._fast.error_fraction(now)
            slow_frac = self._slow_win.error_fraction(now)
        # an EMPTY window reads burn 0 (nothing erring now), not stale
        self._burn_fast_gauge.set(
            fast_frac / budget if fast_frac is not None else 0.0
        )
        self._burn_slow_gauge.set(
            slow_frac / budget if slow_frac is not None else 0.0
        )

    # -- aggregation (pull-based) -------------------------------------------
    def recent(self, limit: Optional[int] = None) -> List[dict]:
        """The newest completed ledgers (oldest→newest) — the exemplar
        lookup path: given a p99 exemplar id from ``/stats``, find its
        full span ledger here (or in the slow-request log)."""
        with self._lock:
            ledgers = list(self._ring)
        return ledgers if limit is None else ledgers[-int(limit):]

    def snapshot_attribution(self, exemplars: Optional[List[str]] = None,
                             t: Optional[float] = None) -> dict:
        """The ``/stats`` ``attribution`` block: per-phase percentiles
        over the completed ring (served requests only), slow-request
        count, burn-rate state, and the p99 window's exemplar trace ids
        (computed by ServeMetrics over its latency window and passed
        in — one latency story, not two)."""
        now = self.clock() if t is None else t
        with self._lock:
            ledgers = [d for d in self._ring if d.get("status") == "ok"]
            slow = self._slow
            completed = self._completed
            budget = 1.0 - self.slo_target
            fast_frac = self._fast.error_fraction(now)
            slow_frac = self._slow_win.error_fraction(now)
        if self.enabled:
            # keep the gauges in step with this (decayed) view — /stats
            # and /metrics must tell one burn story
            self._burn_fast_gauge.set(
                fast_frac / budget if fast_frac is not None else 0.0
            )
            self._burn_slow_gauge.set(
                slow_frac / budget if slow_frac is not None else 0.0
            )
        per_phase: Dict[str, List[float]] = {p: [] for p in PHASES}
        for d in ledgers:
            for phase, ms in d.get("spans_ms", {}).items():
                if phase in per_phase:
                    per_phase[phase].append(ms)
        phases = {}
        for phase in PHASES:
            vals = per_phase[phase]
            phases[phase] = (
                None if not vals else {
                    "count": len(vals),
                    "p50_ms": round(_percentile(vals, 50), 3),
                    "p95_ms": round(_percentile(vals, 95), 3),
                    "p99_ms": round(_percentile(vals, 99), 3),
                }
            )
        return {
            "phases": phases,
            "completed": completed,
            "slow_requests": slow,
            "slow_threshold_ms": round(self.slow_s * 1e3, 3),
            "p99_exemplars": list(exemplars or []),
            "slo_burn": {
                "target": self.slo_target,
                "latency_slo_ms": round(self.latency_slo_s * 1e3, 3),
                "fast_window_s": self._fast.window_s,
                "slow_window_s": self._slow_win.window_s,
                "fast": (
                    round(fast_frac / budget, 4)
                    if fast_frac is not None else None
                ),
                "slow": (
                    round(slow_frac / budget, 4)
                    if slow_frac is not None else None
                ),
            },
        }

    def phase_medians_ms(self) -> Dict[str, Optional[float]]:
        """Per-phase p50s in ms (bench_serve's per-leg calibration row)."""
        snap = self.snapshot_attribution()
        return {
            phase: (info["p50_ms"] if info else None)
            for phase, info in snap["phases"].items()
        }

    def profile_payload(
        self, phase_medians_ms: Optional[Dict[str, Optional[float]]] = None,
        **meta,
    ) -> dict:
        """The ``dpt_serve_profile`` v1 payload: per-bucket service-time
        profiles + the phase medians, stamped with whatever run metadata
        the caller provides (geometry, replicas, SLO). Pass
        ``phase_medians_ms`` when the caller already snapshotted them
        (bench_serve's per-leg row) — one consistent snapshot in the
        row and the artifact, and no second O(ring) aggregation."""
        with self._lock:
            buckets = {
                str(b): prof.payload()
                for b, prof in sorted(self._profiles.items())
            }
        return {
            "kind": PROFILE_KIND,
            "version": PROFILE_VERSION,
            "created_unix": round(time.time(), 3),
            "slo_ms": round(self.slo_s * 1e3, 3),
            "latency_slo_ms": round(self.latency_slo_s * 1e3, 3),
            "phase_medians_ms": (
                phase_medians_ms if phase_medians_ms is not None
                else self.phase_medians_ms()
            ),
            "buckets": buckets,
            **meta,
        }


# -- profile-artifact IO (the planner-file idiom; jax-free) ------------------
def engine_fingerprint(model_arch: str = "unet",
                       image_size=(960, 640),
                       model_widths=None,
                       s2d_levels: int = -1,
                       quantize: Optional[str] = None,
                       kernels: str = "xla") -> str:
    """A short stable hash of the serve engine's MODEL identity — the
    fields of ``ServeConfig`` that change what the device executes (and
    therefore the service times a profile measures). Stamped into every
    ``dpt_serve_profile`` and cross-checked by the ``plan-serve``
    planner: a profile measured on a different arch / resolution /
    quantization must refuse to calibrate a plan, loudly.

    Every value here is a CONCRETE identity, defaults included:
    ``model_widths=None`` means the arch's built-in default widths (the
    serve path never resolves widths from checkpoint metadata — a
    wrong-widths checkpoint fails loudly at load), so two engines
    fingerprint equal iff they execute the same program shape. Callers
    must pass the same flags they serve with, exactly as predict.py's
    identity flags work."""
    import hashlib

    blob = json.dumps({
        "model_arch": str(model_arch),
        "image_size": [int(s) for s in image_size],
        "model_widths": (
            [int(w) for w in model_widths] if model_widths else None
        ),
        "s2d_levels": int(s2d_levels),
        "quantize": quantize,
        "kernels": str(kernels),
    }, sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


def check_profile(payload: dict,
                  expect_buckets=None,
                  expect_fingerprint: Optional[str] = None) -> None:
    """The staleness guard: raise :class:`ProfileMismatchError` when the
    profile's recorded bucket ladder / engine fingerprint disagree with
    what the caller is about to plan for. An expectation the profile
    cannot answer (no recorded field) is ALSO a refusal — "unverifiable"
    must not read as "verified"."""
    if expect_buckets is not None:
        recorded = payload.get("bucket_sizes")
        expected = [int(b) for b in expect_buckets]
        if recorded is None:
            raise ProfileMismatchError(
                "profile records no bucket ladder — cannot verify it "
                f"matches the serving ladder {expected} (re-profile with "
                "a current bench_serve)"
            )
        if [int(b) for b in recorded] != expected:
            raise ProfileMismatchError(
                f"profile was measured on bucket ladder {recorded} but "
                f"the serving config uses {expected} — a plan calibrated "
                "from it would predict the wrong shapes; re-profile"
            )
    if expect_fingerprint is not None:
        recorded = payload.get("engine_fingerprint")
        if recorded is None:
            raise ProfileMismatchError(
                "profile records no engine fingerprint — cannot verify "
                f"it matches engine {expect_fingerprint} (re-profile "
                "with a current bench_serve)"
            )
        if str(recorded) != str(expect_fingerprint):
            raise ProfileMismatchError(
                f"profile was measured on engine {recorded} but the "
                f"serving config fingerprints as {expect_fingerprint} "
                "(different model/resolution/quantization/kernels) — "
                "its service times do not describe this engine; "
                "re-profile"
            )


def save_profile(payload: dict, path: str) -> str:
    """Atomic write of a ``dpt_serve_profile`` payload; returns ``path``."""
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=2)
    os.replace(tmp, path)
    return path


def load_profile(path: Optional[str],
                 expect_buckets=None,
                 expect_fingerprint: Optional[str] = None) -> Optional[dict]:
    """The profile, or None (with a logged note) for missing / corrupt /
    version-skewed files — consumers (the ``plan-serve`` capacity
    planner) degrade to uncalibrated defaults on None; a torn or stale
    artifact must never silently calibrate a plan.

    ``expect_buckets`` / ``expect_fingerprint`` arm the staleness guard
    (:func:`check_profile`): a profile that loads but was measured
    against a different bucket ladder or engine identity raises
    :class:`ProfileMismatchError` — loudly, because a MISMATCHED
    calibration is worse than a missing one."""
    if not path:
        return None
    try:
        with open(path) as f:
            payload = json.load(f)
    except (OSError, ValueError) as exc:
        logger.warning("serve profile %r unreadable (%s) — ignored",
                       path, type(exc).__name__)
        return None
    if (
        not isinstance(payload, dict)
        or payload.get("kind") != PROFILE_KIND
        or payload.get("version") != PROFILE_VERSION
        or not isinstance(payload.get("buckets"), dict)
    ):
        logger.warning(
            "serve profile %r is not a %s v%d artifact — ignored (stale "
            "or foreign file)", path, PROFILE_KIND, PROFILE_VERSION,
        )
        return None
    check_profile(payload, expect_buckets=expect_buckets,
                  expect_fingerprint=expect_fingerprint)
    return payload
