"""Trace hub: rank-tagged step-timeline spans → Perfetto/Chrome trace
JSON, merged across ranks.

``utils/trace.py`` records host-side phase spans (decode / stack / h2d /
dispatch / readback) as JSONL; this module turns those into the Chrome
trace-event format (``{"traceEvents": [...]}``) that Perfetto and
``chrome://tracing`` open directly, with one **process track per rank**
and one **thread track per phase** — so a 2-rank elastic run reads as
two aligned lanes of overlapping phase bars instead of two unrelated
JSONL files.

Cross-rank alignment: span ``t0``/``t1`` are ``time.perf_counter``
values (arbitrary per-process origin — the right clock *within* a
process); every span also carries a wall-clock stamp (``wall``, written
at record time), and the exporter anchors each span at
``wall − (t1 − t0)``. Wall clocks on one host are shared, so ranks of a
multi-process CPU/gloo job land on one comparable axis.

The elastic supervisor (``dist/elastic.py``) arms ``--trace-timeline``
per worker (rank 0 writes ``<path>``, rank R writes ``<path>.rankR``)
and calls :func:`write_merged_trace` over the attempt's files when the
job resolves. For device-side profiles, the trainer's
``--profile-steps N:M`` captures a ``jax.profiler`` trace over exactly
that step range (train/loop.py) — this module stays host-side and
jax-free.
"""

from __future__ import annotations

import glob
import json
import logging
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple, Union

logger = logging.getLogger(__name__)

_RANK_SUFFIX_RE = re.compile(r"\.rank(\d+)$")

#: Stable thread-track ids for the known phases (unknown phases get
#: ids after these, in first-seen order).
_PHASE_ORDER = ("decode", "stack", "h2d", "dispatch", "readback")


def _load_events(path: str) -> List[dict]:
    # utils.trace.load_events without the import (obs stays standalone;
    # the format — JSONL of {"phase", "t0", "t1", ...} — is the contract)
    events: List[dict] = []
    try:
        with open(path) as f:
            for line in f:
                if not line.strip():
                    continue
                try:
                    d = json.loads(line)
                except ValueError:
                    continue  # torn tail line of a crashed writer
                if isinstance(d, dict) and "phase" in d:
                    events.append(d)
    except OSError:
        return []
    return events


def _phase_tid(phase: str, extra: Dict[str, int]) -> int:
    if phase in _PHASE_ORDER:
        return _PHASE_ORDER.index(phase)
    if phase not in extra:
        extra[phase] = len(_PHASE_ORDER) + len(extra)
    return extra[phase]


def _anchor_us(e: dict) -> Optional[float]:
    """Absolute start time of a span in µs (wall-anchored when the span
    carries a wall stamp; bare perf_counter otherwise)."""
    try:
        t0, t1 = float(e["t0"]), float(e["t1"])
    except (KeyError, TypeError, ValueError):
        return None
    wall = e.get("wall")
    if wall is not None:
        try:
            return (float(wall) - (t1 - t0)) * 1e6
        except (TypeError, ValueError):
            pass
    return t0 * 1e6


def trace_events_from_spans(
    spans: Iterable[dict], default_rank: int = 0,
) -> List[dict]:
    """Chrome 'X' (complete) events from step-timeline span dicts. Each
    span's rank tag (or ``default_rank``) becomes the pid/track."""
    out: List[dict] = []
    extra_tids: Dict[str, int] = {}
    for e in spans:
        ts = _anchor_us(e)
        if ts is None:
            continue
        dur = max(0.0, (float(e["t1"]) - float(e["t0"])) * 1e6)
        rank = int(e.get("rank", default_rank))
        phase = str(e["phase"])
        args = {
            k: v for k, v in e.items()
            if k not in ("phase", "t0", "t1", "wall", "rank")
        }
        out.append({
            "name": phase,
            "cat": "step",
            "ph": "X",
            "ts": round(ts, 3),
            "dur": round(dur, 3),
            "pid": rank,
            "tid": _phase_tid(phase, extra_tids),
            "args": args,
        })
    return out


def _metadata_events(ranks: Sequence[int], phases: Sequence[str],
                     process_label: str = "rank") -> List[dict]:
    meta: List[dict] = []
    extra_tids: Dict[str, int] = {}
    for rank in sorted(set(ranks)):
        meta.append({
            "ph": "M", "name": "process_name", "pid": rank, "tid": 0,
            "args": {"name": f"{process_label} {rank}"},
        })
        for phase in phases:
            meta.append({
                "ph": "M", "name": "thread_name", "pid": rank,
                "tid": _phase_tid(phase, extra_tids),
                "args": {"name": phase},
            })
    return meta


def build_trace(spans_by_rank: Dict[int, List[dict]],
                process_label: str = "rank") -> dict:
    """One Perfetto-loadable trace from per-rank span lists, events
    sorted by timestamp (Perfetto tolerates unsorted input; humans
    diffing the JSON do not). ``process_label`` names the per-process
    tracks — "rank" for training jobs, "worker" for the serve fleet's
    merged pane."""
    events: List[dict] = []
    phases: List[str] = []
    for rank, spans in sorted(spans_by_rank.items()):
        for e in spans:
            p = str(e.get("phase", ""))
            if p and p not in phases:
                phases.append(p)
        events.extend(trace_events_from_spans(spans, default_rank=rank))
    events.sort(key=lambda e: (e["ts"], e["pid"], e["tid"]))
    meta = _metadata_events(list(spans_by_rank), phases,
                            process_label=process_label)
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def timeline_rank_paths(base_path: str) -> List[Tuple[int, str]]:
    """The per-rank timeline files of one run: rank 0 writes
    ``<base>``, rank R writes ``<base>.rankR`` (utils/trace wiring in
    train/loop.py). Only files that exist are returned."""
    out: List[Tuple[int, str]] = []
    if os.path.exists(base_path):
        out.append((0, base_path))
    for path in sorted(glob.glob(f"{base_path}.rank*")):
        m = _RANK_SUFFIX_RE.search(path)
        if m:
            out.append((int(m.group(1)), path))
    return out


def merge_timelines(
    paths: Union[str, Sequence[Union[str, Tuple[int, str]]]],
    process_label: str = "rank",
) -> dict:
    """Merge timeline JSONL files into one trace. ``paths`` may be a
    base path (rank files discovered via :func:`timeline_rank_paths`),
    a list of paths (rank inferred from the ``.rankN`` suffix, the
    events' own rank tags, else 0), or explicit ``(rank, path)``
    pairs."""
    if isinstance(paths, str):
        pairs = timeline_rank_paths(paths)
    else:
        pairs = []
        for item in paths:
            if isinstance(item, tuple):
                pairs.append((int(item[0]), str(item[1])))
            else:
                m = _RANK_SUFFIX_RE.search(str(item))
                pairs.append((int(m.group(1)) if m else 0, str(item)))
    by_rank: Dict[int, List[dict]] = {}
    for rank, path in pairs:
        events = _load_events(path)
        for e in events:
            r = int(e.get("rank", rank))
            by_rank.setdefault(r, []).append(e)
    return build_trace(by_rank, process_label=process_label)


def write_merged_trace(
    paths: Union[str, Sequence[Union[str, Tuple[int, str]]]],
    out_path: str,
    process_label: str = "rank",
) -> Optional[str]:
    """Merge + write; returns ``out_path``, or None when no events were
    found (no empty artifacts). Never raises — callers are teardown
    paths (the elastic supervisor's report step)."""
    try:
        trace = merge_timelines(paths, process_label=process_label)
        if not any(e["ph"] == "X" for e in trace["traceEvents"]):
            return None
        os.makedirs(os.path.dirname(os.path.abspath(out_path)),
                    exist_ok=True)
        tmp = f"{out_path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(trace, f)
        os.replace(tmp, out_path)
        return out_path
    except Exception:  # noqa: BLE001 — diagnostic artifact only
        logger.exception("merged-trace write failed")
        return None
