"""Metric family catalog — one place, created eagerly at import.

Every family the train loop, the serve tier, and the elastic supervisor
record into is defined HERE, against the process-wide registry, so any
``/metrics`` endpoint in any process exposes the full catalog (families
a given process never touches expose at zero / header-only — the
Prometheus-idiomatic shape, and what the acceptance check "exposition
covering train, serve, and supervisor metric families" keys on).

Import as ``from distributedpytorch_tpu.obs import defs as obsm`` —
the ``obsm.`` prefix is what dptlint's ``obs-hot-path`` rule matches
when checking that no metric update happens inside a jit/shard_map-
traced function (docs/ANALYSIS.md).

The full catalog with semantics lives in docs/OBSERVABILITY.md.
"""

from __future__ import annotations

from distributedpytorch_tpu.obs.registry import REGISTRY
from distributedpytorch_tpu.obs.reqtrace import SERVICE_TIME_BOUNDS

# -- train (recorded by train/loop.py + utils/metrics.py at drain
#    boundaries — never on the dispatch hot path) ---------------------------
TRAIN_STEPS = REGISTRY.counter(
    "dpt_train_steps_total", "Optimizer steps completed")
TRAIN_IMAGES = REGISTRY.counter(
    "dpt_train_images_total", "Training images consumed")
TRAIN_LOSS = REGISTRY.gauge(
    "dpt_train_loss", "Last drained mean-of-window train loss")
TRAIN_VAL_LOSS = REGISTRY.gauge(
    "dpt_train_val_loss", "Last epoch validation loss")
TRAIN_VAL_DICE = REGISTRY.gauge(
    "dpt_train_val_dice", "Last epoch validation Dice")
TRAIN_STEP_SECONDS = REGISTRY.histogram(
    "dpt_train_step_seconds",
    "Host-observed step-loop iteration time (dispatch cadence, not "
    "device latency)",
    buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
             5.0, 10.0, 30.0, 60.0, 300.0),
)
TRAIN_IMGS_PER_S = REGISTRY.gauge(
    "dpt_train_imgs_per_s", "Steady-state training throughput")
TRAIN_RETRIES = REGISTRY.counter(
    "dpt_train_retries_total",
    "Bounded-backoff retries of transient host failures", ("site",))
TRAIN_ROLLBACKS = REGISTRY.counter(
    "dpt_train_rollbacks_total",
    "Checkpoint rollbacks consumed by the non-finite-loss policy")
TRAIN_SKIPPED_STEPS = REGISTRY.counter(
    "dpt_train_skipped_steps_total",
    "Updates discarded by the non-finite-loss 'skip' policy")
CACHE_HITS = REGISTRY.counter(
    "dpt_host_cache_hits_total", "Decoded-sample cache hits")
CACHE_MISSES = REGISTRY.counter(
    "dpt_host_cache_misses_total", "Decoded-sample cache misses")
CACHE_HIT_RATIO = REGISTRY.gauge(
    "dpt_host_cache_hit_ratio", "Decoded-sample cache hit rate [0, 1]")

# -- serve (recorded by serve/metrics.py off the dispatch loop) -------------
SERVE_REQUESTS = REGISTRY.counter(
    "dpt_serve_requests_total", "Requests resolved", ("status",))
SERVE_IMAGES = REGISTRY.counter(
    "dpt_serve_images_total", "Images served successfully")
SERVE_REJECTIONS = REGISTRY.counter(
    "dpt_serve_rejections_total", "Requests rejected at admission",
    ("reason",))
SERVE_DISPATCHES = REGISTRY.counter(
    "dpt_serve_dispatches_total", "Bucket executables dispatched",
    ("bucket",))
SERVE_PAD_ROWS = REGISTRY.counter(
    "dpt_serve_pad_rows_total", "Pad rows dispatched")
SERVE_REAL_ROWS = REGISTRY.counter(
    "dpt_serve_real_rows_total", "Real rows dispatched")
SERVE_FLUSHES = REGISTRY.counter(
    "dpt_serve_queue_flushes_total",
    "Batching-queue flush decisions by regime "
    "(full/deadline/eager/shed)", ("kind",))
SERVE_QUEUE_DEPTH = REGISTRY.gauge(
    "dpt_serve_queue_depth_images", "Pending images in the batching queue")
SERVE_LATENCY = REGISTRY.histogram(
    "dpt_serve_latency_seconds", "Request latency, admission to response",
    buckets=(0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
             10.0, 30.0),
)
SERVE_QUEUE_SECONDS = REGISTRY.histogram(
    "dpt_serve_queue_seconds", "Queueing delay, admission to dispatch",
    buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
             5.0),
)
SERVE_PREDICT_CACHE = REGISTRY.counter(
    "dpt_serve_predict_cache_total",
    "Clipper-style prediction-cache lookups (exact-match on the "
    "decoded-input hash)", ("result",))
SERVE_CORE_RESTARTS = REGISTRY.counter(
    "dpt_serve_core_restarts_total",
    "In-process dispatch-core relaunches after a dispatch-loop death")
SERVE_WEIGHTS_VERSION = REGISTRY.gauge(
    "dpt_serve_weights_version",
    "Weights version promoted to every replica group (0 = the "
    "startup checkpoint)")
SERVE_ROLLOUTS = REGISTRY.counter(
    "dpt_serve_rollouts_total",
    "Weight-rollout attempts by outcome "
    "(promoted/rolled_back/swap_failed/load_failed)", ("outcome",))
SERVE_ROLLOUT_CANARY = REGISTRY.gauge(
    "dpt_serve_rollout_canary",
    "1 while a rollout canary is being health-watched, else 0")
SERVE_REPLICA_HINT = REGISTRY.gauge(
    "dpt_serve_replica_hint",
    "Recommended replica count from queue-depth/shed hysteresis "
    "(the signal serve/scaler.py actuates)")
SERVE_REPLICAS = REGISTRY.gauge(
    "dpt_serve_replicas",
    "Live replica-group size (moved without restart by the "
    "autoscaler — serve/scaler.py)")
SERVE_SCALE_EVENTS = REGISTRY.counter(
    "dpt_serve_scale_events_total",
    "Autoscaler actuations on the live replica group, each citing the "
    "plan-serve grid point it executes", ("direction",))
SERVE_AB_REQUESTS = REGISTRY.counter(
    "dpt_serve_ab_requests_total",
    "Sustained-A/B requests by arm and resolution (server-side view; "
    "the router's ledger discards hedge losers)", ("arm", "status"))
SERVE_AB_ACTIVE = REGISTRY.gauge(
    "dpt_serve_ab_active",
    "1 while a sustained A/B pins two weight versions to disjoint "
    "replica groups, else 0")
AOT_CACHE = REGISTRY.counter(
    "dpt_aot_cache_total",
    "AOT executable store events (utils/aotstore.py): hit = loaded a "
    "serialized executable (zero compiles), miss = no entry "
    "(compile-and-persist), skew = entry present but corrupt or "
    "runtime/identity-skewed (refused loudly, recompiled), evicted = "
    "removed by `aot gc`", ("result",))

# -- request tracing (obs/reqtrace.py; recorded from completion workers
#    and ingress rejection paths — never the dispatch loop) -----------------
# one ladder (reqtrace.SERVICE_TIME_BOUNDS) for both: these histograms
# and the dpt_serve_profile artifact must describe the SAME
# distribution, or planner calibration drifts from what /metrics shows
SERVE_PHASE_SECONDS = REGISTRY.histogram(
    "dpt_serve_phase_seconds",
    "Per-request phase attribution from the span ledger "
    "(decode/queue_wait/placement/dispatch_wait/device_exec/drain)",
    ("phase",),
    buckets=SERVICE_TIME_BOUNDS,
)
SERVE_DEVICE_EXEC = REGISTRY.histogram(
    "dpt_serve_device_exec_seconds",
    "Host-observed device execution time per bucket size (the "
    "per-bucket service-time profile the capacity planner calibrates "
    "against)",
    ("bucket",),
    buckets=SERVICE_TIME_BOUNDS,
)
SERVE_SLOW_REQUESTS = REGISTRY.counter(
    "dpt_serve_slow_requests_total",
    "Requests above the slow-request threshold (each one structured-"
    "logged with its full span ledger and request id)")
SERVE_SLO_BURN_FAST = REGISTRY.gauge(
    "dpt_serve_slo_burn_fast",
    "Error-budget burn rate over the fast window (1.0 = spending "
    "exactly the budget; >1 = on track to exhaust it)")
SERVE_SLO_BURN_SLOW = REGISTRY.gauge(
    "dpt_serve_slo_burn_slow",
    "Error-budget burn rate over the slow window")

# -- router front door (recorded by serve/router.py; jax-free) --------------
ROUTER_REQUESTS = REGISTRY.counter(
    "dpt_router_requests_total",
    "Front-door requests by final client-visible HTTP code (transparent "
    "retries collapse into one row here)", ("code",))
ROUTER_RETRIES = REGISTRY.counter(
    "dpt_router_retries_total",
    "Transparent resubmissions to a sibling worker "
    "(connection = dead worker ejected mid-request, shed = 503 honored)",
    ("reason",))
ROUTER_HEDGES = REGISTRY.counter(
    "dpt_router_hedges_total",
    "Hedged duplicate requests past the p99 deadline, by which copy "
    "answered the client (primary/hedge) — the loser is cancelled and "
    "never counted as a request", ("winner",))
ROUTER_WORKER_EVENTS = REGISTRY.counter(
    "dpt_router_worker_events_total",
    "Worker-pool transitions (eject on connection failure, readmit on "
    "/healthz readiness, stale on a missed stats scrape)", ("event",))
ROUTER_HEALTHY_WORKERS = REGISTRY.gauge(
    "dpt_router_healthy_workers", "Workers currently in the routable pool")
ROUTER_HA_EVENTS = REGISTRY.counter(
    "dpt_router_ha_events_total",
    "Active/standby pair transitions (takeover = standby promoted "
    "itself after the active missed a probe, demote = a router yielded "
    "the active role to a higher-epoch peer, sync = standby imported "
    "the active's /admin/state snapshot)", ("event",))

# -- elastic supervisor (recorded by dist/elastic.py; jax-free) -------------
ELASTIC_RESTARTS = REGISTRY.counter(
    "dpt_elastic_restarts_total", "Supervisor relaunches of the job")
ELASTIC_WORLD_SIZE = REGISTRY.gauge(
    "dpt_elastic_world_size", "Ranks in the current/last attempt")
ELASTIC_RANK_FAILURES = REGISTRY.counter(
    "dpt_elastic_rank_failures_total",
    "Per-rank failure verdicts across attempts", ("failure_class",))
ELASTIC_ATTEMPTS = REGISTRY.counter(
    "dpt_elastic_attempts_total", "Launch attempts by outcome",
    ("outcome",))
FLEET_SCALE_EVENTS = REGISTRY.counter(
    "dpt_fleet_scale_events_total",
    "Supervisor-level fleet actuations: whole serve workers spawned or "
    "retired (dist/elastic.py FleetScaler), each citing the plan-serve "
    "grid point it executes — the process-level sibling of "
    "dpt_serve_scale_events_total", ("direction",))

# -- obs itself -------------------------------------------------------------
FLIGHT_DUMPS = REGISTRY.counter(
    "dpt_flight_dumps_total", "Flight-recorder artifacts written",
    ("reason_class",))
