"""Process-wide metrics registry with Prometheus text exposition.

One registry per process (module singleton :data:`REGISTRY`), three
metric kinds, all label-aware:

* **Counter** — monotonically increasing float (``inc``); exact under
  concurrency (each child guards its read-modify-write with a tiny
  per-child lock — the cost is one uncontended lock acquire, cheap
  enough for completion workers and the metrics drain, and the registry
  is never touched from the device-dispatch hot path: dptlint's
  ``obs-hot-path`` rule enforces that scope).
* **Gauge** — settable float (``set``/``inc``).
* **Histogram** — fixed cumulative buckets (Prometheus semantics:
  ``le`` bounds, ``_sum``, ``_count`` are exact counters) plus a
  **bounded** sample window (``deque(maxlen=...)``) for host-side
  quantile snapshots — a long-running process must not grow memory per
  observation (the same discipline as ``ServeMetrics``' latency
  window).

Exposition is the Prometheus text format, version 0.0.4
(``expose()``); :func:`validate_exposition` is the strict line-format
checker the tests and the CI smoke step run against it — a malformed
escape or an inconsistent histogram fails loudly instead of silently
dropping a scrape.

Metric families are *created idempotently*: asking for an existing name
with the same kind/labels returns the existing family (trainers and
servers are constructed many times per test process), while a
conflicting re-registration raises.

Deliberately stdlib-only and jax-free: the elastic supervisor (a
jax-free process by design) and the serve HTTP front share this module.
"""

from __future__ import annotations

import bisect
import collections
import math
import re
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

#: Prometheus' default histogram ladder, widened with a 30/60 s tail
#: (cold-compile steps and SLO drains both live out there).
DEFAULT_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
    30.0, 60.0,
)

#: Quantile-window bound per histogram child (snapshot quantiles only —
#: bucket counts and sums stay exact for the process lifetime).
DEFAULT_WINDOW = 2048


def nearest_rank(ordered: Sequence[float], q: float) -> float:
    """Nearest-rank percentile (q in [0, 100]) over an ALREADY-SORTED
    non-empty sequence — THE quantile definition shared by histogram
    windows, ``ServeMetrics``, and the request tracer (one definition,
    or the /stats p99 and the profile artifact's p99 would drift).
    Callers own sorting and the empty case."""
    rank = max(0, min(len(ordered) - 1,
                      round(q / 100.0 * (len(ordered) - 1))))
    return ordered[int(rank)]


def _format_value(v: float) -> str:
    """Prometheus sample value: integral floats render as integers
    (counters read naturally), everything else as repr(float)."""
    f = float(v)
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape_label_value(v: str) -> str:
    return v.replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


def _escape_help(v: str) -> str:
    return v.replace("\\", r"\\").replace("\n", r"\n")


class _Child:
    """One (labelvalues) series of a counter/gauge family."""

    __slots__ = ("_lock", "_value", "_monotonic")

    def __init__(self, monotonic: bool):
        self._lock = threading.Lock()
        self._value = 0.0
        self._monotonic = monotonic

    def inc(self, amount: float = 1.0) -> None:
        if self._monotonic and amount < 0:
            raise ValueError(f"counter increments must be >= 0, got {amount}")
        with self._lock:
            self._value += amount

    def set(self, value: float) -> None:
        if self._monotonic:
            raise TypeError("counters only go up — use inc()")
        with self._lock:
            self._value = float(value)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class _HistogramChild:
    """One series of a histogram family: exact cumulative bucket counts
    plus a bounded quantile window."""

    __slots__ = ("_lock", "bounds", "_bucket_counts", "_sum", "_count",
                 "_window")

    def __init__(self, bounds: Tuple[float, ...], window: int):
        self._lock = threading.Lock()
        self.bounds = bounds
        self._bucket_counts = [0] * (len(bounds) + 1)  # last = +Inf
        self._sum = 0.0
        self._count = 0
        self._window: collections.deque = collections.deque(maxlen=window)

    def observe(self, value: float) -> None:
        v = float(value)
        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self._bucket_counts[i] += 1
            self._sum += v
            self._count += 1
            self._window.append(v)

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def cumulative_buckets(self) -> List[Tuple[str, int]]:
        """[("le-bound", cumulative count), ..., ("+Inf", total)]."""
        with self._lock:
            counts = list(self._bucket_counts)
        out: List[Tuple[str, int]] = []
        running = 0
        for bound, c in zip(self.bounds, counts[:-1]):
            running += c
            out.append((_format_value(bound), running))
        out.append(("+Inf", running + counts[-1]))
        return out

    def quantile(self, q: float) -> Optional[float]:
        """Nearest-rank quantile over the bounded window (None when
        nothing was observed). Snapshot-path only — sorts O(window)."""
        with self._lock:
            window = list(self._window)
        if not window:
            return None
        return nearest_rank(sorted(window), q)


class Family:
    """A named metric family: labelled children or one default child."""

    def __init__(self, name: str, help_text: str, kind: str,
                 labelnames: Tuple[str, ...],
                 buckets: Tuple[float, ...] = DEFAULT_BUCKETS,
                 window: int = DEFAULT_WINDOW):
        self.name = name
        self.help = help_text
        self.kind = kind  # "counter" | "gauge" | "histogram"
        self.labelnames = labelnames
        self.buckets = tuple(sorted(float(b) for b in buckets))
        self.window = int(window)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}
        if not labelnames:
            self._children[()] = self._make_child()

    def _make_child(self):
        if self.kind == "histogram":
            return _HistogramChild(self.buckets, self.window)
        return _Child(monotonic=self.kind == "counter")

    def labels(self, *values, **kv):
        """Child for one label-value combination (created on first use)."""
        if kv:
            if values:
                raise ValueError("pass label values positionally OR by name")
            try:
                values = tuple(str(kv[name]) for name in self.labelnames)
            except KeyError as exc:
                raise ValueError(
                    f"{self.name} expects labels {self.labelnames}"
                ) from exc
            if len(kv) != len(self.labelnames):
                raise ValueError(
                    f"{self.name} expects labels {self.labelnames}, "
                    f"got {sorted(kv)}"
                )
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name} expects {len(self.labelnames)} label "
                f"value(s), got {len(values)}"
            )
        child = self._children.get(values)
        if child is None:
            with self._lock:
                child = self._children.setdefault(values, self._make_child())
        return child

    # unlabeled conveniences ------------------------------------------------
    def _default(self):
        if self.labelnames:
            raise ValueError(
                f"{self.name} is labelled {self.labelnames} — use .labels()"
            )
        return self._children[()]

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def set(self, value: float) -> None:
        self._default().set(value)

    def observe(self, value: float) -> None:
        self._default().observe(value)

    @property
    def value(self) -> float:
        return self._default().value

    def collect(self) -> List[Tuple[Tuple[str, ...], object]]:
        with self._lock:
            return sorted(self._children.items())

    def as_dict(self) -> Dict[str, float]:
        """{label-values-joined: value} — JSON-snapshot convenience for
        counters/gauges (``ServeMetrics`` rebuilds its /stats maps from
        this)."""
        out: Dict[str, float] = {}
        for values, child in self.collect():
            key = ",".join(values)
            out[key] = child.value  # type: ignore[attr-defined]
        return out


class MetricsRegistry:
    """Registry of families; see module docstring."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, Family] = {}

    def _register(self, name: str, help_text: str, kind: str,
                  labelnames: Sequence[str],
                  buckets: Tuple[float, ...] = DEFAULT_BUCKETS,
                  window: int = DEFAULT_WINDOW) -> Family:
        if not _NAME_RE.match(name):
            raise ValueError(f"bad metric name {name!r}")
        labelnames = tuple(labelnames)
        for ln in labelnames:
            if not _LABEL_RE.match(ln) or ln.startswith("__"):
                raise ValueError(f"bad label name {ln!r} for {name!r}")
        with self._lock:
            existing = self._families.get(name)
            if existing is not None:
                if existing.kind != kind or existing.labelnames != labelnames:
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.kind}{existing.labelnames}, cannot "
                        f"re-register as {kind}{labelnames}"
                    )
                return existing
            fam = Family(name, help_text, kind, labelnames,
                         buckets=buckets, window=window)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help_text: str,
                labelnames: Sequence[str] = ()) -> Family:
        return self._register(name, help_text, "counter", labelnames)

    def gauge(self, name: str, help_text: str,
              labelnames: Sequence[str] = ()) -> Family:
        return self._register(name, help_text, "gauge", labelnames)

    def histogram(self, name: str, help_text: str,
                  labelnames: Sequence[str] = (),
                  buckets: Tuple[float, ...] = DEFAULT_BUCKETS,
                  window: int = DEFAULT_WINDOW) -> Family:
        return self._register(name, help_text, "histogram", labelnames,
                              buckets=buckets, window=window)

    def get(self, name: str) -> Optional[Family]:
        with self._lock:
            return self._families.get(name)

    def families(self) -> List[Family]:
        with self._lock:
            return [self._families[k] for k in sorted(self._families)]

    # -- exposition ----------------------------------------------------------
    def expose(self) -> str:
        """Prometheus text exposition (version 0.0.4), all families.
        Label-less families always emit one sample (0 until touched);
        labelled families emit one sample per child seen so far — the
        HELP/TYPE header is emitted either way, so a scraper (and the
        acceptance check) sees every family the process defines."""
        lines: List[str] = []
        for fam in self.families():
            lines.append(f"# HELP {fam.name} {_escape_help(fam.help)}")
            lines.append(f"# TYPE {fam.name} {fam.kind}")
            for values, child in fam.collect():
                labels = ",".join(
                    f'{n}="{_escape_label_value(v)}"'
                    for n, v in zip(fam.labelnames, values)
                )
                if fam.kind == "histogram":
                    for le, cum in child.cumulative_buckets():  # type: ignore
                        le_label = (
                            f'{labels},le="{le}"' if labels else f'le="{le}"'
                        )
                        lines.append(
                            f"{fam.name}_bucket{{{le_label}}} {cum}"
                        )
                    suffix = f"{{{labels}}}" if labels else ""
                    lines.append(
                        f"{fam.name}_sum{suffix} "
                        f"{_format_value(child.sum)}"  # type: ignore
                    )
                    lines.append(
                        f"{fam.name}_count{suffix} {child.count}"  # type: ignore
                    )
                else:
                    suffix = f"{{{labels}}}" if labels else ""
                    lines.append(
                        f"{fam.name}{suffix} "
                        f"{_format_value(child.value)}"  # type: ignore
                    )
        return "\n".join(lines) + "\n"


#: Prometheus text-format content type (what /metrics responds with).
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# -- strict exposition checker (tests + CI smoke) ---------------------------

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>(?:[a-zA-Z_][a-zA-Z0-9_]*="
    r"\"(?:[^\"\\\n]|\\[\\\"n])*\",?)*)\})?"
    r" (?P<value>[+-]?(?:[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?|Inf|NaN))"
    r"(?: (?P<ts>-?[0-9]+))?$"
)
_HELP_RE = re.compile(r"^# HELP ([a-zA-Z_:][a-zA-Z0-9_:]*) (.*)$")
_TYPE_RE = re.compile(
    r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) "
    r"(counter|gauge|histogram|summary|untyped)$"
)


def validate_exposition(text: str) -> Dict[str, str]:
    """Strictly check Prometheus text exposition; returns
    ``{family_name: type}``. Raises ``ValueError`` naming the first bad
    line. Beyond per-line grammar it checks family-level invariants:
    a sample must follow its family's ``# TYPE``; histogram children
    must end their bucket ladder at ``le="+Inf"`` with the +Inf count
    equal to ``_count`` and cumulative counts non-decreasing."""
    types: Dict[str, str] = {}
    # histogram bookkeeping: (family, labelset-minus-le) -> state
    buckets: Dict[Tuple[str, str], List[Tuple[float, float]]] = {}
    counts: Dict[Tuple[str, str], float] = {}
    for i, line in enumerate(text.splitlines(), start=1):
        if line == "":
            continue
        if line.startswith("#"):
            m = _HELP_RE.match(line)
            if m:
                continue
            m = _TYPE_RE.match(line)
            if m:
                name, kind = m.group(1), m.group(2)
                if name in types:
                    raise ValueError(f"line {i}: duplicate TYPE for {name}")
                types[name] = kind
                continue
            raise ValueError(f"line {i}: malformed comment: {line!r}")
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"line {i}: malformed sample line: {line!r}")
        name = m.group("name")
        family = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[: -len(suffix)] in types:
                family = name[: -len(suffix)]
                break
        if family not in types:
            raise ValueError(
                f"line {i}: sample {name!r} precedes its # TYPE line"
            )
        labels = m.group("labels") or ""
        value = float(m.group("value").replace("Inf", "inf"))
        if types[family] == "histogram" and name == f"{family}_bucket":
            le = None
            rest = []
            for pair in filter(None, _split_labels(labels)):
                k, _, v = pair.partition("=")
                if k == "le":
                    le = v.strip('"')
                else:
                    rest.append(pair)
            if le is None:
                raise ValueError(
                    f"line {i}: histogram bucket without le label"
                )
            key = (family, ",".join(rest))
            bound = float("inf") if le == "+Inf" else float(le)
            series = buckets.setdefault(key, [])
            if series and bound <= series[-1][0]:
                raise ValueError(
                    f"line {i}: bucket bounds not increasing for {family}"
                )
            if series and value < series[-1][1]:
                raise ValueError(
                    f"line {i}: cumulative bucket counts decreased "
                    f"for {family}"
                )
            series.append((bound, value))
        elif types[family] == "histogram" and name == f"{family}_count":
            counts[(family, labels)] = value
    for (family, labelset), series in buckets.items():
        if not series or series[-1][0] != float("inf"):
            raise ValueError(
                f"histogram {family}{{{labelset}}} has no le=\"+Inf\" bucket"
            )
        total = counts.get((family, labelset))
        if total is not None and series[-1][1] != total:
            raise ValueError(
                f"histogram {family}{{{labelset}}}: +Inf bucket "
                f"{series[-1][1]} != _count {total}"
            )
    return types


def _split_labels(labels: str) -> Iterable[str]:
    """Split a validated label body on commas outside quotes."""
    out: List[str] = []
    depth_quote = False
    cur = []
    i = 0
    while i < len(labels):
        ch = labels[i]
        if ch == "\\" and depth_quote:
            cur.append(labels[i:i + 2])
            i += 2
            continue
        if ch == '"':
            depth_quote = not depth_quote
        if ch == "," and not depth_quote:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
        i += 1
    if cur:
        out.append("".join(cur))
    return out


# -- fleet-pane exposition merge (the elastic serve supervisor) -------------

def merge_expositions(primary: str, workers: Dict[str, str],
                      label_name: str = "worker") -> str:
    """One Prometheus exposition from a primary process's text plus N
    scraped worker texts, each worker's samples re-labeled with
    ``label_name="<worker>"`` — the elastic serve supervisor's fleet
    pane: one scrape target for the whole shared-nothing fleet, every
    family emitted ONCE (``# TYPE`` twice is a format violation) with
    the supervisor's own unlabeled samples alongside the worker-labeled
    ones.

    Worker texts that fail to parse are skipped whole (a scrape that
    raced a dying worker must not poison the merged pane); the primary
    text is trusted (it comes from :meth:`MetricsRegistry.expose`).
    """
    def _parse(text: str, worker: Optional[str]):
        """(family, kind, help, sample) tuples; raises on any malformed
        line so a torn worker scrape is rejected WHOLE."""
        seen_types: Dict[str, str] = {}
        out = []
        for line in text.splitlines():
            if not line:
                continue
            m = _HELP_RE.match(line)
            if m:
                out.append((m.group(1), None, m.group(2), None))
                continue
            m = _TYPE_RE.match(line)
            if m:
                seen_types[m.group(1)] = m.group(2)
                out.append((m.group(1), m.group(2), None, None))
                continue
            m = _SAMPLE_RE.match(line)
            if m is None:
                raise ValueError(f"malformed sample line: {line!r}")
            name = m.group("name")
            family = name
            for suffix in ("_bucket", "_sum", "_count"):
                base = name[: -len(suffix)] if name.endswith(suffix) else None
                if base and base in seen_types:
                    family = base
                    break
            if worker is None:
                sample = line
            else:
                labels = m.group("labels")
                tag = f'{label_name}="{_escape_label_value(worker)}"'
                body = f"{tag},{labels}" if labels else tag
                sample = f"{name}{{{body}}} {m.group('value')}"
            out.append((family, None, None, sample))
        return out

    families: Dict[str, Dict[str, object]] = {}
    order: List[str] = []

    def _commit(parsed) -> None:
        for family, kind, help_text, sample in parsed:
            fam = families.setdefault(
                family, {"help": None, "type": None, "samples": []}
            )
            if family not in order:
                order.append(family)
            if help_text is not None and fam["help"] is None:
                fam["help"] = help_text
            if kind is not None and fam["type"] is None:
                fam["type"] = kind
            if sample is not None:
                fam["samples"].append(sample)  # type: ignore[union-attr]

    _commit(_parse(primary, None))
    for worker, text in sorted(workers.items()):
        try:
            parsed = _parse(text, worker)
        except ValueError:
            # torn scrape (worker died mid-write): drop this worker's
            # contribution whole, keep the pane serving
            continue
        _commit(parsed)
    lines: List[str] = []
    for name in order:
        fam = families[name]
        if fam["help"] is not None:
            lines.append(f"# HELP {name} {fam['help']}")
        if fam["type"] is not None:
            lines.append(f"# TYPE {name} {fam['type']}")
        lines.extend(fam["samples"])  # type: ignore[arg-type]
    return "\n".join(lines) + "\n"


#: The process-wide registry every subsystem records into.
REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return REGISTRY
