"""Data subsystem: filesystem-paired segmentation datasets + sharded loading.

TPU-native replacement for the reference's torch `Dataset`/`DataLoader`/
`DistributedSampler` stack (reference utils/dataloading.py, train_utils.py
:40-42, :189-191): numpy-producing datasets, a deterministic seeded split, a
per-process sharding sampler with working per-epoch reshuffle, threaded
host-side prefetch, and NHWC batches ready for `jax.device_put`.
"""

from distributedpytorch_tpu.data.dataset import (  # noqa: F401
    BasicDataset,
    CarvanaDataset,
    SampleCache,
    SyntheticSegmentationDataset,
    build_dataset,
    write_synthetic_carvana_tree,
)
from distributedpytorch_tpu.data.loader import (  # noqa: F401
    DataLoader,
    ShardSpec,
    seeded_split,
)
