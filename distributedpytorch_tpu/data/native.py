"""ctypes binding for the native data-loading runtime (native/dpt_data.cpp).

The reference decodes with PIL inside torch DataLoader worker processes
(reference utils/dataloading.py:44-52, utils/train_utils.py:40). Here the
hot path is one C call per *batch*: JPEG/PNG/GIF decode, Pillow-parity
BICUBIC/NEAREST resize, /255 normalize, and NHWC assembly all happen in
C++ threads (native/dpt_data.cpp dpt_load_batch) with no Python in the
per-image loop.

Everything degrades gracefully: if the shared library is absent and cannot
be built (no toolchain, no libjpeg/libpng), `get_lib()` returns None and
callers (data/dataset.py, data/loader.py) fall back to the PIL path. A
missing or broken native layer must never make the package unimportable —
that failure mode cost round 2 everything (VERDICT.md round 2).
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from os.path import splitext
from typing import List, Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger(__name__)

# Formats dpt_data.cpp can decode (decode_file, native/dpt_data.cpp:264-287).
_SUPPORTED_EXTS = {".jpg", ".jpeg", ".png", ".gif"}

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), os.pardir, "native")
_LIB_PATH = os.path.abspath(os.path.join(_NATIVE_DIR, "libdpt_data.so"))

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_attempted = False


def supports(path: str) -> bool:
    """True if the native decoder handles this file's format."""
    return splitext(str(path))[1].lower() in _SUPPORTED_EXTS


def _build() -> bool:
    """Build libdpt_data.so on demand via the Makefile. Best-effort."""
    makefile = os.path.join(_NATIVE_DIR, "Makefile")
    if not os.path.exists(makefile):
        return False
    try:
        proc = subprocess.run(
            ["make", "-C", os.path.abspath(_NATIVE_DIR), "libdpt_data.so"],
            capture_output=True,
            text=True,
            timeout=120,
        )
    except (OSError, subprocess.TimeoutExpired) as exc:  # no make / hang
        logger.info("native build unavailable: %s", exc)
        return False
    if proc.returncode != 0:
        logger.info("native build failed:\n%s", proc.stderr[-2000:])
        return False
    return os.path.exists(_LIB_PATH)


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.dpt_load_item.restype = ctypes.c_int
    lib.dpt_load_item.argtypes = [
        ctypes.c_char_p,  # img_path (nullable)
        ctypes.c_char_p,  # mask_path (nullable)
        ctypes.c_int,  # out_w
        ctypes.c_int,  # out_h
        ctypes.c_void_p,  # float* img_out
        ctypes.c_void_p,  # int32* mask_out
    ]
    lib.dpt_load_batch.restype = ctypes.c_int
    lib.dpt_load_batch.argtypes = [
        ctypes.POINTER(ctypes.c_char_p),  # img_paths (nullable)
        ctypes.POINTER(ctypes.c_char_p),  # mask_paths (nullable)
        ctypes.c_int,  # n
        ctypes.c_int,  # out_w
        ctypes.c_int,  # out_h
        ctypes.c_int,  # n_threads
        ctypes.c_void_p,  # float* imgs_out
        ctypes.c_void_p,  # int32* masks_out
    ]
    lib.dpt_version.restype = ctypes.c_char_p
    return lib


def get_lib() -> Optional[ctypes.CDLL]:
    """dlopen the native library, building it first if needed.

    Returns None (and remembers the failure) when the library can't be
    produced — callers then use the pure-Python PIL path.
    """
    global _lib, _lib_attempted
    if _lib is not None or _lib_attempted:
        return _lib
    with _lock:
        if _lib is not None or _lib_attempted:
            return _lib
        _lib_attempted = True
        if not os.path.exists(_LIB_PATH) and not _build():
            logger.info("native data loader unavailable; using PIL path")
            return None
        try:
            _lib = _bind(ctypes.CDLL(_LIB_PATH))
            logger.info(
                "native data loader: %s", _lib.dpt_version().decode()
            )
        except OSError as exc:
            logger.info("native library failed to load: %s", exc)
            _lib = None
        return _lib


def load_item(
    img_path: Optional[str],
    mask_path: Optional[str],
    out_w: int,
    out_h: int,
) -> Tuple[Optional[np.ndarray], Optional[np.ndarray]]:
    """Decode + preprocess one image/mask pair into NHWC numpy.

    Returns (image (H,W,3) float32 in [0,1] or None, mask (H,W) int32 or
    None) matching BasicDataset.preprocess (data/dataset.py:86-105).
    """
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native library unavailable")
    img = (
        np.empty((out_h, out_w, 3), dtype=np.float32)
        if img_path is not None
        else None
    )
    mask = (
        np.empty((out_h, out_w), dtype=np.int32)
        if mask_path is not None
        else None
    )
    rc = lib.dpt_load_item(
        img_path.encode() if img_path is not None else None,
        mask_path.encode() if mask_path is not None else None,
        int(out_w),
        int(out_h),
        img.ctypes.data if img is not None else None,
        mask.ctypes.data if mask is not None else None,
    )
    if rc != 0:
        which = img_path if rc == 1 else mask_path
        raise RuntimeError(f"native decode failed (rc={rc}): {which}")
    return img, mask


def load_batch(
    img_paths: Optional[Sequence[str]],
    mask_paths: Optional[Sequence[str]],
    out_w: int,
    out_h: int,
    n_threads: int = 4,
) -> Tuple[Optional[np.ndarray], Optional[np.ndarray]]:
    """Assemble a whole batch in one C call (thread pool inside,
    native/dpt_data.cpp dpt_load_batch).

    Returns (images (N,H,W,3) float32, masks (N,H,W) int32); either is None
    when its path list is None.
    """
    lib = get_lib()
    if lib is None:
        raise RuntimeError("native library unavailable")
    n = len(img_paths) if img_paths is not None else len(mask_paths)
    if mask_paths is not None and img_paths is not None:
        assert len(img_paths) == len(mask_paths)

    def _c_paths(paths: Optional[Sequence[str]]):
        if paths is None:
            return None
        arr = (ctypes.c_char_p * n)(*[p.encode() for p in paths])
        return arr

    imgs = (
        np.empty((n, out_h, out_w, 3), dtype=np.float32)
        if img_paths is not None
        else None
    )
    masks = (
        np.empty((n, out_h, out_w), dtype=np.int32)
        if mask_paths is not None
        else None
    )
    rc = lib.dpt_load_batch(
        _c_paths(img_paths),
        _c_paths(mask_paths),
        n,
        int(out_w),
        int(out_h),
        int(n_threads),
        imgs.ctypes.data if imgs is not None else None,
        masks.ctypes.data if masks is not None else None,
    )
    if rc != 0:
        i = rc - 100
        which: List[str] = []
        if img_paths is not None:
            which.append(img_paths[i])
        if mask_paths is not None:
            which.append(mask_paths[i])
        raise RuntimeError(f"native decode failed (rc={rc}): {which}")
    return imgs, masks
