"""Deterministic split + sharded, prefetching batch loader.

Replaces the reference's `random_split` + `DataLoader` + `DistributedSampler`
stack (reference utils/train_utils.py:35-42, :185-191) with host-side numpy
machinery sized for a JAX trainer:

  * `seeded_split` — ONE deterministic split shared by every strategy and
    every process. This deliberately fixes reference quirk 5 (SURVEY.md §2):
    the reference's DDP path splits with a differently-seeded generator than
    its single/DP paths, so val curves were never comparable across methods.
  * `ShardSpec` — DistributedSampler-equivalent per-process sharding: pad the
    sample list to a multiple of world size by wrapping around (exactly what
    torch's DistributedSampler does), then stride by rank.
  * `DataLoader` — per-epoch reshuffle driven by (seed, epoch); the epoch is
    an argument to `epoch_batches`, which structurally fixes the reference's
    missing `sampler.set_epoch` (SURVEY.md §3.2) — you cannot forget to pass
    it. Decodes items with a thread pool (the torch `num_workers=1` process
    boundary, train_utils.py:40, becomes threads: PIL decode releases the
    GIL) and assembles NHWC batches.
"""

from __future__ import annotations

import dataclasses
import logging
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Iterator, Optional, Sequence, Tuple

import numpy as np

from distributedpytorch_tpu.utils import faults
from distributedpytorch_tpu.utils.trace import NULL_TIMELINE

logger = logging.getLogger(__name__)

Batch = Dict[str, np.ndarray]


def seeded_split(
    n: int, val_fraction: float, seed: int = 0
) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic (train_indices, val_indices) split.

    `n_val = int(n * val_fraction)` matches the reference's
    ``int(len(dataset) * val_percent/100)`` rounding (train_utils.py:35-36).
    """
    n_val = int(n * val_fraction)
    perm = np.random.default_rng(seed).permutation(n)
    return perm[n_val:], perm[:n_val]


@dataclasses.dataclass(frozen=True)
class ShardSpec:
    """Which contiguous-strided shard of each (padded) epoch this process owns.

    rank/world mirror `DistributedSampler(dataset, num_replicas, rank)`
    (reference train_utils.py:189): pad by wrap-around so every rank sees the
    same number of samples, then take indices[rank::world].
    """

    rank: int = 0
    world: int = 1

    def __post_init__(self):
        if not 0 <= self.rank < self.world:
            raise ValueError(f"rank {self.rank} out of range for world {self.world}")

    def shard(self, order: np.ndarray) -> np.ndarray:
        if self.world == 1:
            return order
        total = -(-len(order) // self.world) * self.world  # ceil to multiple
        # repeat the whole list as many times as needed (order can be shorter
        # than the padding when world > len(order)), then truncate — torch
        # DistributedSampler semantics: every rank gets exactly total/world
        reps = -(-total // len(order))
        padded = np.concatenate([order] * reps)[:total]
        return padded[self.rank :: self.world]


class DataLoader:
    """Batched, optionally sharded, thread-prefetched iterator over a dataset.

    `dataset` is anything with `__len__` and `__getitem__` returning
    ``{'image': (H,W,C) f32, 'mask': (H,W) i32}`` (see data/dataset.py).
    """

    def __init__(
        self,
        dataset,
        indices: Optional[Sequence[int]] = None,
        batch_size: int = 4,
        shuffle: bool = False,
        drop_last: bool = False,
        seed: int = 0,
        shard: ShardSpec = ShardSpec(),
        num_workers: int = 0,
        cache=None,
        tracer=None,
        max_retries: int = 0,
        retry_backoff_s: float = 0.05,
    ):
        self.dataset = dataset
        self.indices = (
            np.arange(len(dataset)) if indices is None else np.asarray(indices)
        )
        self.batch_size = int(batch_size)
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.seed = seed
        self.shard_spec = shard
        self.num_workers = int(num_workers)
        # transient decode failures (OSError family: disk/network reads,
        # PIL on torn files — and the injected `decode` fault) retry with
        # bounded exponential backoff before surfacing (utils/faults.py)
        self.max_retries = int(max_retries)
        self.retry_backoff_s = float(retry_backoff_s)
        # epoch-persistent decoded-sample cache (data/dataset.SampleCache),
        # shared across loaders of the same dataset (train + val): epochs
        # >= 2 serve whatever fit the budget from host memory, skipping
        # decode entirely
        self.cache = cache
        self.tracer = tracer or NULL_TIMELINE
        self._pool = (
            ThreadPoolExecutor(max_workers=self.num_workers)
            if self.num_workers > 0
            else None
        )

    def __len__(self) -> int:
        """Batches per epoch for this shard."""
        n = self.num_samples()
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)

    def num_samples(self) -> int:
        """Samples per epoch in this process's shard (before drop_last)."""
        return len(self.shard_spec.shard(self.indices))

    def steps_per_epoch(self) -> int:
        return len(self)

    def _epoch_order(self, epoch: int) -> np.ndarray:
        order = self.indices
        if self.shuffle:
            # (seed, epoch)-keyed reshuffle — identical on every process, so
            # shards stay disjoint; varies per epoch, fixing the reference's
            # missing set_epoch (SURVEY.md §3.2).
            rng = np.random.default_rng((self.seed, epoch))
            order = rng.permutation(order)
        return self.shard_spec.shard(order)

    def _load_batch(self, idx_list, epoch: Optional[int] = None,
                    batch_idx: Optional[int] = None) -> Batch:
        """Assemble one batch with bounded-backoff retries on transient
        failures; ``(epoch, batch_idx)`` (when the caller knows them) are
        the `decode` fault-injection site's coordinates."""
        return faults.call_with_retries(
            lambda: self._assemble_batch(idx_list),
            site="decode",
            retries=self.max_retries,
            backoff_s=self.retry_backoff_s,
            epoch=epoch,
            step=batch_idx,
            log=logger,
        )

    def _assemble_batch(self, idx_list) -> Batch:
        """Assemble one batch, serving cached samples from host memory and
        decoding only the misses (traced as the pipeline's ``decode``
        phase — on a warm cache the span collapses to stack-only time)."""
        with self.tracer.span("decode", n=len(idx_list)):
            if self.cache is None:
                return self._decode_batch(idx_list)
            items = {int(i): self.cache.get(int(i)) for i in idx_list}
            missing = [i for i, it in items.items() if it is None]
            if missing:
                fresh = self._decode_batch(missing)
                for row, i in enumerate(missing):
                    item = {
                        "image": fresh["image"][row],
                        "mask": fresh["mask"][row],
                    }
                    self.cache.put(i, item)
                    items[i] = item
                if len(missing) == len(idx_list):
                    # nothing came from cache and indices were unique
                    # (len matches): fresh IS the batch, already in idx
                    # order — the steady state of a full cache must not
                    # pay a redundant split + re-stack per batch
                    return fresh
            return {
                "image": np.stack([items[int(i)]["image"] for i in idx_list]),
                "mask": np.stack([items[int(i)]["mask"] for i in idx_list]),
            }

    def _decode_batch(self, idx_list) -> Batch:
        """Decode one batch from the backing dataset; uses the native C++
        whole-batch path (decode + resize + normalize, threaded in C, see
        data/native.py) when the dataset is filesystem-backed with
        supported formats."""
        ds = self.dataset
        if getattr(ds, "use_native", False) and hasattr(ds, "resolve_paths"):
            try:
                from distributedpytorch_tpu.data import native
            except ImportError:  # missing native layer → per-item PIL path
                native = None

            if native is not None and native.get_lib() is not None:
                paths = [ds.resolve_paths(int(i)) for i in idx_list]
                if all(
                    native.supports(p) and native.supports(m) for p, m in paths
                ):
                    imgs, masks = native.load_batch(
                        [p for p, _ in paths],
                        [m for _, m in paths],
                        ds.newsize[0],
                        ds.newsize[1],
                        n_threads=max(self.num_workers, 4),
                    )
                    return {"image": imgs, "mask": masks}
        items = [ds[int(i)] for i in idx_list]
        return {
            "image": np.stack([it["image"] for it in items]),
            "mask": np.stack([it["mask"] for it in items]),
        }

    def batch_slices(self, epoch: int = 0) -> list:
        """This epoch's batches as index slices, in order — THE definition
        of batch formation, shared by `epoch_batches` and the sharded
        evaluator (evaluate.evaluate_sharded), which assigns whole slices
        to processes; one definition keeps their batch formation
        identical by construction."""
        order = self._epoch_order(epoch)
        cut = (
            len(order) - len(order) % self.batch_size
            if self.drop_last
            else len(order)
        )
        order = order[:cut]
        return [
            order[s : s + self.batch_size]
            for s in range(0, len(order), self.batch_size)
        ]

    def load_slice(self, idx_list) -> Batch:
        """Assemble the batch for one `batch_slices` entry."""
        return self._load_batch(idx_list)

    def epoch_batches(self, epoch: int = 0) -> Iterator[Batch]:
        slices = self.batch_slices(epoch)
        if self._pool is None:
            for i, idx in enumerate(slices):
                yield self._load_batch(idx, epoch=epoch, batch_idx=i)
            return

        # Pipelined prefetch: keep up to 2 whole-batch futures in flight
        # (the native path threads across items inside each batch in C++).
        # bounded_submit cancels queued decodes if the consumer stops early.
        from distributedpytorch_tpu.utils.prefetch import bounded_submit

        def load(pair):
            i, idx = pair
            return self._load_batch(idx, epoch=epoch, batch_idx=i)

        yield from bounded_submit(
            self._pool, load, list(enumerate(slices)), depth=2
        )

    def __iter__(self) -> Iterator[Batch]:
        return self.epoch_batches(0)
