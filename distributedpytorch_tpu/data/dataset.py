"""Filesystem-paired image/mask datasets with reference preprocess parity.

Behavior parity with the reference `BasicDataset`/`CarvanaDataset`
(reference utils/dataloading.py:12-78), re-expressed for a JAX/TPU host
pipeline:

  * sample IDs are filename stems of the images dir, dotfiles skipped
    (dataloading.py:19);
  * each item glob-pairs ``<id><mask_suffix>.*`` in the masks dir and
    ``<id>.*`` in the images dir, asserting exactly one match of each
    (dataloading.py:56-60);
  * loading supports PIL images plus ``.npy``/``.npz`` and ``.pt``/``.pth``
    tensors (dataloading.py:44-52);
  * images resize with BICUBIC, masks with NEAREST (dataloading.py:31);
  * images are scaled by /255, masks are left as raw integer labels
    (dataloading.py:39-40);
  * `CarvanaDataset` is `BasicDataset` with ``mask_suffix='_mask'``
    (dataloading.py:76-78).

TPU-first divergence (deliberate): items are **NHWC numpy** arrays — image
``(H, W, 3) float32``, mask ``(H, W) int32`` — not CHW torch tensors, because
XLA:TPU wants channels-last (SURVEY.md §7 hard-part 4). `newsize` keeps the
reference's ``(W, H)`` ordering (dataloading.py:29 reads it as ``newW, newH``).
"""

from __future__ import annotations

import logging
import os
import threading
from os.path import splitext
from pathlib import Path
from typing import Dict, Hashable, Optional, Sequence, Tuple

import numpy as np
from PIL import Image

try:
    from distributedpytorch_tpu.data import native
except ImportError:  # pragma: no cover - a broken/absent native layer must
    native = None  # never make the package unimportable (VERDICT.md round 2)

logger = logging.getLogger(__name__)

Item = Dict[str, np.ndarray]
#: Cache keys: the train loaders key by dataset index (int); the serving
#: tier keys by ``(path, size)`` tuples. Anything hashable works — the
#: cache itself never interprets the key.
Key = Hashable


class SampleCache:
    """Epoch-persistent, memory-budgeted cache of decoded samples.

    The epoch loop re-reads the SAME samples every epoch, yet the seed
    pipeline re-ran PIL/libjpeg decode + resize for each of them, every
    epoch — on a 1-core host that decode bound the whole run
    (docs/PERFORMANCE.md input-pipeline table; VERDICT r05 item 7 asks
    for one-time host staging). This cache sits under DataLoader's batch
    assembly: the first epoch decodes and stores items until the byte
    budget is full, later epochs serve hits straight from host memory.

    Deliberately no eviction: the access pattern is a uniform re-scan of
    the whole epoch (reshuffled order, same set), where any
    evict-on-full policy would thrash — every sample displaced is one
    that will be needed again next epoch. Whatever fits stays for the
    run; the remainder decodes each epoch, so a too-small budget
    degrades smoothly toward the uncached behavior.

    Sharded multi-process runs reshuffle BEFORE striding, so a rank's
    per-epoch sample set changes: epoch 2 is not a pure re-scan and its
    hit rate starts at ~|shard ∩ cached| rather than ~100%. Because
    nothing is evicted, each rank's cache still grows monotonically
    toward the full (budget-bounded) dataset and the hit rate converges
    over a few epochs — warm-up is slower, the steady state is the same.
    Size the per-process budget accordingly (it is per rank, not global).

    Thread-safe: the loader's decode pool and the placement worker hit
    it concurrently. Stored arrays are shared across epochs — callers
    must treat items as read-only (batch assembly np.stack-copies, so
    nothing downstream mutates them).

    The serving tier (serve/engine.py) reuses this as its request-path
    decode cache, keyed by ``(path, size)`` instead of dataset index:
    repeat traffic over the same objects skips PIL/libjpeg entirely.
    """

    def __init__(self, budget_bytes: int):
        self.budget_bytes = int(budget_bytes)
        self._items: Dict[Key, Item] = {}
        self._lock = threading.Lock()
        self.used_bytes = 0
        self.hits = 0
        self.misses = 0
        self._full_logged = False

    @staticmethod
    def _nbytes(item: Item) -> int:
        return sum(int(np.asarray(v).nbytes) for v in item.values())

    def get(self, idx: Key) -> Optional[Item]:
        with self._lock:
            item = self._items.get(idx)
            if item is None:
                self.misses += 1
            else:
                self.hits += 1
            return item

    def put(self, idx: Key, item: Item) -> bool:
        """Store if the budget allows; returns whether it was stored."""
        size = self._nbytes(item)
        with self._lock:
            if idx in self._items:
                return True
            if self.used_bytes + size > self.budget_bytes:
                if not self._full_logged:
                    self._full_logged = True
                    logger.info(
                        "sample cache full at %d items / %.1f MiB (budget "
                        "%.1f MiB) — remaining samples decode every epoch",
                        len(self._items),
                        self.used_bytes / 2**20,
                        self.budget_bytes / 2**20,
                    )
                return False
            # decouple from any whole-batch parent buffer: a row view
            # would pin the full decoded batch even when only this row
            # fits (np.array(copy=True), NOT ascontiguousarray — a
            # first-axis slice is already contiguous and would be
            # returned uncopied, silently retaining the parent)
            self._items[idx] = {
                k: np.array(v, copy=True) for k, v in item.items()
            }
            self.used_bytes += size
            return True

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)


class BasicDataset:
    """Images dir + masks dir paired by filename stem."""

    def __init__(
        self,
        images_dir: str,
        masks_dir: str,
        newsize: Sequence[int] = (960, 640),
        mask_suffix: str = "",
    ):
        self.images_dir = Path(images_dir)
        self.masks_dir = Path(masks_dir)
        self.newsize = tuple(int(v) for v in newsize)
        self.mask_suffix = mask_suffix

        self.ids = [
            splitext(f)[0]
            for f in os.listdir(images_dir)
            if not f.startswith(".")
        ]
        if not self.ids:
            raise RuntimeError(
                f"No input file found in {images_dir}, make sure you put your images there"
            )
        self.ids.sort()  # listdir order is fs-dependent; sort for determinism
        logger.info("Creating dataset with %d examples", len(self.ids))

    def __len__(self) -> int:
        return len(self.ids)

    @classmethod
    def load(cls, filename) -> Image.Image:
        """PIL / .npy / .pt loading (reference dataloading.py:44-52)."""
        ext = splitext(str(filename))[1]
        if ext in (".npz", ".npy"):
            return Image.fromarray(np.load(filename))
        if ext in (".pt", ".pth"):
            import torch  # local import: torch is only needed for .pt masks

            return Image.fromarray(torch.load(filename).numpy())
        return Image.open(filename)

    @classmethod
    def preprocess(
        cls, pil_img: Image.Image, newsize: Sequence[int], is_mask: bool
    ) -> np.ndarray:
        """Resize + normalize (reference dataloading.py:27-42), NHWC output."""
        new_w, new_h = int(newsize[0]), int(newsize[1])
        assert new_w > 0 and new_h > 0, (
            "Scale is too small, resized images would have no pixel"
        )
        pil_img = pil_img.resize(
            (new_w, new_h), resample=Image.NEAREST if is_mask else Image.BICUBIC
        )
        arr = np.asarray(pil_img)

        if is_mask:
            return arr.astype(np.int32)

        if arr.ndim == 2:  # grayscale image → single channel, channels-last
            arr = arr[..., np.newaxis]
        return (arr / 255.0).astype(np.float32)

    def resolve_paths(self, idx: int) -> Tuple[str, str]:
        """(image_path, mask_path) for one sample, with the reference's
        exactly-one-glob-match asserts (dataloading.py:56-60)."""
        name = self.ids[idx]
        mask_files = list(self.masks_dir.glob(name + self.mask_suffix + ".*"))
        img_files = list(self.images_dir.glob(name + ".*"))
        assert len(mask_files) == 1, (
            f"Either no mask or multiple masks found for the ID {name}: {mask_files}"
        )
        assert len(img_files) == 1, (
            f"Either no image or multiple images found for the ID {name}: {img_files}"
        )
        return str(img_files[0]), str(mask_files[0])

    use_native = True  # class-level toggle: C++ decode path when available

    def __getitem__(self, idx: int) -> Item:
        img_path, mask_path = self.resolve_paths(idx)

        if (
            self.use_native
            and native is not None
            and native.supports(img_path)
            and native.supports(mask_path)
        ):
            if native.get_lib() is not None:
                image, mask = native.load_item(
                    img_path, mask_path, self.newsize[0], self.newsize[1]
                )
                return {"image": image, "mask": mask}

        mask = self.load(mask_path)
        img = self.load(img_path)
        assert img.size == mask.size, (
            f"Image and mask should be the same size, "
            f"but are {img.size} and {mask.size}"
        )
        return {
            "image": self.preprocess(img, self.newsize, is_mask=False),
            "mask": self.preprocess(mask, self.newsize, is_mask=True),
        }


class CarvanaDataset(BasicDataset):
    """Carvana naming convention: masks end in ``_mask``
    (reference dataloading.py:76-78)."""

    def __init__(self, images_dir, masks_dir, newsize: Sequence[int] = (960, 640)):
        super().__init__(images_dir, masks_dir, newsize, mask_suffix="_mask")


def build_dataset(
    images_dir: str, masks_dir: str, newsize: Sequence[int] = (960, 640)
) -> BasicDataset:
    """Carvana-first with BasicDataset fallback — the reference's try/except
    chain (reference utils/train_utils.py:27-32). Unlike the reference, the
    Carvana attempt probes one item: mask pairing only fails at glob time, so
    a constructor-only try would defer the failure to mid-training."""
    try:
        ds = CarvanaDataset(images_dir, masks_dir, newsize)
        ds[0]
        logger.info("Carvana dataset detected")
        return ds
    except (AssertionError, RuntimeError):
        logger.info("Falling back to basic dataset")
        return BasicDataset(images_dir, masks_dir, newsize)


class SyntheticSegmentationDataset:
    """In-memory procedural car-ish blobs — same item contract as
    `BasicDataset`, no disk or PIL in the loop.

    Serves two roles the reference has no answer for (SURVEY.md §4):
    deterministic unit-test data, and a benchmark input source that removes
    disk/JPEG decode from measured step time.
    """

    def __init__(
        self,
        length: int = 64,
        newsize: Sequence[int] = (960, 640),
        seed: int = 0,
    ):
        self.length = length
        self.newsize = tuple(int(v) for v in newsize)
        self.seed = seed
        self.ids = [f"synthetic_{i:04d}" for i in range(length)]

    def __len__(self) -> int:
        return self.length

    def __getitem__(self, idx: int) -> Item:
        if not 0 <= idx < self.length:
            raise IndexError(idx)
        w, h = self.newsize
        rng = np.random.default_rng(self.seed * 1_000_003 + idx)
        image = rng.random((h, w, 3), dtype=np.float32)
        # an axis-aligned ellipse "car" per sample
        cy, cx = rng.integers(h // 4, 3 * h // 4), rng.integers(w // 4, 3 * w // 4)
        ry, rx = rng.integers(h // 8, h // 4), rng.integers(w // 8, w // 4)
        yy, xx = np.ogrid[:h, :w]
        mask = (
            ((yy - cy) / max(ry, 1)) ** 2 + ((xx - cx) / max(rx, 1)) ** 2 <= 1.0
        ).astype(np.int32)
        image[..., 0] = np.where(mask, 0.25 + 0.5 * image[..., 0], image[..., 0])
        return {"image": image, "mask": mask}


def write_synthetic_carvana_tree(
    root: str,
    n: int = 8,
    size_wh: Tuple[int, int] = (96, 64),
    seed: int = 0,
) -> Tuple[str, str]:
    """Materialize a tiny Carvana-layout tree (train_hq/ + train_masks/ with
    ``_mask.gif`` masks) for filesystem-path tests. Returns (images, masks)."""
    images_dir = os.path.join(root, "train_hq")
    masks_dir = os.path.join(root, "train_masks")
    os.makedirs(images_dir, exist_ok=True)
    os.makedirs(masks_dir, exist_ok=True)
    src = SyntheticSegmentationDataset(length=n, newsize=size_wh, seed=seed)
    for i in range(n):
        item = src[i]
        name = f"car_{i:03d}"
        img8 = (item["image"] * 255).astype(np.uint8)
        Image.fromarray(img8).save(os.path.join(images_dir, name + ".jpg"))
        # Carvana masks are {0,1} GIFs — the ``== 1`` binarization in the loss
        # depends on this (SURVEY.md §2 quirk 3).
        Image.fromarray(item["mask"].astype(np.uint8)).save(
            os.path.join(masks_dir, name + "_mask.gif")
        )
    return images_dir, masks_dir
