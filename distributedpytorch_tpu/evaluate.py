"""Validation pass: mean per-batch loss (+ the Dice metric the reference
never computes).

Parity with reference evaluate.py:6-25 — eval-mode forward over the val
loader, mean of per-batch criterion values. The UNet has no dropout/batchnorm
so train/eval mode is a no-op distinction (the reference toggles it anyway);
here the same pure apply serves both.
"""

from __future__ import annotations

import contextlib
from typing import Callable, Tuple

import jax
import numpy as np

from distributedpytorch_tpu.utils.prefetch import bounded_prefetch


def evaluate(
    eval_step: Callable,
    params,
    loader,
    place_batch: Callable = None,
    epoch: int = 0,
    progress: bool = False,
) -> Tuple[float, float]:
    """Returns (mean val loss, mean val dice) over the loader.

    `eval_step(params, batch) -> {'loss', 'dice'}` is the strategy-jitted
    step; `place_batch` moves host batches onto the mesh. `progress` shows
    the reference's per-round tqdm bar (reference evaluate.py:12).
    """
    from tqdm import tqdm

    losses, dices = [], []
    batches = loader.epoch_batches(epoch)
    if progress:
        batches = tqdm(
            batches, total=len(loader), desc="Validation round",
            unit="batch", leave=False,
        )
    # Keep device scalars and pull them in chunks — a float() per batch is a
    # blocking device→host round trip per metric (measured ~1.1 s/val-batch
    # over a tunneled runtime), while NO sync at all lets the host place the
    # entire val set's input buffers on the device before the first eval
    # step retires (gigabytes of live HBM at full resolution). A chunked
    # device_get bounds run-ahead to CHUNK batches per transfer.
    CHUNK = 8
    for batch in batches:
        if place_batch is not None:
            batch = place_batch(batch)
        metrics = eval_step(params, batch)
        losses.append(metrics["loss"])
        dices.append(metrics["dice"])
        if len(losses) % CHUNK == 0:
            losses[-CHUNK:], dices[-CHUNK:] = jax.device_get(
                (losses[-CHUNK:], dices[-CHUNK:])
            )
    if not losses:
        return float("nan"), float("nan")
    losses, dices = jax.device_get((losses, dices))
    return float(np.mean(losses)), float(np.mean(dices))


def evaluate_sharded(
    eval_step: Callable,
    grouped_eval_step: Callable,
    params,
    loader,
    place_batch: Callable,
    shard,
    epoch: int = 0,
    progress: bool = False,
) -> Tuple[float, float]:
    """Multi-process evaluation: each process loads and computes 1/world of
    the val set, every process returns the same (mean loss, mean dice).

    Batch formation is IDENTICAL to the replicated path (consecutive
    b-sized slices of the val order), so per-batch metrics — and the mean
    the plateau scheduler consumes — match `evaluate` exactly. Whole
    batches are assigned round-robin: rank p loads global batches p, p+w,
    ..., contributes each as its shard of one (w·b)-sized grouped dispatch
    (`place_batch` assembles the global array from per-process parts), and
    the grouped step returns all w per-batch metrics to every process.
    The ragged tail (< w batches) falls back to the replicated path, so no
    rank ever skips a collective another rank is waiting in.

    `shard` is the strategy's `eval_shard()`; world == 1 short-circuits to
    plain `evaluate` (same loop, no grouping).
    """
    from tqdm import tqdm

    w, rank = shard.world, shard.rank
    if w == 1:
        return evaluate(
            eval_step, params, loader, place_batch, epoch=epoch, progress=progress
        )

    b = loader.batch_size
    slices = loader.batch_slices(epoch)  # the SAME formation evaluate() uses
    # only uniform b-sized batches can stack into the grouped dispatch; the
    # (at most one) ragged final slice joins the replicated tail
    full = [s for s in slices if len(s) == b]
    n_groups = len(full) // w
    tail = full[n_groups * w :] + slices[len(full) :]

    mine = [full[g * w + rank] for g in range(n_groups)]
    # decode this rank's next batches while the device chews the current
    # group — same overlap epoch_batches gives the replicated path
    gen = bounded_prefetch(mine, loader.load_slice, depth=2)
    iterator = (
        tqdm(gen, total=n_groups, desc="Validation round (sharded)",
             unit="group", leave=False)
        if progress
        else gen
    )
    losses, dices = [], []
    CHUNK = 8
    with contextlib.closing(gen):
        for _idx, local in iterator:
            metrics = grouped_eval_step(params, place_batch(local))
            losses.append(metrics["loss"])  # (w,) device vectors, batch order
            dices.append(metrics["dice"])
            if len(losses) % CHUNK == 0:
                losses[-CHUNK:], dices[-CHUNK:] = jax.device_get(
                    (losses[-CHUNK:], dices[-CHUNK:])
                )
    losses = [x for arr in jax.device_get(losses) for x in np.asarray(arr)]
    dices = [x for arr in jax.device_get(dices) for x in np.asarray(arr)]
    tail_metrics = [
        eval_step(params, place_batch(loader.load_slice(idx))) for idx in tail
    ]
    for m in jax.device_get(tail_metrics):  # ONE host round trip for the tail
        losses.append(float(m["loss"]))
        dices.append(float(m["dice"]))
    if not losses:
        return float("nan"), float("nan")
    return float(np.mean(losses)), float(np.mean(dices))
