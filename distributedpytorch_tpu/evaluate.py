"""Validation pass: mean per-batch loss (+ the Dice metric the reference
never computes).

Parity with reference evaluate.py:6-25 — eval-mode forward over the val
loader, mean of per-batch criterion values. The UNet has no dropout/batchnorm
so train/eval mode is a no-op distinction (the reference toggles it anyway);
here the same pure apply serves both.
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple

import numpy as np


def evaluate(
    eval_step: Callable,
    params,
    loader,
    place_batch: Callable = None,
    epoch: int = 0,
) -> Tuple[float, float]:
    """Returns (mean val loss, mean val dice) over the loader.

    `eval_step(params, batch) -> {'loss', 'dice'}` is the strategy-jitted
    step; `place_batch` moves host batches onto the mesh.
    """
    losses, dices = [], []
    for batch in loader.epoch_batches(epoch):
        if place_batch is not None:
            batch = place_batch(batch)
        metrics = eval_step(params, batch)
        losses.append(float(metrics["loss"]))
        dices.append(float(metrics["dice"]))
    if not losses:
        return float("nan"), float("nan")
    return float(np.mean(losses)), float(np.mean(dices))
