"""Validation pass: mean per-batch loss (+ the Dice metric the reference
never computes).

Parity with reference evaluate.py:6-25 — eval-mode forward over the val
loader, mean of per-batch criterion values. The UNet has no dropout/batchnorm
so train/eval mode is a no-op distinction (the reference toggles it anyway);
here the same pure apply serves both.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import numpy as np


def evaluate(
    eval_step: Callable,
    params,
    loader,
    place_batch: Callable = None,
    epoch: int = 0,
    progress: bool = False,
) -> Tuple[float, float]:
    """Returns (mean val loss, mean val dice) over the loader.

    `eval_step(params, batch) -> {'loss', 'dice'}` is the strategy-jitted
    step; `place_batch` moves host batches onto the mesh. `progress` shows
    the reference's per-round tqdm bar (reference evaluate.py:12).
    """
    from tqdm import tqdm

    losses, dices = [], []
    batches = loader.epoch_batches(epoch)
    if progress:
        batches = tqdm(
            batches, total=len(loader), desc="Validation round",
            unit="batch", leave=False,
        )
    # Keep device scalars and pull them in chunks — a float() per batch is a
    # blocking device→host round trip per metric (measured ~1.1 s/val-batch
    # over a tunneled runtime), while NO sync at all lets the host place the
    # entire val set's input buffers on the device before the first eval
    # step retires (gigabytes of live HBM at full resolution). A chunked
    # device_get bounds run-ahead to CHUNK batches per transfer.
    CHUNK = 8
    for batch in batches:
        if place_batch is not None:
            batch = place_batch(batch)
        metrics = eval_step(params, batch)
        losses.append(metrics["loss"])
        dices.append(metrics["dice"])
        if len(losses) % CHUNK == 0:
            losses[-CHUNK:], dices[-CHUNK:] = jax.device_get(
                (losses[-CHUNK:], dices[-CHUNK:])
            )
    if not losses:
        return float("nan"), float("nan")
    losses, dices = jax.device_get((losses, dices))
    return float(np.mean(losses)), float(np.mean(dices))
