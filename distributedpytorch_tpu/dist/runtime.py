"""Process-group runtime: `jax.distributed` with torchrun-compatible env.

The reference joins its process group with
``dist.init_process_group('nccl', init_method='env://')`` under a torchrun
launcher that sets LOCAL_RANK / RANK / WORLD_SIZE / MASTER_ADDR / MASTER_PORT
(reference train.py:29-31, :58-61; README.md:37). The TPU-native equivalent
is `jax.distributed.initialize`, which on real TPU pods autodetects topology;
off-pod (or when launched by torchrun per the driver's north star) we map the
torchrun env onto its coordinator/process arguments.

No NCCL anywhere: after initialization, collectives are XLA's, riding ICI
within a pod slice and DCN across slices (SURVEY.md §5 'Distributed
communication backend').
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Optional

import jax

logger = logging.getLogger(__name__)

_INITIALIZED = False


@dataclasses.dataclass(frozen=True)
class RuntimeInfo:
    process_id: int
    num_processes: int
    coordinator: Optional[str]

    @property
    def is_main(self) -> bool:
        return self.process_id == 0


def _torchrun_env() -> Optional[RuntimeInfo]:
    """Map torchrun's env contract onto jax.distributed's, if present."""
    if "WORLD_SIZE" not in os.environ or "RANK" not in os.environ:
        return None
    world = int(os.environ["WORLD_SIZE"])
    rank = int(os.environ["RANK"])
    addr = os.environ.get("MASTER_ADDR", "127.0.0.1")
    # jax.distributed's coordinator must not collide with torchrun's c10d
    # rendezvous port, so offset it deterministically.
    port = int(os.environ.get("MASTER_PORT", "29500")) + 1
    return RuntimeInfo(rank, world, f"{addr}:{port}")


def _enable_cpu_collectives() -> None:
    """Give multi-process CPU runs a working collectives backend.

    jaxlib's CPU client defaults to collectives 'none', so ANY
    multiprocess computation — the DDP gradient all-reduce, the sharded
    evaluator's grouped dispatch, `process_allgather` (both the stop
    agreement and the FSDP checkpoint gather) — dies with "Multiprocess
    computations aren't implemented on the CPU backend". Gloo ships in
    jaxlib; it just has to be selected BEFORE the backend initializes.
    Called only on the multi-process paths: single-process runs never
    need it, and on TPU backends the flag is simply unread."""
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # older jax without the flag: leave as-is
        logger.warning(
            "could not enable gloo CPU collectives; multi-process CPU "
            "computations may be unavailable", exc_info=True,
        )


def _warm_host_collectives() -> None:
    """Form the all-process host-collective (Gloo, on CPU backends) context
    NOW, while every rank is still in lockstep from `initialize()`'s
    rendezvous.

    Gloo context formation has a hard ~30 s key-value deadline per peer.
    Without this warm-up the first host collective is wherever the trainer
    first calls `multihost_utils.process_allgather` — the per-epoch stop
    check (train/loop.py `_stop_agreed`) — by which point rank skew on a
    contended host (N processes time-slicing few cores, compile times
    diverging) can exceed the deadline and kill the whole job with
    "Gloo context initialization failed: DEADLINE_EXCEEDED" (observed with
    4 localhost processes on a 1-core box). Once the context exists,
    later collectives block on connected sockets with no such deadline.
    On TPU pods this is a single sub-millisecond allgather — harmless."""
    import numpy as np
    from jax.experimental import multihost_utils

    multihost_utils.process_allgather(np.zeros((1,), np.int32))


def _init_timeout_kwargs() -> dict:
    """Bound the rendezvous wait (``DPT_DIST_INIT_TIMEOUT_S``, seconds).

    jax's default initialization timeout is 300 s — fine for a pod
    bring-up, far too patient for the elastic supervisor's relaunch
    loop: a worker stuck joining a rendezvous whose peers already died
    should fail fast so the supervisor can classify it and respawn the
    whole world (dist/elastic.py sets this for its workers' children
    only through the env, so standalone launches keep jax's default)."""
    raw = os.environ.get("DPT_DIST_INIT_TIMEOUT_S")
    if not raw:
        return {}
    try:
        return {"initialization_timeout": int(float(raw))}
    except ValueError:
        logger.warning("ignoring malformed DPT_DIST_INIT_TIMEOUT_S=%r", raw)
        return {}


def initialize_from_env(force: bool = False) -> RuntimeInfo:
    """Initialize multi-process JAX if a launcher env is present.

    Order: explicit JAX_COORDINATOR env → torchrun env → single process.
    Safe to call unconditionally (idempotent; no-op single-process)."""
    global _INITIALIZED
    if _INITIALIZED:
        return RuntimeInfo(jax.process_index(), jax.process_count(), None)

    # Real multi-host TPU pods: argless initialize() autodetects the pod's
    # own coordinator from the TPU runtime/cloud metadata. Opt-in (env
    # flag) because on single-host and tunneled setups the detection probes
    # would stall startup.
    if os.environ.get("DPT_JAX_AUTO_INIT") == "1":
        _enable_cpu_collectives()
        jax.distributed.initialize(**_init_timeout_kwargs())
        _INITIALIZED = True
        info = RuntimeInfo(jax.process_index(), jax.process_count(), None)
        if info.num_processes > 1:
            _warm_host_collectives()
        logger.info(
            "jax.distributed auto-initialized: process %d/%d",
            info.process_id,
            info.num_processes,
        )
        return info

    coord = os.environ.get("JAX_COORDINATOR_ADDRESS")
    if coord:
        info = RuntimeInfo(
            int(os.environ.get("JAX_PROCESS_ID", "0")),
            int(os.environ.get("JAX_NUM_PROCESSES", "1")),
            coord,
        )
    else:
        info = _torchrun_env()

    if info is None or info.num_processes <= 1:
        return RuntimeInfo(0, 1, None)

    _enable_cpu_collectives()
    jax.distributed.initialize(
        coordinator_address=info.coordinator,
        num_processes=info.num_processes,
        process_id=info.process_id,
        **_init_timeout_kwargs(),
    )
    _INITIALIZED = True
    _warm_host_collectives()
    logger.info(
        "jax.distributed initialized: process %d/%d via %s",
        info.process_id,
        info.num_processes,
        info.coordinator,
    )
    return info


def shutdown() -> None:
    """`dist.destroy_process_group` parity (reference train.py:61)."""
    global _INITIALIZED
    if _INITIALIZED:
        jax.distributed.shutdown()
        _INITIALIZED = False
