"""Multi-process runtime glue (the reference's torch.distributed layer):
process-group init (runtime.py), rank health/heartbeats (health.py), and
the elastic supervisor (elastic.py — the torchrun/TorchElastic role)."""

from distributedpytorch_tpu.dist.runtime import (  # noqa: F401
    RuntimeInfo,
    initialize_from_env,
    shutdown,
)
