"""Multi-process runtime glue (the reference's torch.distributed layer)."""

from distributedpytorch_tpu.dist.runtime import (  # noqa: F401
    RuntimeInfo,
    initialize_from_env,
    shutdown,
)
