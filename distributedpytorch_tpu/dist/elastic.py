"""Elastic multi-process supervisor: spawn, watch, relaunch, reshard.

The reference's launcher is `torchrun` (README.md:37) — i.e. TorchElastic:
an agent that supervises worker ranks, detects failures, and restarts
the job, possibly at a different world size. `jax.distributed` has no
such layer; this module provides it, provable end-to-end on the 2–4
process CPU/gloo mesh (tests/test_elastic.py):

  * **spawn** — launch N worker ranks of ``python -m
    distributedpytorch_tpu`` (or any command) with the torchrun-style
    env contract `dist/runtime.py` already maps onto
    `jax.distributed.initialize`, a fresh rendezvous port per attempt,
    per-rank log files, and a per-attempt heartbeat directory
    (``--heartbeat-dir`` is appended to the worker argv);
  * **watch** — poll exit codes + the beat files; `dist/health.classify`
    turns them into per-rank verdicts (dead / hung / desynced) within a
    bounded window (``--heartbeat-timeout`` beat age, opt-in
    ``--progress-timeout`` step-progress age, spawn grace for workers
    that die before their first beat);
  * **teardown** — on any failed rank, SIGTERM the survivors (they are
    blocked inside collectives their dead peer abandoned), wait
    ``--teardown-grace``, SIGKILL stragglers — and print ONE line per
    failed rank (``rank R: dead at epoch:step``) instead of every
    survivor's wall of channel tracebacks;
  * **relaunch** — up to ``--max-restarts`` times with exponential
    backoff, resuming from the newest intact retained checkpoint
    (``-c <method>`` appended to the worker argv once one exists — the
    mesh-resharding restore in checkpoint.py makes that work even when
    the world size changed);
  * **elastic world size** — a rank index that fails
    ``--rank-fail-limit`` consecutive attempts is treated as a lost
    slot: the job relaunches on the remaining M ranks (never below
    ``--min-ranks``), and the checkpoint saved on N processes reshards
    onto the M-process mesh.

  * **static preflight** — before the first spawn, the job's strategy ×
    schedule runs through the static distributed-correctness analyzer
    (``python -m distributedpytorch_tpu analyze`` in a provisioned CPU
    subprocess, docs/ANALYSIS.md): a statically-deadlocked schedule or a
    rank-divergent collective would otherwise spawn N ranks that hang
    until the heartbeat window expires and burn the whole restart budget
    relaunching into the same hang. Findings refuse the launch
    (``STATIC_CHECK_EXIT``); analyzer infra failures never block;
    ``--no-preflight`` overrides. The analyzer also compares the
    ordered-collective fingerprint under every simulated rank of THIS
    job's world size (``--fingerprint-world N``, rule
    ``collective-fingerprint``), so a collective gated on a rank the
    dual-rank re-trace never simulates is caught before the spawn
    instead of desyncing the gloo rendezvous.

Chaos drills: ``--chaos SITE[@RANK]:EPOCH:STEP[:COUNT]`` arms a fault
(utils/faults.py — ``rank_kill`` / ``rank_hang`` live in the step loop)
via ``--inject-fault`` on the FIRST attempt only, so the relaunched
attempt does not immediately re-kill itself at the same coordinates.

**Serve workload** (``--workload serve``): the same supervision adopts
serve processes (serve/cli.py) as its second workload — "a dead
dispatch loop should be a relaunch, not an outage" (ROADMAP), and the
layer above the server's own in-process core relaunches. Differences
from training, all mechanical: worker R gets ``--port base+R`` (one
HTTP front per worker — a shared-nothing fleet behind any TCP load
balancer), there is no checkpoint resume to append (the serve args
already carry ``-c``), and no static preflight to run (serving is
collective-free by construction). The per-attempt ``--trace-timeline``
IS armed (serve/cli.py writes per-request span ledgers under the same
rank-suffix convention), merged into one fleet Perfetto timeline with
"worker R" tracks; with ``--metrics-port`` the supervisor additionally
scrapes every worker's ``/metrics`` and re-exposes the families merged and
worker-labeled on its own port — the fleet pane. The
beats come from the dispatch loop — it ticks progress every turn, so
``--progress-timeout`` catches a wedged pipeline (hung device call,
stalled completions) whose beat *thread* is still alive — and serve
workers run until failure or :meth:`ElasticSupervisor.request_stop`
(SIGINT on the CLI), so "every rank exited 0" is a stop, not a result.

Deliberately jax-free: the supervisor process never initializes a
backend (and never dials a tunneled TPU runtime) — all its knowledge of
the job comes from exit codes, beat files, and the checkpoint chain on
disk.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import logging
import os
import shlex
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence

from distributedpytorch_tpu.dist import health
from distributedpytorch_tpu.obs import defs as obsm
from distributedpytorch_tpu.obs import flight
from distributedpytorch_tpu.serve import control

logger = logging.getLogger(__name__)

#: rc a worker may use for "I am aborting because a PEER failed" (see
#: cli.py's per-rank error summary): the supervisor attributes the
#: failure to the primary rank, not to survivors that died of it.
PEER_FAILURE_EXIT = 13

#: Supervisor rc when the static preflight (analysis/, docs/ANALYSIS.md)
#: found the job's step program statically broken: nothing was spawned.
STATIC_CHECK_EXIT = 3


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _worker_arg(args: Sequence[str], names: Sequence[str], default: str,
                abbrev: bool = False) -> str:
    """Pull a flag value out of the worker argv (last occurrence wins,
    like argparse). Supports ``--flag value`` and ``--flag=value``;
    ``abbrev`` additionally accepts argparse-style prefix spellings
    (``--pipeline-sched 1f1b``) — the trainer's parser allows them, so
    a supervisor that only matched the full spelling would silently
    read its default instead of the schedule the workers actually run."""
    value = default
    args = list(args)

    def matches(flag: str, name: str) -> bool:
        if flag == name:
            return True
        return (abbrev and flag.startswith("--") and len(flag) >= 4
                and name.startswith(flag))

    for i, a in enumerate(args):
        flag, eq, rest = a.partition("=")
        for n in names:
            if (len(n) == 2 and not n.startswith("--")
                    and a.startswith(n) and a != n):
                # glued short form: argparse reads -tMP as -t with value
                # "MP" — and -t=X as value "=X", the '=' taken verbatim
                value = a[len(n):]
            elif matches(flag, n):
                if eq:
                    value = rest
                elif i + 1 < len(args):
                    value = args[i + 1]
    return value


def _checkpoint_exists(checkpoint_dir: str, tag: str) -> bool:
    """Is there anything resumable on disk? Mirrors
    `checkpoint.retained_checkpoints` without importing the jax/flax
    stack into the supervisor process."""
    base = os.path.join(checkpoint_dir, f"{tag}.ckpt")
    if os.path.exists(base):
        return True
    return any(os.path.exists(f"{base}.{i}") for i in range(1, 64))


class FleetMetricsScraper:
    """The fleet pane's ingest half (docs/SERVING.md "Fleet pane"): a
    daemon thread scraping each serve worker's ``/metrics`` (port
    base+R) and keeping the latest exposition text per worker. The
    supervisor's own metrics endpoint re-exposes these merged and
    worker-labeled (``registry.merge_expositions``), so one scrape
    target tells the whole shared-nothing fleet's story. A worker that
    fails its scrape (dead, relaunching, mid-bind) drops out of the
    pane until it answers again — stale numbers from a dead worker
    would read as a healthy flatline."""

    def __init__(self, host: str, base_port: int, world_fn,
                 interval_s: float = 2.0, timeout_s: float = 2.0,
                 on_sweep=None):
        self.host = host
        self.base_port = int(base_port)
        self.world_fn = world_fn  # () -> current world size
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        # per-sweep subscriber (the router's placement feed): called
        # with the {rank: exposition_text} of each completed sweep
        self.on_sweep = on_sweep
        self._latest: Dict[str, str] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="dpt-fleet-scrape",
        )

    def start(self) -> "FleetMetricsScraper":
        self._thread.start()
        return self

    def _scrape_worker(self, rank: int) -> Optional[str]:
        import urllib.request

        url = f"http://{self.host}:{self.base_port + rank}/metrics"
        try:
            with urllib.request.urlopen(url, timeout=self.timeout_s) as resp:
                return resp.read().decode()
        except Exception:  # noqa: BLE001 — a dead worker is not news
            return None

    def scrape_once(self) -> Dict[str, str]:
        """One sweep over the current fleet (also the unit under test).
        Workers are scraped CONCURRENTLY: serially, every wedged worker
        would add its full timeout to the sweep and the healthy workers'
        numbers would go tens of seconds stale on a large fleet — the
        exact staleness this pane exists to avoid."""
        import concurrent.futures

        world = max(0, int(self.world_fn()))
        if world == 0:
            return {}
        with concurrent.futures.ThreadPoolExecutor(
            max_workers=min(world, 16),
            thread_name_prefix="dpt-fleet-scrape",
        ) as pool:
            texts = list(pool.map(self._scrape_worker, range(world)))
        return {str(r): t for r, t in enumerate(texts) if t is not None}

    def _loop(self) -> None:
        # sweep IMMEDIATELY: the pane must not serve an empty merged
        # exposition for the first interval after startup
        while True:
            seen = self.scrape_once()
            with self._lock:
                self._latest = seen
            if self.on_sweep is not None:
                try:
                    self.on_sweep(seen)
                except Exception:  # noqa: BLE001 — a subscriber must
                    # not kill the pane
                    logger.exception("fleet scrape: on_sweep failed")
            if self._stop.wait(self.interval_s):
                return

    def latest(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._latest)

    def stop(self) -> None:
        self._stop.set()


class FleetScaler:
    """Supervisor-level capacity actuator: spawn/retire WHOLE serve
    workers (the process-level loop plan-serve actually sizes — the
    in-process :class:`serve.scaler.ReplicaScaler` only resizes replica
    groups *inside* one worker). Both actuators share one control law
    (serve/control.py): every decision cites the ``dpt_serve_plan``
    grid point it executes, exactly like the replica scaler's.

    The recommendation signal is the plan itself — the observed fleet
    arrival rate matched to the nearest simulated poisson scenario at
    or above it, that scenario's recommended replica count read as a
    worker count (one worker hosts one planned replica's capacity at
    fleet granularity). Streak hysteresis (``up_windows`` consecutive
    diverging windows to grow, ``down_windows`` to shrink — shrinking
    is the dangerous direction) plus the shared cooldown keep it from
    flapping; one worker moves per actuation.

    Spawn rides the per-rank relaunch machinery: fresh port base+R, an
    attempt-0 heartbeat slot, and the fleet-shared ``$DPT_AOT_CACHE`` —
    the newcomer cold-starts warm off the executables its siblings
    already compiled (``recompiles: 0``). Retire drains via the
    router(s): eject from every front door, wait out in-flight, THEN
    SIGTERM (serve/cli.py drains on it)."""

    def __init__(self, supervisor: "ElasticSupervisor", plan=None,
                 min_workers: int = 1, max_workers: Optional[int] = None,
                 up_windows: int = 2, down_windows: int = 4,
                 cooldown_windows: Optional[int] = None):
        from distributedpytorch_tpu.serve.control import (  # jax-free
            plan_recommendation,
        )

        self._recommend = plan_recommendation
        if isinstance(plan, str):
            from distributedpytorch_tpu.analysis.serve_planner import (
                load_serve_plan,  # jax-free: profile + sim only
            )

            plan = load_serve_plan(plan)
        self.supervisor = supervisor
        self.plan = plan
        self.min_workers = max(1, int(min_workers))
        self.max_workers = int(
            max_workers if max_workers is not None
            else max(supervisor.nprocs, self.min_workers)
        )
        self.up_windows = max(1, int(up_windows))
        self.down_windows = max(1, int(down_windows))
        self.cooldown_windows = int(
            cooldown_windows if cooldown_windows is not None
            else max(self.up_windows, self.down_windows)
        )
        # start past cooldown: the FIRST sustained divergence may act
        self.windows_since_action = self.cooldown_windows
        self._up_streak = 0
        self._down_streak = 0
        self.decisions: List[dict] = []
        self.spawns = 0
        self.retires = 0
        # arrival-rate observation (thread mode): router request deltas
        self._last_requests: Optional[int] = None
        self._last_t: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def step(self, observed_rate_rps: Optional[float] = None):
        """One control window: age the cooldown, read the plan's
        recommendation for the observed rate, decide through the shared
        law, actuate at most one worker."""
        from distributedpytorch_tpu.serve import control

        self.windows_since_action += 1
        current = len(self.supervisor.active_serve_ranks())
        recommendation = self._recommend(self.plan, observed_rate_rps)
        hold_reason = None
        if recommendation is not None:
            if recommendation > current:
                self._up_streak += 1
                self._down_streak = 0
                if self._up_streak < self.up_windows:
                    hold_reason = (
                        f"up streak {self._up_streak}/{self.up_windows}")
            elif recommendation < current:
                self._down_streak += 1
                self._up_streak = 0
                if self._down_streak < self.down_windows:
                    hold_reason = (f"down streak {self._down_streak}/"
                                   f"{self.down_windows}")
            else:
                self._up_streak = self._down_streak = 0
        decision = control.decide_scale(
            current, recommendation,
            min_units=self.min_workers, max_units=self.max_workers,
            windows_since_action=self.windows_since_action,
            cooldown_windows=self.cooldown_windows,
            hold_reason=hold_reason,
            rate_rps=observed_rate_rps, plan=self.plan,
        )
        return self.apply(decision)

    def apply(self, decision):
        """Actuate a non-hold decision: one worker per window, through
        the supervisor's spawn/retire machinery. Stamps the ledger /
        flight / metric trail either way."""
        import dataclasses as _dc

        from distributedpytorch_tpu.serve import control

        achieved = decision.current
        if decision.direction != control.DIR_HOLD:
            if decision.direction == control.DIR_UP:
                rank = self.supervisor.spawn_fleet_worker()
                if rank is not None:
                    achieved = decision.current + 1
                    self.spawns += 1
            else:
                rank = self.supervisor.retire_fleet_worker()
                if rank is not None:
                    achieved = decision.current - 1
                    self.retires += 1
            if achieved != decision.current:
                self.windows_since_action = 0
                self._up_streak = self._down_streak = 0
                obsm.FLEET_SCALE_EVENTS.labels(
                    direction=decision.direction).inc()
                logger.info(
                    "fleet scaler: %s %d -> %d (%s) plan_point=%s",
                    decision.direction, decision.current, achieved,
                    decision.reason, decision.plan_point,
                )
            entry = {**decision.payload(), "achieved": achieved}
            self.decisions.append(entry)
            del self.decisions[:-50]
            flight.record("fleet_scale", **{
                k: v for k, v in entry.items() if v is not None})
        return _dc.replace(decision, target=achieved)

    # -- background thread (elastic --fleet-interval) ------------------------
    def _observed_rate(self) -> Optional[float]:
        router = self.supervisor.router
        if router is None:
            return None
        now = time.monotonic()
        total = router.requests_ok + router.requests_failed
        rate = None
        if self._last_requests is not None and now > self._last_t:
            rate = (total - self._last_requests) / (now - self._last_t)
        self._last_requests, self._last_t = total, now
        return rate

    def _run(self, interval_s: float) -> None:
        while not self._stop.wait(interval_s):
            try:
                self.step(observed_rate_rps=self._observed_rate())
            except Exception:  # noqa: BLE001 — the control loop must
                # outlive one bad window
                logger.exception("fleet scaler: step failed")

    def start(self, interval_s: float) -> "FleetScaler":
        self._thread = threading.Thread(
            target=self._run, args=(float(interval_s),),
            name="dpt-fleet-scaler", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)

    def status(self) -> dict:
        return {
            "workers": len(self.supervisor.active_serve_ranks()),
            "min_workers": self.min_workers,
            "max_workers": self.max_workers,
            "cooldown_windows": self.cooldown_windows,
            "windows_since_action": self.windows_since_action,
            "spawns": self.spawns,
            "retires": self.retires,
            "plan": bool(self.plan),
            "decisions": self.decisions[-10:],
        }


@dataclasses.dataclass
class AttemptResult:
    """What one launch attempt came to (recorded in the report JSON)."""

    attempt: int
    world: int
    ok: bool
    failures: List[str]  # the one-line per-rank summaries
    exit_codes: Dict[int, Optional[int]]
    duration_s: float


class ElasticSupervisor:
    """Supervise one elastic job (see module docstring).

    ``worker_cmd`` is the base command (default: this package's CLI);
    ``worker_args`` is appended to it. The supervisor appends per-rank
    env (RANK / WORLD_SIZE / MASTER_ADDR / MASTER_PORT), the heartbeat
    flags, ``--chaos`` specs (attempt 0 only), and ``-c <tag>`` once a
    checkpoint exists."""

    def __init__(
        self,
        worker_args: Sequence[str],
        nprocs: int,
        worker_cmd: Optional[Sequence[str]] = None,
        min_ranks: int = 1,
        max_restarts: int = 3,
        heartbeat_timeout_s: float = 10.0,
        heartbeat_interval_s: float = 0.5,
        progress_timeout_s: float = 0.0,
        spawn_timeout_s: float = 300.0,
        poll_interval_s: float = 0.25,
        restart_backoff_s: float = 1.0,
        teardown_grace_s: float = 10.0,
        rank_fail_limit: int = 2,
        run_dir: str = "./elastic_run",
        report_path: Optional[str] = None,
        cpu_devices: int = 0,
        chaos: Sequence[str] = (),
        env: Optional[Dict[str, str]] = None,
        cwd: Optional[str] = None,
        preflight: bool = True,
        preflight_timeout_s: float = 300.0,
        trace: bool = True,
        metrics_port: Optional[int] = None,
        workload: str = "train",
        router_port: Optional[int] = None,
        router_standby_port: Optional[int] = None,
        fleet_plan=None,
        fleet_min_workers: int = 1,
        fleet_max_workers: Optional[int] = None,
        fleet_interval_s: float = 0.0,
    ):
        if nprocs < 1:
            raise ValueError(f"nprocs must be >= 1, got {nprocs}")
        if not 1 <= min_ranks <= nprocs:
            raise ValueError(
                f"min_ranks must be in [1, {nprocs}], got {min_ranks}"
            )
        if workload not in ("train", "serve"):
            raise ValueError(
                f"workload must be 'train' or 'serve', got {workload!r}"
            )
        self.workload = workload
        self.worker_args = list(worker_args)
        default_cmd = [sys.executable, "-u", "-m", "distributedpytorch_tpu"]
        if workload == "serve":
            default_cmd.append("serve")
        self.worker_cmd = list(
            worker_cmd if worker_cmd is not None else default_cmd
        )
        self.nprocs = int(nprocs)
        self.min_ranks = int(min_ranks)
        self.max_restarts = int(max_restarts)
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self.progress_timeout_s = float(progress_timeout_s)
        self.spawn_timeout_s = float(spawn_timeout_s)
        self.poll_interval_s = float(poll_interval_s)
        self.restart_backoff_s = float(restart_backoff_s)
        self.teardown_grace_s = float(teardown_grace_s)
        self.rank_fail_limit = max(1, int(rank_fail_limit))
        # absolute: the workers receive this path in their argv and may
        # run under a different cwd than the supervisor
        self.run_dir = os.path.abspath(str(run_dir))
        self.report_path = report_path or os.path.join(
            self.run_dir, "report.json"
        )
        self.cpu_devices = int(cpu_devices)
        self.chaos = tuple(chaos)
        self.base_env = dict(env) if env is not None else None
        self.cwd = cwd  # workers' cwd (their relative artifact dirs)
        self.preflight = bool(preflight)
        self.preflight_timeout_s = float(preflight_timeout_s)
        self.preflight_findings: List[str] = []
        # telemetry (docs/OBSERVABILITY.md): per-rank step timelines are
        # armed by default — every elastic run is a diagnostic context,
        # and a dead attempt's merged Perfetto trace is its post-mortem
        self.trace = bool(trace)
        self.metrics_port = metrics_port
        self.merged_timeline: Optional[str] = None
        # fleet pane (serve workload + --metrics-port): the per-worker
        # /metrics scraper feeding the supervisor's merged exposition
        self.fleet_scraper: Optional[FleetMetricsScraper] = None
        # front door (serve workload + --router-port): ONE address
        # proxying /predict across the workers with load-aware
        # placement, transparent retry of 503s/dead workers, and
        # /admin/ab fan-out (serve/router.py — jax-free, runs in this
        # process). None = clients talk to worker ports directly.
        self.router_port = router_port
        self.router = None
        # HA pair (--router-standby-port): a SECOND router instance —
        # both proxy /predict at all times; the standby pulls the
        # active's /admin/state snapshot every probe interval and takes
        # over on the first missed probe (serve/router.py "HA"). The
        # client contract is two addresses, no VIP (docs/SERVING.md).
        self.router_standby_port = router_standby_port
        self.standby_router = None
        # fleet-level elasticity (FleetScaler): spawn/retire whole
        # serve workers off the plan-serve recommendation
        self.fleet_plan = fleet_plan
        self.fleet_min_workers = int(fleet_min_workers)
        self.fleet_max_workers = fleet_max_workers
        self.fleet_interval_s = float(fleet_interval_s)
        self.fleet_scaler: Optional[FleetScaler] = None
        self._retired_ranks: set = set()
        self._grace_until: Dict[int, float] = {}

        # resume coordinates, parsed from the worker argv (the trainer's
        # epoch checkpoints land at <checkpoint_dir>/<train_method>.ckpt).
        # A serve fleet has no resume: workers reload their -c checkpoint
        # themselves, and the tag only labels the report.
        self.method_tag = (
            "serve" if self.workload == "serve" else _worker_arg(
                self.worker_args, ("-t", "--train-method"), "singleGPU",
                abbrev=True,
            )
        )
        # serve worker R binds base+R: one HTTP front per process — a
        # shared-nothing fleet any TCP load balancer can sit in front of
        self.base_port = int(_worker_arg(
            self.worker_args, ("--port",), "8008"
        )) if self.workload == "serve" else None
        # exact-only on purpose: the trainer has a DISTINCT exact flag
        # --checkpoint (load a .pth), which argparse resolves to itself
        # but a prefix match would misread as --checkpoint-dir and break
        # resume (relaunch would probe <cwd>/model.pth for checkpoints)
        ckpt_dir = _worker_arg(
            self.worker_args, ("--checkpoint-dir",), "./checkpoints"
        )
        if not os.path.isabs(ckpt_dir):
            # a relative checkpoint dir is resolved by the WORKERS
            # against their cwd; the resume check here must look in the
            # same place or every relaunch silently restarts from
            # scratch (the supervisor's own cwd may differ)
            ckpt_dir = os.path.join(self.cwd or os.getcwd(), ckpt_dir)
        self.checkpoint_dir = ckpt_dir

        self._shutdown = threading.Event()
        self.restarts = 0
        self.world_history: List[int] = []
        self.attempts: List[AttemptResult] = []
        self._procs: List[subprocess.Popen] = []

    # ------------------------------------------------------------------
    def _worker_env(self, rank: int, world: int, port: int,
                    attempt: int = 0) -> Dict[str, str]:
        if self.cpu_devices > 0:
            # CPU-mesh drills/tests: ONE definition of the virtual-device
            # provisioning moves (utils/provision.py — jax-free module)
            from distributedpytorch_tpu.utils.provision import provisioned_env

            env = provisioned_env(self.cpu_devices, base=self.base_env)
        else:
            env = dict(os.environ if self.base_env is None else self.base_env)
        env.update(
            {
                "RANK": str(rank),
                "LOCAL_RANK": str(rank),
                "WORLD_SIZE": str(world),
                "MASTER_ADDR": "127.0.0.1",
                "MASTER_PORT": str(port),
            }
        )
        # a worker stuck joining a rendezvous whose peers died must fail
        # fast (dist/runtime._init_timeout_kwargs) — the supervisor, not
        # jax's 300 s default, owns the retry loop
        env.setdefault(
            "DPT_DIST_INIT_TIMEOUT_S",
            str(int(max(30.0, self.spawn_timeout_s))),
        )
        # worker flight-recorder dumps (obs/flight.py) land with the
        # attempt's other artifacts (rank logs, beats, timelines)
        env.setdefault(
            "DPT_FLIGHT_DIR",
            os.path.join(self.run_dir, f"attempt{attempt}"),
        )
        # shared AOT executable store (utils/aotstore.py) for serve
        # fleets: ONE dir across ranks AND attempts — a relaunch loads
        # the executables attempt 0 compiled instead of re-paying the
        # whole ladder. Safe shared (unlike the per-rank XLA cache
        # below): entries are integrity-footed and atomically renamed,
        # and racing ranks write identical bytes under identical keys.
        # An operator's own $DPT_AOT_CACHE (or base_env) wins.
        if self.workload == "serve":
            env.setdefault(
                "DPT_AOT_CACHE", os.path.join(self.run_dir, "aot_cache")
            )
        # per-rank persistent XLA compilation caches: co-launched ranks
        # compiling identical tiny-model entries race a shared cache dir
        # (same reason tests/test_multiprocess.py splits per rank)
        prefix = env.pop("DPT_XLA_CACHE_PREFIX", None)
        if env.get("DPT_AOT_CACHE"):
            # A worker that persists executables to the shared AOT store
            # must NOT also use a persistent XLA compilation cache: an
            # executable rehydrated from that cache serializes WITHOUT
            # its backend kernel symbols, so the store entry it produces
            # is refused ("Symbols not found") by every sibling that
            # tries to load it. The store supersedes the XLA cache here —
            # it persists exactly what the cache would have, fleet-wide.
            env.pop("JAX_COMPILATION_CACHE_DIR", None)
        elif prefix:
            env["JAX_COMPILATION_CACHE_DIR"] = f"{prefix}_rank{rank}"
        return env

    def _worker_argv(self, attempt: int, rank: int = 0,
                     hb_attempt: Optional[int] = None) -> List[str]:
        # hb_attempt pins the heartbeat/timeline directory independently
        # of the flag-selecting attempt index: a serve worker relaunched
        # IN PLACE (attempt > 0 flags, so chaos specs are not re-armed)
        # must keep beating where its surviving siblings still beat
        hb = attempt if hb_attempt is None else hb_attempt
        argv = self.worker_cmd + self.worker_args
        argv += [
            "--heartbeat-dir", self._hb_dir(hb),
            "--heartbeat-interval", str(self.heartbeat_interval_s),
        ]
        if self.trace:
            # one base path per attempt; rank 0 writes it, rank R writes
            # <path>.rankR (train/loop.py for training; serve/cli.py
            # writes per-request span ledgers under the same convention)
            # — merged after the run by the trace hub into one
            # rank/worker-disambiguated Perfetto timeline
            argv += ["--trace-timeline", self._timeline_base(hb)]
        if attempt == 0:
            for spec in self.chaos:
                argv += ["--inject-fault", spec]
        if self.workload == "serve":
            # appended LAST (last occurrence wins): worker R's HTTP
            # front on base+R regardless of a user-passed --port
            argv += ["--port", str(self.base_port + rank)]
            return argv
        # resume from the newest intact retained checkpoint once one
        # exists. Appended LAST so it wins over any user-passed -c
        # (argparse last-occurrence semantics) — a restart must resume
        # THIS job, not reload the user's warm-start weights again.
        if attempt > 0 and _checkpoint_exists(self.checkpoint_dir, self.method_tag):
            argv += ["-c", self.method_tag]
        return argv

    def _hb_dir(self, attempt: int) -> str:
        # fresh beat dir per attempt: stale beats from a torn-down world
        # must never be classified against the relaunched one
        return os.path.join(self.run_dir, f"attempt{attempt}", "heartbeat")

    def _timeline_base(self, attempt: int) -> str:
        return os.path.join(self.run_dir, f"attempt{attempt}",
                            "timeline.jsonl")

    def _merge_timelines(self) -> Optional[str]:
        """Merge every attempt's per-rank timeline JSONL into ONE
        Perfetto trace for the whole supervised job (rank-disambiguated
        tracks; docs/OBSERVABILITY.md). A serve fleet's per-request
        span ledgers merge the same way — its process tracks read
        "worker R" and the result is the fleet timeline (one pane for N
        shared-nothing workers). Never raises — this runs on the report
        path of jobs that may already be failing."""
        if not self.trace:
            return None
        from distributedpytorch_tpu.obs import trace_hub

        pairs: List = []
        for attempt in range(len(self.world_history)):
            pairs.extend(trace_hub.timeline_rank_paths(
                self._timeline_base(attempt)
            ))
        out = os.path.join(self.run_dir, "timeline_merged.json")
        self.merged_timeline = trace_hub.write_merged_trace(
            pairs, out,
            process_label="worker" if self.workload == "serve" else "rank",
        )
        return self.merged_timeline

    def _log_path(self, attempt: int, rank: int) -> str:
        return os.path.join(
            self.run_dir, f"attempt{attempt}", f"rank{rank}.log"
        )

    # ------------------------------------------------------------------
    def _spawn(self, attempt: int, world: int) -> None:
        port = _free_port()
        os.makedirs(self._hb_dir(attempt), exist_ok=True)
        logger.info(
            "elastic attempt %d: launching %d rank(s): %s",
            attempt, world, shlex.join(self._worker_argv(attempt, 0)),
        )
        self._procs = []
        self._log_files = []
        try:
            for rank in range(world):
                log_f = open(self._log_path(attempt, rank), "ab")
                self._log_files.append(log_f)
                self._procs.append(
                    subprocess.Popen(
                        # per-rank argv: identical for training; serve
                        # workers differ by their --port assignment
                        self._worker_argv(attempt, rank),
                        env=self._worker_env(rank, world, port, attempt),
                        cwd=self.cwd,
                        stdout=log_f,
                        stderr=subprocess.STDOUT,
                    )
                )
        except Exception:
            # a spawn failure on rank k (fd exhaustion, ENOMEM) must not
            # orphan ranks 0..k-1: they hold the rendezvous port and
            # would keep mutating checkpoints with no supervisor
            self._teardown()
            raise

    def _exit_codes(self) -> Dict[int, Optional[int]]:
        return {r: p.poll() for r, p in enumerate(self._procs)}

    def _classify(self, attempt: int, world: int, started_at: float):
        return health.classify(
            world,
            health.read_beats(self._hb_dir(attempt)),
            self._exit_codes(),
            timeout_s=self.heartbeat_timeout_s,
            started_at=started_at,
            spawn_timeout_s=self.spawn_timeout_s,
            progress_timeout_s=self.progress_timeout_s,
        )

    def _teardown(self) -> None:
        """Stop every surviving rank: SIGTERM (the trainer checkpoints
        and exits at the next agreed boundary when it can), grace,
        SIGKILL stragglers (a survivor blocked inside a collective its
        dead peer abandoned cannot run its handler)."""
        for p in self._procs:
            if p.poll() is None:
                try:
                    p.send_signal(signal.SIGTERM)
                except OSError:
                    pass
        deadline = time.monotonic() + self.teardown_grace_s
        while time.monotonic() < deadline and any(
            p.poll() is None for p in self._procs
        ):
            time.sleep(0.1)
        for p in self._procs:
            if p.poll() is None:
                p.kill()
        for p in self._procs:
            p.wait()
        for f in getattr(self, "_log_files", []):
            try:
                f.close()
            except OSError:
                pass

    def _relaunch_rank(self, rank: int, attempt: int) -> None:
        """Replace ONE failed serve worker in place — the collective-free
        fleet's siblings keep serving the whole time. Heartbeats and
        timelines stay pinned to the attempt-0 directories (the
        survivors are still writing there); ``attempt`` only selects
        argv flags, so chaos specs are never re-armed on a relaunch."""
        old = self._procs[rank]
        if old.poll() is None:  # hung, not dead: stop it first
            try:
                old.send_signal(signal.SIGTERM)
            except OSError:
                pass
            deadline = time.monotonic() + self.teardown_grace_s
            while time.monotonic() < deadline and old.poll() is None:
                time.sleep(0.05)
            if old.poll() is None:
                old.kill()
        old.wait()
        log_f = open(self._log_path(0, rank), "ab")
        self._log_files.append(log_f)
        try:
            self._procs[rank] = subprocess.Popen(
                self._worker_argv(attempt, rank, hb_attempt=0),
                env=self._worker_env(rank, len(self._procs),
                                     _free_port(), 0),
                cwd=self.cwd,
                stdout=log_f,
                stderr=subprocess.STDOUT,
            )
        except Exception:
            self._teardown()
            raise

    # -- fleet elasticity (serve workload; FleetScaler's actuation) ----------
    def _worker_host(self) -> str:
        return _worker_arg(self.worker_args, ("--host",), "127.0.0.1")

    def _routers(self):
        return [r for r in (self.router, self.standby_router)
                if r is not None]

    def active_serve_ranks(self) -> List[int]:
        """Rank slots currently meant to be serving (spawned and not
        deliberately retired)."""
        return [r for r in range(len(self._procs))
                if r not in self._retired_ranks]

    def spawn_fleet_worker(self) -> Optional[int]:
        """Grow the fleet by ONE worker: reuse the lowest retired rank
        slot (its port base+R and heartbeat slot come back with it) or
        append a fresh rank. Rides the same machinery as a per-rank
        relaunch — attempt-0 beat/timeline dirs, the fleet-shared
        ``$DPT_AOT_CACHE`` (the newcomer loads the executables its
        siblings compiled: ``recompiles: 0``) — then waits for
        ``/healthz`` ready and admits the worker to every router.
        Returns the rank, or None if the spawn failed."""
        # the rank choice is the pure rule the protocol explorer
        # model-checks (serve/control.fleet_spawn_rank): lowest retired
        # slot reused, else a fresh appended rank
        rank = control.fleet_spawn_rank(
            self.active_serve_ranks(), frozenset(self._retired_ranks)
        )
        logger.info("elastic fleet: spawning worker %d (port %d)",
                    rank, self.base_port + rank)
        log_f = open(self._log_path(0, rank), "ab")
        self._log_files.append(log_f)
        world = max(len(self._procs), rank + 1)
        try:
            proc = subprocess.Popen(
                # attempt index 1: chaos specs are armed on attempt 0
                # argv only — a spawned newcomer must not re-fire them
                self._worker_argv(1, rank, hb_attempt=0),
                env=self._worker_env(rank, world, _free_port(), 0),
                cwd=self.cwd,
                stdout=log_f,
                stderr=subprocess.STDOUT,
            )
        except Exception:  # noqa: BLE001 — a failed grow must not kill
            # the fleet that exists
            logger.exception("elastic fleet: spawn of worker %d failed",
                             rank)
            return None
        if rank < len(self._procs):
            self._procs[rank] = proc
        else:
            self._procs.append(proc)
        self._retired_ranks.discard(rank)
        self._grace_until[rank] = time.time() + max(
            self.spawn_timeout_s, self.heartbeat_timeout_s
        )
        host = self._worker_host()
        if self._wait_worker_ready(rank):
            for router in self._routers():
                router.ensure_worker(host, self.base_port + rank)
        else:
            # admit unhealthy: the routers' own probes readmit the
            # moment /healthz answers (slow model load, not a failure)
            for router in self._routers():
                router.ensure_worker(host, self.base_port + rank,
                                     healthy=False)
        obsm.ELASTIC_WORLD_SIZE.set(len(self.active_serve_ranks()))
        return rank

    def _wait_worker_ready(self, rank: int,
                           timeout_s: Optional[float] = None) -> bool:
        import urllib.request

        url = (f"http://{self._worker_host()}:{self.base_port + rank}"
               "/healthz")
        deadline = time.monotonic() + (
            timeout_s if timeout_s is not None else self.spawn_timeout_s
        )
        while time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(url, timeout=2.0) as resp:
                    if resp.status == 200:
                        return True
            except Exception:  # noqa: BLE001 — still booting
                pass
            if self._shutdown.wait(0.1):
                return False
        return False

    def retire_fleet_worker(self) -> Optional[int]:
        """Shrink the fleet by ONE worker: the highest active rank.
        Order matters — eject from every router FIRST (no new
        placements), wait out router-tracked in-flight requests, THEN
        SIGTERM (serve/cli.py drains its own queue on it), grace,
        SIGKILL stragglers. Returns the rank, or None if there is
        nothing retireable."""
        # rank choice + the never-below-one refusal are the pure rule
        # the protocol explorer model-checks (control.fleet_retire_rank);
        # the actuation below follows control.FLEET_RETIRE_ORDER —
        # routers stop placing BEFORE the process dies
        rank = control.fleet_retire_rank(self.active_serve_ranks())
        if rank is None:
            return None
        address = f"{self._worker_host()}:{self.base_port + rank}"
        logger.info("elastic fleet: retiring worker %d (%s)",
                    rank, address)
        for router in self._routers():
            router.retire_worker(
                address, drain_timeout_s=self.teardown_grace_s)
        self._retired_ranks.add(rank)
        proc = self._procs[rank]
        if proc.poll() is None:
            try:
                proc.send_signal(signal.SIGTERM)
            except OSError:
                pass
            deadline = time.monotonic() + self.teardown_grace_s
            while time.monotonic() < deadline and proc.poll() is None:
                time.sleep(0.05)
            if proc.poll() is None:
                proc.kill()
        proc.wait()
        obsm.ELASTIC_WORLD_SIZE.set(len(self.active_serve_ranks()))
        return rank

    def request_stop(self) -> None:
        """Ask a running supervision loop to stop cleanly: tear down the
        workers and return 0 with ``final: stopped``. The serve
        workload's exit path (serve fleets run until told otherwise —
        SIGINT on the CLI, a test's teardown); also honored mid-watch by
        training jobs."""
        self._shutdown.set()

    def _watch(self, attempt: int, world: int) -> Dict[int, health.RankHealth]:
        """Block until the attempt resolves: every rank exits 0 (all-ok
        map) or some rank fails (classified map) — or a clean stop is
        requested (the caller checks ``_shutdown``). Never raises on
        worker behavior — classification is the contract."""
        started_at = time.time()
        while True:
            if self._shutdown.is_set():
                return {r: health.RankHealth(r, "ok") for r in range(world)}
            codes = self._exit_codes()
            if all(rc == 0 for rc in codes.values()):
                # still consult the beats: a desynced world tears itself
                # down CLEANLY (every rank marks its beat, snapshots,
                # and exits 0 via the agreed stop) — all-zero exit codes
                # alone would report that truncated job as success
                verdicts = self._classify(attempt, world, started_at)
                if any(h.failed for h in verdicts.values()):
                    return verdicts
                return {
                    r: health.RankHealth(r, "ok") for r in range(world)
                }
            verdicts = self._classify(attempt, world, started_at)
            # a PEER_FAILURE_EXIT rank is a casualty, not a cause; only
            # treat it as the failure if NO primary failure exists
            primary = {
                r: h for r, h in verdicts.items()
                if h.failed and codes.get(r) != PEER_FAILURE_EXIT
            }
            if primary or any(h.failed for h in verdicts.values()):
                # give one extra beat-interval for a primary failure to
                # surface before blaming a secondary exit
                if not primary:
                    time.sleep(self.heartbeat_interval_s)
                    verdicts = self._classify(attempt, world, started_at)
                return verdicts
            time.sleep(self.poll_interval_s)

    # ------------------------------------------------------------------
    def static_preflight(self) -> List[str]:
        """Run the static distributed-correctness analyzer over this
        job's strategy × schedule BEFORE spawning any rank: a step whose
        collective program is statically broken (deadlocked ppermute
        schedule, rank-divergent collective, dropped gradient reduction)
        would otherwise spawn N ranks that hang until the heartbeat
        window expires, burn the whole restart budget relaunching into
        the same hang, and exit having attributed the failure to
        "hung" ranks instead of the program.

        Returns the findings lines (empty = clean). Scoped to the
        COLLECTIVE layer for this job's strategy × schedule: a source
        lint nit anywhere in the package is CI's gate, not a reason to
        refuse an otherwise-sound launch. Stays jax-free: the analyzer
        runs via the shared runner (analysis/preflight.py — ``python -m
        distributedpytorch_tpu analyze`` in a provisioned CPU
        subprocess), so the supervisor never initializes a backend or
        dials a TPU runtime. Analyzer infrastructure failures (rc !=
        0/1, timeout) return [] — availability first: the supervisor
        must never refuse a launch because the analyzer itself broke.

        Strategies the analyzer doesn't cover (``singleGPU``, the
        multi-process-only ``DDP``) skip the check entirely — same
        rationale as bench_multi's ``_preflight_combos``: nothing to
        verify statically, so don't pay a provisioned analyzer
        subprocess on every launch of a non-collective job."""
        from distributedpytorch_tpu.analysis import ANALYSIS_STRATEGIES
        from distributedpytorch_tpu.analysis.preflight import run_preflight

        if self.workload == "serve":
            # serving is collective-free by construction (independent
            # single-device replica executables — the same reason
            # bench_multi's serve config is in the no-combos class):
            # nothing to verify statically, nothing to pay for
            return []
        from distributedpytorch_tpu.parallel.mesh import is_mesh_spec

        if (
            self.method_tag not in ANALYSIS_STRATEGIES
            and not is_mesh_spec(self.method_tag)
        ):
            return []
        schedule = _worker_arg(
            self.worker_args, ("--pipeline-schedule",), "gpipe",
            abbrev=True,
        )
        rc, findings = run_preflight(
            [self.method_tag], [schedule], self.preflight_timeout_s,
            layer="collectives", base_env=self.base_env, cwd=self.cwd,
            # compare each combo's ordered-collective fingerprint under
            # THIS job's world size: a collective gated on a rank >= 2
            # passes the dual-rank re-trace but would desync an N-rank
            # gloo rendezvous — catch it before the first spawn
            fingerprint_world=self.nprocs,
        )
        if rc == 1:
            return findings
        if rc != 0:
            logger.warning(
                "elastic: static preflight could not run (rc=%d) — "
                "proceeding with the launch: %.300s",
                rc, "; ".join(findings),
            )
        return []

    # ------------------------------------------------------------------
    def _write_report(self, final: Optional[str] = None) -> None:
        os.makedirs(
            os.path.dirname(os.path.abspath(self.report_path)), exist_ok=True
        )
        payload = {
            "restarts": self.restarts,
            "world_history": self.world_history,
            "final": final,
            "attempts": [dataclasses.asdict(a) for a in self.attempts],
        }
        if self.preflight_findings:
            payload["preflight_findings"] = list(self.preflight_findings)
        if self.merged_timeline:
            payload["merged_timeline"] = self.merged_timeline
        tmp = f"{self.report_path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=2)
        os.replace(tmp, self.report_path)

    def run(self) -> int:
        """Supervise to completion. Returns 0 when an attempt finishes
        with every rank at exit 0; 1 when the restart budget is
        exhausted (the report JSON holds the full per-attempt record
        either way); STATIC_CHECK_EXIT (3) when the static preflight
        refused the launch — no rank was spawned and no budget spent."""
        if self.preflight:
            self.preflight_findings = self.static_preflight()
            if self.preflight_findings:
                for line in self.preflight_findings:
                    logger.error("elastic: static preflight: %s", line)
                logger.error(
                    "elastic: refusing to spawn %d rank(s): the step "
                    "fails static distributed-correctness checks (see "
                    "docs/ANALYSIS.md; --no-preflight overrides)",
                    self.nprocs,
                )
                self._write_report(final="static_check_failed")
                return STATIC_CHECK_EXIT
        metrics_server = None
        fleet_scraper = None
        router_httpd = None
        standby_httpd = None
        if self.workload == "serve" and self.router_port is not None:
            # the front door: one address, load-aware placement over
            # worker ports base+R, transparent retry of sheds and
            # SIGKILLed workers (a relaunching worker is a retried
            # sibling, not a client-visible failure). With
            # --router-standby-port, TWO instances run as an
            # active/standby HA pair: both proxy, the standby pulls the
            # active's /admin/state snapshot each probe interval and
            # takes over on the first missed probe — the front door's
            # own death is a client retry to the second address, never
            # an outage.
            from distributedpytorch_tpu.serve.router import (
                Router,
                make_router_http,
            )

            host = self._worker_host()
            workers = [(host, self.base_port + r)
                       for r in range(self.nprocs)]
            peer = ((host, self.router_standby_port)
                    if self.router_standby_port is not None else None)
            self.router = Router(workers, role="active",
                                 peer=peer).start()
            router_httpd = make_router_http(
                self.router, host=host, port=self.router_port,
            )
            threading.Thread(
                target=router_httpd.serve_forever, daemon=True,
                name="dpt-router-http",
            ).start()
            if self.router_standby_port is not None:
                self.standby_router = Router(
                    workers, role="standby",
                    peer=(host, self.router_port),
                ).start()
                standby_httpd = make_router_http(
                    self.standby_router, host=host,
                    port=self.router_standby_port,
                )
                threading.Thread(
                    target=standby_httpd.serve_forever, daemon=True,
                    name="dpt-router-standby-http",
                ).start()
            logger.info(
                "elastic: router front door on http://%s:%d%s over %d "
                "worker(s) — POST /predict, POST /admin/ab, GET /stats",
                host, router_httpd.server_address[1],
                (f" (+ standby on :{self.router_standby_port})"
                 if standby_httpd is not None else ""),
                self.nprocs,
            )
        if self.workload == "serve" and (
                self.fleet_plan is not None
                or self.fleet_max_workers is not None):
            self.fleet_scaler = FleetScaler(
                self, plan=self.fleet_plan,
                min_workers=self.fleet_min_workers,
                max_workers=self.fleet_max_workers,
            )
            if self.fleet_interval_s > 0:
                self.fleet_scaler.start(self.fleet_interval_s)
        if self.metrics_port is not None:
            from distributedpytorch_tpu.obs.http import start_metrics_server

            expose_fn = None
            if self.workload == "serve" and self.base_port is not None:
                # the fleet pane: scrape every worker's /metrics and
                # re-expose the families merged + worker-labeled on the
                # supervisor's own port — one scrape target for N
                # shared-nothing workers (docs/SERVING.md)
                from distributedpytorch_tpu.obs.registry import (
                    REGISTRY,
                    merge_expositions,
                )

                host = _worker_arg(self.worker_args, ("--host",),
                                   "127.0.0.1")
                def _fan_sweep(seen):
                    # BOTH routers place off the same per-worker
                    # numbers: the standby's placement state is
                    # reconstructed from this sweep, not from the
                    # active — part of why failover is stateless
                    for router in self._routers():
                        router.ingest_fleet_metrics(seen)

                fleet_scraper = FleetMetricsScraper(
                    host, self.base_port,
                    # dynamic: the fleet scaler may have grown the
                    # world past nprocs (retired ranks scrape as dead
                    # and drop out of the pane, which is correct)
                    lambda: (len(self._procs) if self._procs
                             else self.nprocs),
                    # the router places off the SAME per-worker numbers
                    # this pane collects: each sweep feeds it queue
                    # depths (and marks non-answering workers stale)
                    on_sweep=(_fan_sweep if self._routers() else None),
                ).start()
                self.fleet_scraper = fleet_scraper

                def expose_fn():
                    return merge_expositions(
                        REGISTRY.expose(), fleet_scraper.latest(),
                    )

            metrics_server = start_metrics_server(
                self.metrics_port, expose_text_fn=expose_fn,
            )
            logger.info("elastic: serving /metrics on port %d%s",
                        metrics_server.port,
                        " (fleet pane: merged worker-labeled families)"
                        if fleet_scraper is not None else "")
        try:
            if self.workload == "serve":
                return self._run_supervised_serve()
            return self._run_supervised()
        except KeyboardInterrupt:
            # the serve workload's normal exit (fleets run until told
            # otherwise); for training it is the operator's call either
            # way — tear down and record a clean stop, not a failure
            logger.info("elastic: interrupted — stopping the fleet")
            self.request_stop()
            self._teardown()
            self._write_report(final="stopped")
            return 0
        finally:
            if self.fleet_scaler is not None:
                self.fleet_scaler.stop()
            if fleet_scraper is not None:
                fleet_scraper.stop()
            if router_httpd is not None:
                router_httpd.shutdown()
            if standby_httpd is not None:
                standby_httpd.shutdown()
            if self.router is not None:
                self.router.stop()
            if self.standby_router is not None:
                self.standby_router.stop()
            if metrics_server is not None:
                metrics_server.close()

    def _run_supervised_serve(self) -> int:
        """Supervision for the collective-free serve fleet: a failed
        worker is relaunched ALONE, in place, while its siblings keep
        serving — behind the router front door the relaunch gap is a
        retried sibling, never a fleet-wide outage. Training keeps the
        whole-world restart (``_run_supervised``): a torn collective
        cannot be healed per rank. The restart budget counts relaunch
        WAVES (one wave may replace several workers), and the attempt
        ledger records one failed entry per wave so reports read the
        same as training's. The world only changes DELIBERATELY here —
        through the fleet scaler's spawn/retire (a retired rank's death
        is the plan, not a failure); unplanned deaths are relaunches."""
        world = self.nprocs
        attempt = 0
        self.world_history.append(world)
        obsm.ELASTIC_WORLD_SIZE.set(world)
        t0 = time.monotonic()
        self._spawn(0, world)
        started_at = time.time()
        # a just-relaunched/spawned worker's stale beat (or missing
        # beat while it re-warms off the AOT store) must not read as a
        # new death; shared with spawn_fleet_worker, hence an attribute
        grace_until = self._grace_until
        while True:
            # the fleet scaler may have grown/shrunk the world
            if len(self._procs) != world:
                world = len(self._procs)
                self.world_history.append(world)
            if self._shutdown.is_set():
                codes = self._exit_codes()
                self._teardown()
                self.attempts.append(AttemptResult(
                    attempt=attempt, world=world, ok=True, failures=[],
                    exit_codes=codes,
                    duration_s=time.monotonic() - t0,
                ))
                self._merge_timelines()
                self._write_report(final="stopped")
                logger.info(
                    "elastic serve fleet stopped on request: %d "
                    "relaunch wave(s), world %d", self.restarts, world,
                )
                return 0
            codes = self._exit_codes()
            verdicts = self._classify(0, world, started_at)
            now = time.time()
            failed: Dict[int, health.RankHealth] = {}
            for r in range(world):
                if r in self._retired_ranks:
                    continue  # dead by design — the scaler retired it
                alive = codes.get(r) is None
                if alive and now < grace_until.get(r, 0.0):
                    continue
                # ANY exit is a failure here: a serve worker runs until
                # the supervisor says stop, even exit 0 means capacity
                # silently left the fleet
                if verdicts[r].failed or not alive:
                    failed[r] = verdicts[r]
            if not failed:
                time.sleep(self.poll_interval_s)
                continue
            lines = health.format_failures(
                {r: verdicts[r] for r in failed}
            )
            for r in sorted(failed):
                if not verdicts[r].failed:
                    lines.append(
                        f"rank {r}: dead (exited {codes.get(r)} — a "
                        "serve worker runs until stopped)"
                    )
            self.attempts.append(AttemptResult(
                attempt=attempt, world=world, ok=False, failures=lines,
                exit_codes=codes, duration_s=time.monotonic() - t0,
            ))
            obsm.ELASTIC_ATTEMPTS.labels(outcome="failed").inc()
            for r, h in failed.items():
                obsm.ELASTIC_RANK_FAILURES.labels(
                    failure_class=h.state
                ).inc()
                flight.record("rank_failure", rank=r, state=h.state,
                              epoch=h.epoch, step=h.step)
            for line in lines:
                logger.error("%s", line)
            if self.restarts >= self.max_restarts:
                self._teardown()
                self._merge_timelines()
                self._write_report(final="failed")
                flight.dump(
                    "elastic_budget_exhausted",
                    path=os.path.join(self.run_dir,
                                      "flight_supervisor.json"),
                    extra={"failures": lines,
                           "world_history": self.world_history},
                )
                logger.error(
                    "elastic serve fleet failed: restart budget (%d) "
                    "exhausted; per-rank logs under %s",
                    self.max_restarts, self.run_dir,
                )
                return 1
            self.restarts += 1
            obsm.ELASTIC_RESTARTS.inc()
            attempt += 1
            t0 = time.monotonic()
            backoff = self.restart_backoff_s * (2.0 ** (self.restarts - 1))
            logger.warning(
                "elastic serve: relaunching worker(s) %s in place "
                "(restart %d/%d; siblings keep serving) in %.1fs",
                sorted(failed), self.restarts, self.max_restarts, backoff,
            )
            if self._shutdown.wait(backoff):
                continue
            for r in sorted(failed):
                self._relaunch_rank(r, attempt)
                grace_until[r] = time.time() + max(
                    self.spawn_timeout_s, self.heartbeat_timeout_s
                )
            self._write_report(final=None)

    def _run_supervised(self) -> int:
        world = self.nprocs
        attempt = 0
        consecutive_fails = {r: 0 for r in range(world)}
        while True:
            self.world_history.append(world)
            obsm.ELASTIC_WORLD_SIZE.set(world)
            t0 = time.monotonic()
            self._spawn(attempt, world)
            verdicts = self._watch(attempt, world)
            if self._shutdown.is_set():
                # snapshot BEFORE teardown (same reason as the failure
                # path below): a healthy worker this stop is about to
                # SIGTERM must not be recorded as if it died on its own
                codes = self._exit_codes()
                self._teardown()
                self.attempts.append(AttemptResult(
                    attempt=attempt, world=world, ok=True, failures=[],
                    exit_codes=codes,
                    duration_s=time.monotonic() - t0,
                ))
                self._merge_timelines()
                self._write_report(final="stopped")
                logger.info(
                    "elastic job stopped on request: %d restart(s), "
                    "world history %s", self.restarts, self.world_history,
                )
                return 0
            failed = {r: h for r, h in verdicts.items() if h.failed}
            # snapshot exit codes BEFORE teardown: a healthy survivor the
            # supervisor is about to SIGTERM must not be recorded as if
            # it died on its own (the report would contradict its own
            # failure lines)
            codes = self._exit_codes()
            self._teardown()
            lines = health.format_failures(verdicts)
            self.attempts.append(
                AttemptResult(
                    attempt=attempt,
                    world=world,
                    ok=not failed,
                    failures=lines,
                    exit_codes=codes,
                    duration_s=time.monotonic() - t0,
                )
            )
            obsm.ELASTIC_ATTEMPTS.labels(
                outcome="ok" if not failed else "failed"
            ).inc()
            for h in failed.values():
                obsm.ELASTIC_RANK_FAILURES.labels(
                    failure_class=h.state
                ).inc()
                flight.record("rank_failure", rank=h.rank, state=h.state,
                              epoch=h.epoch, step=h.step)
            if not failed:
                self._merge_timelines()
                self._write_report(final="ok")
                logger.info(
                    "elastic job complete: %d restart(s), world history %s",
                    self.restarts, self.world_history,
                )
                return 0
            # the per-rank error summary (docs/RELIABILITY.md): one line
            # per failed rank, not a wall of survivor tracebacks
            for line in lines:
                logger.error("%s", line)
            if self.restarts >= self.max_restarts:
                self._merge_timelines()
                self._write_report(final="failed")
                flight.dump(
                    "elastic_budget_exhausted",
                    path=os.path.join(self.run_dir, "flight_supervisor.json"),
                    extra={"failures": lines,
                           "world_history": self.world_history},
                )
                logger.error(
                    "elastic job failed: restart budget (%d) exhausted; "
                    "per-rank logs under %s",
                    self.max_restarts, self.run_dir,
                )
                return 1
            # elastic world size: a rank index that failed
            # rank_fail_limit consecutive attempts is a lost slot.
            # PEER_FAILURE_EXIT ranks are casualties of someone else's
            # failure, not failing slots — counting them would shrink
            # the world by every healthy rank that died OF the one bad
            # slot.
            for r in range(world):
                slot_failed = r in failed and codes.get(r) != PEER_FAILURE_EXIT
                consecutive_fails[r] = (
                    consecutive_fails.get(r, 0) + 1 if slot_failed else 0
                )
            lost = sum(
                1 for r in range(world)
                if consecutive_fails.get(r, 0) >= self.rank_fail_limit
            )
            new_world = max(self.min_ranks, world - lost)
            if new_world != world:
                logger.warning(
                    "elastic: %d slot(s) failed %d consecutive attempt(s) — "
                    "relaunching on %d rank(s) (was %d); the checkpoint "
                    "reshards onto the smaller mesh",
                    lost, self.rank_fail_limit, new_world, world,
                )
                world = new_world
                consecutive_fails = {r: 0 for r in range(world)}
            self.restarts += 1
            obsm.ELASTIC_RESTARTS.inc()
            self._write_report(final=None)
            backoff = self.restart_backoff_s * (2.0 ** (self.restarts - 1))
            logger.warning(
                "elastic: relaunching (restart %d/%d, world %d) in %.1fs",
                self.restarts, self.max_restarts, world, backoff,
            )
            time.sleep(backoff)
            attempt += 1


def main(argv: Optional[Sequence[str]] = None) -> int:
    """``python -m distributedpytorch_tpu elastic -n N [opts] -- <train
    args...>`` — the torchrun-shaped launch surface (reference
    README.md:37), with supervision."""
    ap = argparse.ArgumentParser(
        prog="python -m distributedpytorch_tpu elastic",
        description="Elastic supervisor: spawn N ranks, detect failures "
        "via heartbeats, relaunch from the newest intact checkpoint "
        "(possibly at a smaller world size).",
    )
    ap.add_argument("-n", "--nprocs", type=int, required=True,
                    help="Worker ranks to launch")
    ap.add_argument("--workload", type=str, default="train",
                    choices=["train", "serve"],
                    help="What the workers are: 'train' (the training "
                         "CLI, checkpoint-resumed relaunches) or "
                         "'serve' (serve/cli.py HTTP workers, one per "
                         "--port base+rank; no resume, no preflight — "
                         "a dead dispatch loop is a relaunch, not an "
                         "outage)")
    ap.add_argument("--min-ranks", type=int, default=1,
                    help="Never relaunch below this world size")
    ap.add_argument("--max-restarts", type=int, default=3,
                    help="Relaunch budget (exponential backoff between)")
    ap.add_argument("--heartbeat-timeout", type=float, default=10.0,
                    help="Beat-file age (s) beyond which a live rank is hung")
    ap.add_argument("--heartbeat-interval", type=float, default=0.5,
                    help="Worker beat cadence (s); passed to workers")
    ap.add_argument("--progress-timeout", type=float, default=0.0,
                    help="Step-progress age (s) beyond which a rank is hung "
                         "(0 = off; set above compile/eval duration)")
    ap.add_argument("--spawn-timeout", type=float, default=300.0,
                    help="Grace (s) for a worker to write its first beat")
    ap.add_argument("--restart-backoff", type=float, default=1.0,
                    help="Base relaunch backoff (doubles per restart)")
    ap.add_argument("--teardown-grace", type=float, default=10.0,
                    help="SIGTERM→SIGKILL grace for survivors")
    ap.add_argument("--rank-fail-limit", type=int, default=2,
                    help="Consecutive failures before a slot is dropped")
    ap.add_argument("--run-dir", type=str, default="./elastic_run",
                    help="Heartbeats, per-rank logs, report.json")
    ap.add_argument("--report", type=str, default=None,
                    help="Report JSON path (default <run-dir>/report.json)")
    ap.add_argument("--cpu-devices", type=int, default=0,
                    help="Give each rank an N-device virtual CPU mesh "
                         "(drills/tests; 0 = inherit the real backend)")
    ap.add_argument("--chaos", action="append", default=[],
                    metavar="SITE[@RANK]:EPOCH:STEP[:COUNT]",
                    help="Arm a fault (--inject-fault) on the FIRST "
                         "attempt only — drills the detect/relaunch path "
                         "without re-killing the relaunched job")
    ap.add_argument("--no-preflight", action="store_true",
                    help="Skip the static distributed-correctness "
                         "preflight (python -m distributedpytorch_tpu "
                         "analyze over this job's strategy/schedule in a "
                         "CPU subprocess) that otherwise runs before any "
                         "rank is spawned")
    ap.add_argument("--preflight-timeout", type=float, default=300.0,
                    help="Preflight subprocess budget (s); an analyzer "
                         "that cannot run never blocks the launch")
    ap.add_argument("--no-trace", action="store_true",
                    help="Do not arm per-rank step timelines "
                         "(--trace-timeline) or merge them into the "
                         "run's Perfetto trace (<run-dir>/"
                         "timeline_merged.json)")
    ap.add_argument("--metrics-port", type=int, default=None,
                    help="Serve the supervisor's Prometheus /metrics "
                         "(restarts, world size, per-rank failure "
                         "classes) on this port; with --workload serve "
                         "this becomes the FLEET pane — every worker's "
                         "/metrics scraped and re-exposed merged with "
                         "worker=\"R\" labels (one scrape target for "
                         "the whole fleet)")
    ap.add_argument("--router-port", type=int, default=None,
                    help="With --workload serve: front the fleet on ONE "
                         "address — an HTTP router proxying /predict "
                         "across the workers with load-aware placement, "
                         "transparent retry of 503s and dead workers, "
                         "and POST /admin/ab fan-out (serve/router.py)")
    ap.add_argument("--router-standby-port", type=int, default=None,
                    help="With --router-port: run a SECOND router as an "
                         "active/standby HA pair on this port. Both "
                         "proxy /predict; the standby pulls the "
                         "active's /admin/state snapshot every probe "
                         "interval and takes over on the first missed "
                         "probe — clients keep both addresses and fail "
                         "over on connection refusal (no VIP; "
                         "docs/SERVING.md 'Front door HA')")
    ap.add_argument("--fleet-plan", type=str, default=None,
                    help="dpt_serve_plan JSON for the FLEET scaler: the "
                         "supervisor spawns/retires whole serve workers "
                         "to match the plan's replica recommendation "
                         "for the observed arrival rate, every decision "
                         "citing its plan-serve grid point")
    ap.add_argument("--fleet-min-workers", type=int, default=1,
                    help="Fleet scaler floor (never retire below)")
    ap.add_argument("--fleet-max-workers", type=int, default=None,
                    help="Fleet scaler ceiling; setting it (or "
                         "--fleet-plan) enables the fleet scaler")
    ap.add_argument("--fleet-interval", type=float, default=10.0,
                    help="Fleet scaler control-window cadence (s); "
                         "<= 0 leaves the scaler manual (tests/ops "
                         "drive .step() directly)")
    ap.add_argument("worker_args", nargs=argparse.REMAINDER,
                    help="Training CLI args (prefix with --)")
    args = ap.parse_args(argv)

    worker_args = list(args.worker_args)
    if worker_args and worker_args[0] == "--":
        worker_args = worker_args[1:]

    logging.basicConfig(level=logging.INFO, format="%(message)s")
    sup = ElasticSupervisor(
        worker_args,
        nprocs=args.nprocs,
        min_ranks=args.min_ranks,
        max_restarts=args.max_restarts,
        heartbeat_timeout_s=args.heartbeat_timeout,
        heartbeat_interval_s=args.heartbeat_interval,
        progress_timeout_s=args.progress_timeout,
        spawn_timeout_s=args.spawn_timeout,
        restart_backoff_s=args.restart_backoff,
        teardown_grace_s=args.teardown_grace,
        rank_fail_limit=args.rank_fail_limit,
        run_dir=args.run_dir,
        report_path=args.report,
        cpu_devices=args.cpu_devices,
        chaos=args.chaos,
        preflight=not args.no_preflight,
        preflight_timeout_s=args.preflight_timeout,
        trace=not args.no_trace,
        metrics_port=args.metrics_port,
        workload=args.workload,
        router_port=args.router_port,
        router_standby_port=args.router_standby_port,
        fleet_plan=args.fleet_plan,
        fleet_min_workers=args.fleet_min_workers,
        fleet_max_workers=args.fleet_max_workers,
        fleet_interval_s=args.fleet_interval,
    )
    return sup.run()


if __name__ == "__main__":
    sys.exit(main())
