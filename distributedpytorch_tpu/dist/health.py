"""Rank health: heartbeats, failure classification, step agreement.

TorchElastic's agent watches its workers (the reference launches via
`torchrun`, README.md:37, whose modern form IS TorchElastic); the JAX
runtime has no such layer — a SIGKILLed rank leaves its peers blocked
inside a gloo/XLA collective until some distant channel deadline, with
no record of WHO died or WHERE. This module is the detection half of
the elastic runtime (`dist/elastic.py` is the supervision half):

  * **Heartbeat** (worker side): a daemon thread that writes a per-rank
    beat file (JSON: pid, epoch, step, wall time, status) every
    ``interval_s`` seconds. The trainer's step loop only assigns two
    integer attributes per iteration (`update`) — no host sync, no
    collective, nothing on the step critical path. Files are written
    atomically (tmp + rename) so a reader never sees a torn beat.
  * **read_beats / classify** (supervisor side): parse the beat
    directory and classify every expected rank as ``ok`` / ``dead``
    (its process exited) / ``hung`` (process alive but no beat within
    the timeout) / ``desynced`` (beat-marked by the trainer's step
    agreement, or epoch counters more than one epoch apart — legal skew
    is bounded by the per-epoch collectives, so a larger gap means a
    rank is no longer executing the same program).
  * **format_failures**: the one-line-per-rank summary
    (``rank R: <dead|hung|desynced> at epoch:step``) that replaces the
    wall of channel-shaped tracebacks every survivor used to print.

The same beats cover BOTH supervised workloads (dist/elastic.py):
training ranks tick from the step loop (epoch/step = training
coordinates), serve workers tick from the dispatch loop (epoch stays
0, ``step`` counts completed requests, ``timed`` is true from the
first turn — AOT compiles happen before serving starts). Serve-shaped
failure maps onto the existing verdicts with no new states: a dead
worker process is ``dead``, a frozen process is ``hung`` via beat age,
and a wedged serve pipeline — dispatch stuck in a device call,
completions stalled until every in-flight slot is held — stops the
dispatch loop's ticks while the beat thread survives, which is exactly
the stale-``progress_time`` ``hung`` verdict (the epoch-skew desync
rule is vacuous at constant epoch 0).

Deliberately jax-free: the supervisor imports this before any backend
initializes, and the classifier must be unit-testable with fabricated
beats (tests/test_health.py).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import time
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)

#: Classifier states, in display-priority order.
STATES = ("ok", "dead", "hung", "desynced")

#: Legal epoch skew between live ranks: the per-epoch collectives (stop
#: agreement, eval, checkpoint gather) bound how far ahead a healthy
#: rank can run — more than one epoch apart means divergent programs.
MAX_EPOCH_SKEW = 1


def beat_path(directory: str, rank: int) -> str:
    return os.path.join(directory, f"rank_{int(rank)}.beat")


@dataclasses.dataclass
class Beat:
    """One parsed beat file (the worker's last self-report).

    ``time`` is when the beat THREAD last wrote (stops only if the whole
    process is frozen — the thread survives a step loop wedged inside a
    native call); ``progress_time`` is when the STEP LOOP last called
    ``update`` (stops the moment the loop stops making progress, which
    is how a hang inside a collective actually presents)."""

    rank: int
    pid: int
    epoch: int = 0
    step: int = 0
    time: float = 0.0
    progress_time: float = 0.0
    # does the progress timeout apply? The trainer mirrors the step
    # watchdog's exemption: the FIRST executed epoch compiles every
    # executable shape (minutes on a cold cache) with zero step
    # progress, so a hang verdict there would kill healthy jobs —
    # progress is judged only once the worker says it is in steady
    # state. False for beats that never say (stubs, old formats).
    timed: bool = False
    status: str = "ok"  # "ok" | "desynced" (set by the step agreement)

    @property
    def coords(self) -> str:
        return f"{self.epoch}:{self.step}"


@dataclasses.dataclass
class RankHealth:
    """Classifier verdict for one rank."""

    rank: int
    state: str  # one of STATES
    epoch: int = 0
    step: int = 0
    detail: str = ""

    @property
    def failed(self) -> bool:
        return self.state != "ok"


class Heartbeat:
    """Worker-side beat writer: one daemon thread, one file per rank.

    ``update(epoch, step)`` is the ONLY per-step call and does two
    attribute assignments — the file write happens on the thread at
    ``interval_s`` cadence (plus once immediately at start, so a rank
    that wedges during its very first compile still registers as alive-
    then-hung rather than never-launched). ``mark(status)`` lets the
    trainer flag a classified condition (desync) for the supervisor."""

    def __init__(self, directory: str, rank: int, interval_s: float = 1.0):
        self.directory = str(directory)
        self.rank = int(rank)
        self.interval_s = max(0.05, float(interval_s))
        self.epoch = 0
        self.step = 0
        self.progress_time = time.time()
        self.timed = False
        self.status = "ok"
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # mark() writes from the trainer thread while the beat thread
        # writes on its interval; both share one tmp name (keyed by
        # pid), so unserialized writes could rename a torn beat into
        # place
        self._write_lock = threading.Lock()

    # -- trainer-facing (hot path: attribute assignments only) --------------
    def update(self, epoch: int, step: int) -> None:
        self.epoch = epoch
        self.step = step
        self.progress_time = time.time()

    def mark(self, status: str) -> None:
        self.status = status
        self._write()  # a classified failure must not wait out the interval

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "Heartbeat":
        os.makedirs(self.directory, exist_ok=True)
        self._write()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"dpt-heartbeat-r{self.rank}"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        self._write()  # final beat: the exit coordinates

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self._write()

    def _write(self) -> None:
        payload = {
            "rank": self.rank,
            "pid": os.getpid(),
            "epoch": int(self.epoch),
            "step": int(self.step),
            "time": time.time(),
            "progress_time": self.progress_time,
            "timed": bool(self.timed),
            "status": self.status,
        }
        path = beat_path(self.directory, self.rank)
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with self._write_lock:
                with open(tmp, "w") as f:
                    json.dump(payload, f)
                os.replace(tmp, path)
        except OSError:  # beat loss is tolerable; crashing the rank is not
            logger.debug("heartbeat write failed", exc_info=True)


def read_beats(directory: str) -> Dict[int, Beat]:
    """Parse every rank's beat file; unreadable/torn files are skipped
    (the atomic write makes that a transient, not a corruption)."""
    beats: Dict[int, Beat] = {}
    try:
        names = os.listdir(directory)
    except OSError:
        return beats
    for name in names:
        if not (name.startswith("rank_") and name.endswith(".beat")):
            continue
        try:
            with open(os.path.join(directory, name)) as f:
                d = json.load(f)
            beat = Beat(
                rank=int(d["rank"]),
                pid=int(d.get("pid", 0)),
                epoch=int(d.get("epoch", 0)),
                step=int(d.get("step", 0)),
                time=float(d.get("time", 0.0)),
                progress_time=float(d.get("progress_time", d.get("time", 0.0))),
                timed=bool(d.get("timed", False)),
                status=str(d.get("status", "ok")),
            )
        except (OSError, ValueError, KeyError):
            continue
        beats[beat.rank] = beat
    return beats


def classify(
    world: int,
    beats: Dict[int, Beat],
    exited: Dict[int, Optional[int]],
    timeout_s: float,
    now: Optional[float] = None,
    started_at: Optional[float] = None,
    spawn_timeout_s: Optional[float] = None,
    progress_timeout_s: float = 0.0,
) -> Dict[int, RankHealth]:
    """Classify every rank of an N-rank job.

    ``exited`` maps rank → exit code (None while the process is still
    running) — the supervisor knows this from ``Popen.poll()``, which is
    both faster and more certain than any beat-derived inference, so a
    dead process wins over everything. Precedence per rank:

      1. **dead** — process exited nonzero (or by signal: negative rc);
         a clean 0 exit is ``ok`` (the job may legitimately finish).
      2. **desynced** — the rank's own step agreement marked it, or its
         epoch counter is > :data:`MAX_EPOCH_SKEW` behind the most
         advanced LIVE rank (collectives bound legal skew).
      3. **hung** — live process, but (a) the newest beat is older than
         ``timeout_s`` (whole process frozen: SIGSTOP, GIL-held wedge —
         the beat thread itself survives a step loop stuck inside a
         native call), or (b) ``progress_timeout_s`` > 0 and the step
         loop has not advanced within it (a hang inside a collective
         presents exactly this way: the beat stays fresh, progress
         stops), or (c) no beat was EVER written within
         ``spawn_timeout_s`` of ``started_at`` (worker died before
         reaching the trainer; only judged when ``started_at`` given).
      4. **ok** otherwise.

    Detection latency is bounded: a dead rank is seen at the next
    supervisor poll; a hung rank within its timeout + one poll; a
    desynced rank at its next per-epoch agreement (which `mark`\\ s the
    beat immediately).
    """
    now = time.time() if now is None else now
    spawn_timeout_s = timeout_s if spawn_timeout_s is None else spawn_timeout_s
    live_epochs = [
        b.epoch for r, b in beats.items()
        if r < world and exited.get(r) is None
    ]
    frontier = max(live_epochs) if live_epochs else 0
    out: Dict[int, RankHealth] = {}
    for rank in range(world):
        beat = beats.get(rank)
        epoch = beat.epoch if beat else 0
        step = beat.step if beat else 0
        rc = exited.get(rank)
        hung_detail = None
        if beat is None:
            if started_at is not None and now - started_at > spawn_timeout_s:
                hung_detail = f"no beat within {spawn_timeout_s:.0f}s of launch"
        elif now - beat.time > timeout_s:
            hung_detail = f"last beat {now - beat.time:.1f}s ago"
        elif (
            progress_timeout_s > 0
            and beat.timed  # steady state only — see Beat.timed
            and now - beat.progress_time > progress_timeout_s
        ):
            hung_detail = (
                f"no step progress for {now - beat.progress_time:.1f}s"
            )
        if rc is not None and rc != 0:
            detail = f"signal {-rc}" if rc < 0 else f"exit {rc}"
            out[rank] = RankHealth(rank, "dead", epoch, step, detail)
        elif beat is not None and beat.status == "desynced":
            out[rank] = RankHealth(
                rank, "desynced", epoch, step, "step agreement diverged"
            )
        elif (
            rc is None
            and beat is not None
            and frontier - beat.epoch > MAX_EPOCH_SKEW
        ):
            out[rank] = RankHealth(
                rank, "desynced", epoch, step,
                f"epoch {beat.epoch} vs live frontier {frontier}",
            )
        elif rc is None and hung_detail is not None:
            out[rank] = RankHealth(rank, "hung", epoch, step, hung_detail)
        else:
            out[rank] = RankHealth(rank, "ok", epoch, step)
    return out


def format_failures(health: Dict[int, RankHealth]) -> List[str]:
    """The single-line per-rank failure summary (docs/RELIABILITY.md):
    ``rank R: <dead|hung|desynced> at epoch:step (detail)`` — what the
    supervisor prints INSTEAD of every survivor's channel tracebacks."""
    lines = []
    for rank in sorted(health):
        h = health[rank]
        if not h.failed:
            continue
        detail = f" ({h.detail})" if h.detail else ""
        lines.append(f"rank {h.rank}: {h.state} at {h.epoch}:{h.step}{detail}")
    return lines
