"""The serve loop: queue → placement worker → replica dispatch →
completion drain — wrapped in an in-process supervisor that relaunches
a dead dispatch core instead of turning it into an outage.

The request path is the PR-1 training pipeline turned inference-side —
the same three-thread overlap, with the same discipline about WHO is
allowed to block on a device value:

* **ingress** (caller threads / HTTP handlers): decode + preprocess
  (``SampleCache``-backed), consult the Clipper-style prediction cache
  (serve/cache.py), admit into the :class:`BatchingQueue`. Rejections
  resolve the request future immediately with a status — overload is an
  answer, not an exception.
* **placement worker** (``utils/prefetch.pipelined_placement`` — the
  PR-1 machinery verbatim): claims a replica in-flight SLOT, stacks +
  pads the flushed group into its bucket shape, and ``device_put``s it
  — all ``depth`` buckets ahead of dispatch, so bucket N+1's H2D rides
  under bucket N's execution. Slots return at *completion* (``pull``),
  so claiming one here doubles as backpressure: when every slot is
  taken, the placement worker blocks, the queue coalesces toward fuller
  buckets, and total work-in-system stays bounded — overload surfaces
  as admission rejections, never as a silently growing device queue.
* **dispatch loop** (``_dispatch_loop``): pops placed buckets and fires
  the replica's AOT executable. It NEVER blocks on a device value — no
  ``np.asarray``, no ``.item()``, no ``block_until_ready`` (dptlint's
  ``serve-hot-path`` rule enforces exactly this scope; ``pull`` is the
  sanctioned drain).
* **completion workers** (``pull``): block on the device result, slice
  off pad rows, split per request, threshold to masks, resolve futures,
  stamp metrics. Per-request accounting lives entirely here — the
  dispatch loop stays sync-free.

**Self-healing** (``_supervise``): the dispatch loop dying used to be a
terminal event — every pending future failed and the server answered
``shutdown`` until a human restarted the process. Now it is a blip: the
dying incarnation still resolves every in-flight future (``error``,
never a hang), then the supervisor thread rebuilds the core — a fresh
:class:`BatchingQueue` + dispatch thread against the same AOT-compiled
engine — after exponential backoff, up to ``restart_limit`` times.
During the gap ``submit`` answers :data:`REJECT_RELAUNCHING` (HTTP 503
with ``Retry-After`` — "back off and retry HERE, soon") and ``/healthz``
reports ``ready: false``; budget exhausted → the server goes terminal
(``shutdown`` — "retry elsewhere") so a process-level supervisor
(``elastic --workload serve``) can relaunch the whole worker. Chaos
sites ``serve_dispatch_death`` / ``serve_replica_wedge`` /
``serve_decode`` (utils/faults.py) make every one of these paths
deterministically drillable on CPU.
"""

from __future__ import annotations

import concurrent.futures
import dataclasses
import logging
import os
import queue as queue_mod
import threading
import time
from typing import List, Optional

import numpy as np

from distributedpytorch_tpu.obs import defs as obsm
from distributedpytorch_tpu.obs import flight
from distributedpytorch_tpu.obs.reqtrace import ReqTracer
from distributedpytorch_tpu.serve.bucketing import stack_group
from distributedpytorch_tpu.serve.cache import PredictionCache, request_key
from distributedpytorch_tpu.serve.engine import Replica, ServeEngine
from distributedpytorch_tpu.serve.metrics import ServeMetrics
from distributedpytorch_tpu.serve.queue import (
    REJECT_SHUTDOWN,
    BatchingQueue,
    ServeRequest,
)
from distributedpytorch_tpu.utils import faults
from distributedpytorch_tpu.utils.prefetch import SINGLE, pipelined_placement

logger = logging.getLogger(__name__)

STATUS_OK = "ok"
STATUS_REJECTED = "rejected"
STATUS_ERROR = "error"
STATUS_SHUTDOWN = "shutdown"

#: Rejection reason while the dispatch core is between incarnations:
#: "this instance will be back in under a second — back off and retry
#: HERE" (vs ``shutdown``'s "retry elsewhere"). Surfaces as HTTP 503
#: with a ``Retry-After`` header.
REJECT_RELAUNCHING = "relaunching"

#: Server lifecycle states (``/stats`` ``state`` field, readiness).
STATE_SERVING = "serving"
STATE_RELAUNCHING = "relaunching"
STATE_STOPPED = "stopped"

#: _place's "this group already failed and was resolved" marker: the
#: dispatch loop skips it and keeps serving (None means "stopping" and
#: ends the loop — a single bad batch must not take the server down).
_PLACE_FAILED = object()


@dataclasses.dataclass
class ServeResponse:
    """What a request's future resolves to. ``masks`` is one
    ``(H, W) uint8 {0, 255}`` array per submitted image (None unless
    status == "ok"). ``cached`` marks prediction-cache hits."""

    key: str
    status: str
    reason: str = ""
    masks: Optional[List[np.ndarray]] = None
    latency_ms: float = 0.0
    cached: bool = False
    # the ingress-assigned trace id (obs/reqtrace.py): echoed as
    # X-Request-Id by the HTTP front, the join key into the slow-request
    # log / flight ring / Perfetto timeline
    request_id: str = ""

    @property
    def ok(self) -> bool:
        return self.status == STATUS_OK


def pull(server: "Server", replica: Replica, out, bucket: int,
         reqs: List[ServeRequest], dispatch_t: float,
         dispatch_version: int = -1) -> None:
    """Completion drain (sanctioned sync point): block on the device
    result, fan masks back out to request futures, record metrics — and
    only THEN return the replica's in-flight slot. Freeing the slot at
    completion (not at dispatch) is what bounds work-in-system: on an
    async runtime a dispatch returns immediately, and a slot freed there
    would let the device execution queue absorb unbounded backlog that
    the admission cap never sees — overload latency would grow without
    a single rejection."""
    try:
        probs = np.asarray(out)  # device→host; blocks until compute done
        done_t = server.clock()
        # per-bucket service-time profile: one observation per executed
        # bucket (the calibration input plan-serve replays traces
        # against), tagged with the flush reason the queue stamped
        first_trace = next(
            (req.trace for req in reqs if req.trace is not None), None,
        )
        server.tracer.record_dispatch(
            bucket, sum(req.size for req in reqs),
            device_exec_s=done_t - dispatch_t,
            flush_reason=(
                first_trace.flush_reason if first_trace is not None else None
            ),
        )
        row = 0
        for req in reqs:
            masks = [
                server.engine.postprocess(probs[row + i])
                for i in range(req.size)
            ]
            row += req.size
            cache_key = getattr(req, "cache_key", None)
            if (cache_key is not None
                    and server.predict_cache is not None
                    and dispatch_version == req.cache_version):
                # the mask is cacheable only when the weights version
                # the DISPATCH actually used (read in the dispatch loop,
                # not here — a rollback completing before this drain
                # would lie) equals the version the key was scoped to:
                # a canary-computed mask must never land under the
                # promoted version's key, even if the canary has since
                # rolled back
                server.predict_cache.put(cache_key, masks)
            resolve_t = server.clock()
            if req.trace is not None:
                req.trace.mark("device_done", done_t)
                req.trace.mark("resolved", resolve_t)
            server.metrics.record_request(
                req.size, req.enqueue_t, dispatch_t, done_t,
                request_id=req.request_id, arm=req.arm,
            )
            req.future.set_result(ServeResponse(
                key=req.key, status=STATUS_OK, masks=masks,
                latency_ms=(done_t - req.enqueue_t) * 1e3,
                request_id=req.request_id,
            ))
            # close the ledger AFTER the future resolves: the drain span
            # honestly covers slice/threshold/fan-out
            server.tracer.complete(req.trace, STATUS_OK, t=resolve_t)
        server._completed += len(reqs)  # heartbeat progress (serve beats)
        timeline = server.tracer.timeline
        if timeline is not None:
            # the drain is the sanctioned blocking context: JSONL spans
            # append once per completed GROUP, like training's per-step
            # flush cadence
            timeline.flush()
    except Exception as exc:  # noqa: BLE001 — a drain failure must fail
        logger.exception("completion drain failed for bucket %d", bucket)
        for req in reqs:  # the requests, never hang their futures
            if not req.future.done():
                server.metrics.record_failure(arm=req.arm)
                req.future.set_result(ServeResponse(
                    key=req.key, status=STATUS_ERROR, reason=str(exc),
                    request_id=req.request_id,
                ))
                server.tracer.complete(req.trace, STATUS_ERROR)
    finally:
        server._free.put(replica)
        # capacity just freed: wake the queue so an eager flush happens
        # now instead of at the next waiter timeout / SLO deadline
        server.queue.kick()


class Server:
    """In-process serving core. The HTTP layer (serve/cli.py) and the
    load generator (tools/bench_serve.py) both drive exactly this
    object, so what the bench measures is what production runs."""

    def __init__(
        self,
        engine: ServeEngine,
        slo_ms: float = 50.0,
        hard_cap_images: Optional[int] = None,
        placement_depth: int = 2,
        completion_workers: Optional[int] = None,
        eager_when_idle: bool = True,
        inflight_per_replica: int = 2,
        restart_limit: int = 3,
        restart_backoff_s: float = 0.25,
        predict_cache_mb: int = 0,
        slow_request_ms: float = 0.0,
        latency_slo_ms: Optional[float] = None,
        timeline=None,
        clock=time.monotonic,
    ):
        self.engine = engine
        self.clock = clock
        self.metrics = ServeMetrics(clock=clock)
        # request-scoped tracing (obs/reqtrace.py, docs/OBSERVABILITY.md
        # "Request tracing"): span ledgers, per-phase attribution, SLO
        # burn-rate windows, per-bucket service-time profiles.
        # latency_slo_ms defaults to 2x the batching SLO (the burn
        # windows' good-request bound); slow_request_ms <= 0 defaults to
        # 2x that again (the structured-log threshold).
        self.tracer = ReqTracer(
            slo_s=float(slo_ms) / 1e3,
            latency_slo_s=(
                float(latency_slo_ms) / 1e3
                if latency_slo_ms is not None else None
            ),
            slow_s=(
                float(slow_request_ms) / 1e3
                if slow_request_ms and slow_request_ms > 0 else None
            ),
            clock=clock,
            timeline=timeline,
        )
        self.slo_ms = float(slo_ms)
        self.hard_cap_images = hard_cap_images
        self.queue = self._new_queue()
        self.placement_depth = int(placement_depth)
        self.eager_when_idle = bool(eager_when_idle)
        # In-process supervision: how many dispatch-core relaunches this
        # server may spend over its lifetime (the elastic supervisor owns
        # the process-level budget above this), and the base backoff
        # (doubles per consecutive restart).
        self.restart_limit = int(restart_limit)
        self.restart_backoff_s = float(restart_backoff_s)
        self.core_restarts = 0
        self.predict_cache = (
            PredictionCache(int(predict_cache_mb) * 2**20)
            if predict_cache_mb and predict_cache_mb > 0 else None
        )
        # The in-flight slot pool: each replica appears
        # ``inflight_per_replica`` times, a slot is claimed at placement
        # and returned at COMPLETION (see ``pull``). 2 slots/replica =
        # one bucket executing + one queued behind it on the device, so
        # H2D and compute overlap without the device queue becoming an
        # unbounded latency buffer.
        self._free: queue_mod.Queue = queue_mod.Queue()
        self.inflight_per_replica = max(1, int(inflight_per_replica))
        for _slot in range(self.inflight_per_replica):
            for replica in engine.replicas:
                self._free.put(replica)
        # all-slots-free is the drain test for "nothing in flight":
        # slots return at completion, AFTER futures resolve
        self._total_slots = self._free.qsize()
        # serializes resize_replicas against itself (the scaler thread
        # and an /admin caller must not race the slot-pool surgery)
        self._resize_lock = threading.Lock()
        if completion_workers is None:
            # every in-flight slot must be drainable concurrently, or the
            # drain pool (not the devices) becomes the throughput ceiling
            completion_workers = len(engine.replicas) * max(
                1, int(inflight_per_replica)
            )
        self._completion = concurrent.futures.ThreadPoolExecutor(
            max_workers=max(1, completion_workers),
            thread_name_prefix="dpt-serve-pull",
        )
        self._stop = threading.Event()
        self._gen_stop = threading.Event()  # current incarnation's stop
        self._state = STATE_SERVING
        self._thread: Optional[threading.Thread] = None
        self._dispatch_error: Optional[BaseException] = None
        self._dispatch_seq = 0  # chaos-site step coordinate
        self._completed = 0  # requests served; heartbeat step counter
        self.heartbeat = None  # dist/health.Heartbeat when supervised
        self.rollout = None  # serve/rollout.RolloutManager when attached
        self.abtest = None  # serve/rollout.ABTest when attached
        self.scaler = None  # serve/scaler.ReplicaScaler when attached
        # sustained-A/B replica-group map ({"a": indices, "b": indices})
        # set by ABTest.start / cleared by ABTest.stop; None = no A/B.
        # _claim_replica filters the slot pool through it so an armed
        # batch only ever lands on its own arm's replicas.
        self.ab_arms = None
        self.config = None  # set by from_config; /healthz fingerprint
        # serve/sim.ArrivalRecorder when --record-arrivals is set: one
        # bounded JSONL line per ingress (wall-time, rows, bucket) —
        # the recorded-trace input the plan-serve capacity simulator
        # replays; attached by serve/cli.py or the bench, closed by stop
        self.arrival_recorder = None

    def _new_queue(self) -> BatchingQueue:
        return BatchingQueue(
            self.engine.planner, slo_s=self.slo_ms / 1e3,
            hard_cap_images=self.hard_cap_images, clock=self.clock,
        )

    # -- lifecycle -----------------------------------------------------------
    @property
    def state(self) -> str:
        return self._state

    @property
    def ready(self) -> bool:
        """Readiness (vs liveness): accepting AND serving traffic now.
        False while the dispatch core is between incarnations, after the
        restart budget is spent, during shutdown — and while a rollout
        canary is being health-watched (the LB hint that this instance
        is mid-experiment; requests are still answered)."""
        if self._state != STATE_SERVING:
            return False
        rollout = self.rollout
        return rollout is None or not rollout.canarying

    def start(self) -> "Server":
        if self._thread is not None:
            raise RuntimeError("server already started")
        self._thread = threading.Thread(
            target=self._supervise, name="dpt-serve-supervise", daemon=True
        )
        self._thread.start()
        return self

    def _supervise(self) -> None:
        """Run dispatch-core incarnations until a clean stop or the
        restart budget is spent. Each incarnation gets its own
        ``BatchingQueue`` and stop event; the engine (the expensive AOT
        state) is shared across all of them — a relaunch costs a backoff
        sleep, never a recompile."""
        while True:
            gen_stop = self._gen_stop
            self._dispatch_error = None
            self._state = STATE_SERVING
            self._dispatch_loop(self.queue, gen_stop)
            if self._stop.is_set() or self._dispatch_error is None:
                return  # clean stop() — not a failure
            self.core_restarts += 1
            obsm.SERVE_CORE_RESTARTS.inc()
            if self.core_restarts > self.restart_limit:
                self._state = STATE_STOPPED
                logger.error(
                    "serve dispatch core died %d times — restart budget "
                    "(%d) exhausted; going terminal (a process-level "
                    "supervisor should relaunch this worker)",
                    self.core_restarts, self.restart_limit,
                )
                flight.record("serve_core_terminal",
                              restarts=self.core_restarts)
                self._stop.set()
                return
            self._state = STATE_RELAUNCHING
            backoff = self.restart_backoff_s * (
                2.0 ** (self.core_restarts - 1)
            )
            logger.warning(
                "serve dispatch core died (%s) — relaunching in %.2fs "
                "(restart %d/%d)",
                type(self._dispatch_error).__name__, backoff,
                self.core_restarts, self.restart_limit,
            )
            flight.record("serve_core_relaunch",
                          restart=self.core_restarts, backoff_s=backoff)
            hb = self.heartbeat
            if hb is not None:
                # the relaunch IS progress: keep the supervisor's
                # stale-progress verdict for wedges, not for recoveries
                hb.update(0, self._completed)
            if self._stop.wait(backoff):
                return
            # fresh incarnation: new queue (the old one is stopped) +
            # new stop event; the slot pool self-restores — every error
            # path of the dead incarnation returned its slot
            self.queue = self._new_queue()
            self._gen_stop = threading.Event()

    def stop(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop serving. ``drain=True`` first waits for the queue to
        empty and in-flight buckets to complete; still-pending requests
        after the deadline (or with ``drain=False``) resolve with a
        ``shutdown`` status — a stopping server never hangs a client."""
        if drain:
            # wall-clock on purpose (NOT self.clock): the drain advances
            # by real sleeps, so an injected fake clock would never reach
            # a deadline computed from itself. Draining means BOTH the
            # queue is empty AND every in-flight slot has returned — a
            # group already flushed into the placement pipeline is out
            # of the queue but not yet served, and cutting it off at
            # depth==0 would shutdown-resolve work the drain budget was
            # there to finish.
            limit = time.monotonic() + timeout
            while (time.monotonic() < limit
                   and self._dispatch_error is None
                   and not self._stop.is_set()
                   and (self.queue.depth_images > 0
                        or self._free.qsize() < self._total_slots)):
                time.sleep(0.01)
        self._stop.set()
        self._gen_stop.set()
        self._state = STATE_STOPPED
        # fleet components attached by serve/cli.attach_fleet (watcher
        # and autoscale are plain attrs — absent on bare servers)
        for attr in ("watcher", "scaler", "autoscale", "abtest", "rollout"):
            component = getattr(self, attr, None)
            if component is not None:
                component.stop()
        if self.arrival_recorder is not None:
            self.arrival_recorder.close()
        for req in self.queue.stop():
            if not req.future.done():
                req.future.set_result(ServeResponse(
                    key=req.key, status=STATUS_SHUTDOWN, reason="shutdown",
                    request_id=req.request_id,
                ))
                self.tracer.complete(req.trace, STATUS_SHUTDOWN)
        if self._thread is not None:
            self._thread.join(timeout=timeout)
        self._completion.shutdown(wait=True)
        timeline = self.tracer.timeline
        if timeline is not None:
            timeline.flush()

    # -- ingress -------------------------------------------------------------
    def submit(self, images, key: str = "",
               request_id: Optional[str] = None, arm: str = "",
               ) -> "concurrent.futures.Future":
        """Admit one request. ``images``: a single ``(H, W, C)`` row, a
        ``(k, H, W, C)`` stack, a list of rows, or a list of path
        strings / PIL images (decoded through the engine's cache). The
        future ALWAYS resolves to a :class:`ServeResponse` — rejection
        and shutdown included. ``request_id`` is the caller-supplied
        trace id (W3C ``traceparent`` at the HTTP front); None assigns
        one — every response carries it, and every 503 path stamps it
        into the flight ring with its reason. ``arm`` pins the request
        to a sustained-A/B replica group (the router's ``X-AB-Arm``
        header); empty with an A/B running, the server derives it from
        the request id so direct clients split deterministically too."""
        future: concurrent.futures.Future = concurrent.futures.Future()
        trace = self.tracer.begin(request_id=request_id)
        rid = trace.request_id if trace is not None else (request_id or "")
        abtest = self.abtest
        if abtest is not None and abtest.active:
            if not arm or arm not in (self.ab_arms or ()):
                arm = abtest.arm_for(rid)
        else:
            arm = ""
        recorder = self.arrival_recorder
        state = self._state
        if state != STATE_SERVING:
            if recorder is not None:
                # the relaunch-gap/shutdown 503s are OFFERED load too —
                # a trace missing them would replay an optimistically
                # thinned overload (rows best-effort: no decode here)
                recorder.record(time.time(), self._estimate_rows(images))
            # between dispatch-core incarnations ("retry here shortly")
            # or terminally stopped ("retry elsewhere") — either way an
            # immediate answer, never a queue entry a dead core strands
            reason = (REJECT_RELAUNCHING if state == STATE_RELAUNCHING
                      else REJECT_SHUTDOWN)
            status = (STATUS_REJECTED if state == STATE_RELAUNCHING
                      else STATUS_SHUTDOWN)
            self.metrics.record_rejection(reason, arm=arm)
            self.tracer.reject(trace, reason, request_id=rid, state=state)
            future.set_result(ServeResponse(
                key=key, status=status, reason=reason, request_id=rid,
            ))
            return future
        try:
            faults.maybe_raise_transient("serve_decode")
            rows = self._as_rows(images)
        except Exception as exc:  # noqa: BLE001 — bad input is a response
            if recorder is not None:
                recorder.record(time.time(), self._estimate_rows(images))
            self.metrics.record_failure(arm=arm)
            self.tracer.complete(trace, STATUS_ERROR)
            future.set_result(ServeResponse(
                key=key, status=STATUS_ERROR, reason=str(exc),
                request_id=rid,
            ))
            return future
        if recorder is not None:
            # record at INGRESS, before admission: a capacity replay
            # needs the offered load, shed requests included
            recorder.record(
                time.time(), len(rows), shape=rows[0].shape,
                bucket=self.engine.planner.bucket_for(len(rows)),
            )
        cache_key = None
        cache_version = 0
        # a canary in flight forces prediction-cache bypass (one key,
        # two answers) — remembered so a shed during the bypass window
        # is attributable to it in the flight ring
        cache_bypassed = (
            self.predict_cache is not None and self.engine.versions_mixed
        )
        if cache_bypassed:
            self.predict_cache.record_bypass()
        if self.predict_cache is not None and not cache_bypassed:
            cache_version = self.engine.weights_version
            cache_key = request_key(rows, cache_version)
            cached = self.predict_cache.get(cache_key)
            if cached is not None:
                self.metrics.record_cached(len(rows))
                self.tracer.complete(trace, "cached")
                future.set_result(ServeResponse(
                    key=key, status=STATUS_OK, masks=list(cached),
                    latency_ms=0.0, cached=True, request_id=rid,
                ))
                return future
        req = ServeRequest(images=rows, future=future, key=key,
                           request_id=rid, trace=trace,
                           cache_key=cache_key, cache_version=cache_version,
                           arm=arm)
        reason = self.queue.submit(req)
        if reason is not None:
            if reason == REJECT_SHUTDOWN and self._state != STATE_STOPPED:
                # the dispatch core died between our state check and the
                # queue admit: this instance is RELAUNCHING, not going
                # away — don't send the client elsewhere over a blip
                reason = REJECT_RELAUNCHING
            self.metrics.record_rejection(reason, arm=arm)
            self.tracer.reject(trace, reason, request_id=rid,
                               rows=len(rows), cache_bypassed=cache_bypassed)
            # a stopping server answers "shutdown" (retry elsewhere),
            # not "overloaded" (back off and retry HERE)
            status = (STATUS_SHUTDOWN if reason == REJECT_SHUTDOWN
                      else STATUS_REJECTED)
            future.set_result(ServeResponse(
                key=key, status=status, reason=reason, request_id=rid,
            ))
        return future

    def retry_after_s(self, reason: str) -> int:
        """The HTTP ``Retry-After`` hint for a 503: a relaunching core
        is back after its backoff; an overloaded queue drains within
        ~an SLO; a stopping server wants clients gone for good — give
        the LB a few seconds to notice."""
        if reason == REJECT_RELAUNCHING:
            # mirror _supervise's computation for the CURRENT gap —
            # core_restarts was already incremented when it began
            backoff = self.restart_backoff_s * (
                2.0 ** max(0, self.core_restarts - 1)
            )
            return max(1, int(backoff + 0.999))
        if reason == REJECT_SHUTDOWN:
            return 5
        return max(1, int(self.slo_ms / 1e3 + 0.999))

    def _as_rows(self, images) -> List[np.ndarray]:
        if isinstance(images, np.ndarray):
            if images.ndim == 3:
                return [self.engine.preprocess(images)]
            if images.ndim == 4:
                return [self.engine.preprocess(row) for row in images]
            raise ValueError(f"expected 3- or 4-d array, got {images.shape}")
        if isinstance(images, (list, tuple)):
            return [self.engine.preprocess(src) for src in images]
        return [self.engine.preprocess(images)]  # path / PIL image

    @staticmethod
    def _estimate_rows(images) -> int:
        """Best-effort row count for arrival recording on paths that
        never decode (relaunch-gap 503s, undecodable bodies) — shape
        arithmetic only, mirroring ``_as_rows``'s dispatch. The common
        HTTP single-image case is exact (1)."""
        if isinstance(images, np.ndarray):
            return images.shape[0] if images.ndim == 4 else 1
        if isinstance(images, (list, tuple)):
            return max(1, len(images))
        return 1

    # -- the serve pipeline --------------------------------------------------
    def _bucket_stream(self, queue: BatchingQueue, gen_stop: threading.Event):
        """Flushed groups as prefetch work items. ``eager`` tracks free
        capacity: with an idle replica, batching must never add latency
        (work-conserving); with all replicas busy, the queue keeps
        coalescing toward fuller buckets. The flag is a callable so a
        slot freed MID-wait (``pull`` kicks the queue) flips eager on
        immediately instead of the request waiting out its SLO.

        Each loop iteration ticks the serve worker's heartbeat (two
        attribute assignments — dist/health.Heartbeat.update): the loop
        turns every <=0.25 s when healthy (idle included), so a wedged
        pipeline — dispatch stuck in a device call, completions stalled
        until every slot is held — stops the ticks and the elastic
        supervisor's progress timeout classifies the worker hung."""

        def eager() -> bool:
            return self.eager_when_idle and not self._free.empty()

        while not (gen_stop.is_set() or self._stop.is_set()):
            hb = self.heartbeat
            if hb is not None:
                hb.update(0, self._completed)
            got = queue.wait_for_work(timeout=0.25, eager=eager)
            if got is not None:
                yield (SINGLE, got)

    def _place(self, kind: str, payload):
        """Placement worker: claim a replica (backpressure), stack + pad
        to the bucket shape, H2D onto the replica's device."""
        bucket, reqs = payload
        # placement-transition marker (ring slot only; dptlint's
        # obs-hot-path/serve-hot-path rules keep anything blocking out)
        flight.record("serve_place", bucket=bucket, reqs=len(reqs))
        # groups are arm-pure by construction (the queue flushes only
        # head same-arm runs), so the first request names the group's arm
        replica = self._claim_replica(arm=reqs[0].arm)
        if replica is None:  # stopping — these were already popped from
            # the queue, so queue.stop() will never see them: resolve
            # here or their futures hang forever
            for req in reqs:
                if not req.future.done():
                    req.future.set_result(ServeResponse(
                        key=req.key, status=STATUS_SHUTDOWN,
                        reason="shutdown", request_id=req.request_id,
                    ))
                    self.tracer.complete(req.trace, STATUS_SHUTDOWN)
            return None
        try:
            rows = [row for req in reqs for row in req.images]
            batch = stack_group(rows, bucket)
            placed = replica, self.engine.place(replica, batch), bucket, reqs
            placed_t = self.clock()
            for req in reqs:
                if req.trace is not None:
                    # placement span ends here: slot-claim backpressure
                    # + stack/pad + H2D all attributed to `placement`
                    req.trace.mark("placed", placed_t)
            return placed
        except BaseException as exc:  # noqa: BLE001 — contain to the group:
            # resolve ITS futures and return the claimed slot; letting
            # this propagate through the prefetch worker would kill the
            # loop with the group's futures unresolved and the slot lost
            logger.exception("placement failed for bucket %d", bucket)
            self._free.put(replica)
            self.queue.kick()
            for req in reqs:
                if not req.future.done():
                    self.metrics.record_failure()
                    req.future.set_result(ServeResponse(
                        key=req.key, status=STATUS_ERROR, reason=str(exc),
                        request_id=req.request_id,
                    ))
                    self.tracer.complete(req.trace, STATUS_ERROR)
            return _PLACE_FAILED

    def _claim_replica(self, arm: str = "") -> Optional[Replica]:
        # reads the CURRENT incarnation's stop event from self: the
        # supervisor only replaces it after this incarnation's stream is
        # fully drained, so a worker parked here always sees its own
        while not (self._gen_stop.is_set() or self._stop.is_set()):
            try:
                replica = self._free.get(timeout=0.1)
            except queue_mod.Empty:
                continue
            arms = self.ab_arms
            if arm and arms is not None and arm in arms:
                if replica.index not in arms[arm]:
                    # wrong arm's slot: return it and keep waiting for
                    # one of ours — the put wakes any sibling claimer,
                    # and the pause keeps a fully-busy arm from spinning
                    # this thread hot against its own put-backs
                    self._free.put(replica)
                    time.sleep(0.002)
                    continue
            return replica
        return None

    # -- live replica-group scaling (serve/scaler.py's actuator) -------------
    def resize_replicas(self, target: int, timeout: float = 30.0) -> int:
        """Grow or shrink the LIVE replica group to ``target`` without a
        restart — the autoscaler's actuator, also callable directly.

        Grow: ``engine.add_replica()`` per step (an AOT-store hit makes
        each one a load, not a compile) and seed its in-flight slots
        into the pool — the very next flush can land on it. Shrink:
        claim the victim replica's slots OUT of the pool first (waiting
        for in-flight dispatches to drain them back), so the replica is
        provably idle before ``engine.retire_replica()`` drops it.
        Returns the replica count actually reached; a shrink that
        cannot drain the victim within ``timeout`` puts everything back
        and stops there — serving correctness over scale-down punctuality.
        Refuses (no-op) while replica groups serve mixed weight
        versions: resizing would cut across a canary or A/B group."""
        with self._resize_lock:
            target = max(1, int(target))
            if target != self.engine.num_replicas and (
                    self.engine.versions_mixed or self.ab_arms is not None):
                logger.warning(
                    "resize to %d refused: replica groups are pinned "
                    "(rollout canary or A/B in flight)", target,
                )
                return self.engine.num_replicas
            while self.engine.num_replicas < target:
                replica = self.engine.add_replica()
                # the completion pool was sized for the construction-time
                # replica count; raise its ceiling so the new slots stay
                # drainable concurrently (threads spawn lazily)
                self._completion._max_workers = max(
                    self._completion._max_workers,
                    (self.engine.num_replicas * self.inflight_per_replica),
                )
                for _slot in range(self.inflight_per_replica):
                    self._free.put(replica)
                self._total_slots += self.inflight_per_replica
                self.queue.kick()
            while self.engine.num_replicas > target:
                victim = self.engine.replicas[-1]
                held = 0
                deadline = time.monotonic() + timeout
                while held < self.inflight_per_replica:
                    if time.monotonic() > deadline or self._stop.is_set():
                        for _ in range(held):
                            self._free.put(victim)
                        logger.warning(
                            "shrink to %d aborted: replica %d still has "
                            "in-flight work after %.0fs",
                            target, victim.index, timeout,
                        )
                        self.queue.kick()
                        return self.engine.num_replicas
                    try:
                        replica = self._free.get(timeout=0.1)
                    except queue_mod.Empty:
                        continue
                    if replica is victim:
                        held += 1  # slot leaves the pool for good
                    else:
                        # hand non-victim slots straight back — serving
                        # continues at full strength during the drain;
                        # the pause keeps this from spinning against its
                        # own put-back
                        self._free.put(replica)
                        time.sleep(0.002)
                self._total_slots -= self.inflight_per_replica
                self.engine.retire_replica()
                self.queue.kick()
            obsm.SERVE_REPLICAS.set(self.engine.num_replicas)
            return self.engine.num_replicas

    def _dispatch_loop(self, queue: BatchingQueue,
                       gen_stop: threading.Event) -> None:
        stream = pipelined_placement(
            self._bucket_stream(queue, gen_stop), self._place,
            depth=self.placement_depth, name="dpt-serve-place",
        )
        try:
            for _item, placed in stream:
                if placed is None:
                    break
                if placed is _PLACE_FAILED:  # group already resolved
                    continue
                replica, x_dev, bucket, reqs = placed
                try:
                    self._dispatch_seq += 1
                    if faults.fire("serve_dispatch_death",
                                   step=self._dispatch_seq):
                        raise faults.InjectedFault(
                            "injected serve_dispatch_death"
                        )
                    if faults.fire("serve_replica_wedge",
                                   step=self._dispatch_seq):
                        # what a hung device call looks like from the
                        # host: the loop stops turning, beats go stale
                        time.sleep(float(
                            os.environ.get("DPT_FAULT_HANG_S", "600")
                        ))
                    dispatch_t = self.clock()
                    for req in reqs:
                        if req.trace is not None:
                            # dispatch_wait ends here — a wedged
                            # replica/predecessor stalling the loop is
                            # what this span catches
                            req.trace.mark("dispatched", dispatch_t)
                    flight.record("serve_dispatch", bucket=bucket,
                                  reqs=len(reqs))
                    out = self.engine.run(replica, x_dev)
                    # read AFTER run: the executable captured
                    # replica.variables inside run, and swap_weights
                    # writes version-then-variables, so this pair can
                    # race only toward (old vars, new version) — a
                    # skipped cache put, never a poisoned one
                    dispatch_version = replica.weights_version
                    self.metrics.record_dispatch(
                        bucket, sum(req.size for req in reqs)
                    )
                    self._completion.submit(
                        pull, self, replica, out, bucket, reqs,
                        dispatch_t, dispatch_version,
                    )
                except BaseException:
                    # the group in hand would otherwise die with the
                    # loop, its futures unresolved (queue.stop() below
                    # can't see it — it left the queue at flush time)
                    self._free.put(replica)
                    for req in reqs:
                        if not req.future.done():
                            self.metrics.record_failure()
                            req.future.set_result(ServeResponse(
                                key=req.key, status=STATUS_ERROR,
                                reason="dispatch failed",
                                request_id=req.request_id,
                            ))
                            self.tracer.complete(req.trace, STATUS_ERROR)
                    raise
        except BaseException as exc:  # noqa: BLE001 — fail pending futures
            self._dispatch_error = exc
            logger.exception("serve dispatch loop died")
            # the serving tier's post-mortem artifact: the ring's tail
            # shows the flush/place/dispatch sequence that killed the loop
            flight.dump("serve_dispatch_death",
                        extra={"error": f"{type(exc).__name__}: "
                                        f"{str(exc)[:200]}"})
            # end THIS incarnation only (_supervise decides whether the
            # server relaunches or goes terminal) — the drain below is
            # finite because gen_stop ends _bucket_stream
            gen_stop.set()
            for req in queue.stop():
                if not req.future.done():
                    req.future.set_result(ServeResponse(
                        key=req.key, status=STATUS_ERROR, reason=str(exc),
                        request_id=req.request_id,
                    ))
                    self.tracer.complete(req.trace, STATUS_ERROR)
        finally:
            # Groups flushed from the queue but still buffered in the
            # placement pipeline when the loop exits would otherwise
            # vanish with their futures unresolved (queue.stop() never
            # sees them — they were already popped). Every exit path has
            # a stop event set (break only follows a stop-time placement
            # miss; normal exhaustion means _bucket_stream already
            # returned), so the stream is finite: drain it and resolve
            # stragglers.
            exc = self._dispatch_error
            status = STATUS_ERROR if exc is not None else STATUS_SHUTDOWN
            reason = str(exc) if exc is not None else "shutdown"
            for _item, placed in stream:
                if placed is None or placed is _PLACE_FAILED:
                    continue
                replica, _x_dev, _bucket, reqs = placed
                self._free.put(replica)
                for req in reqs:
                    if not req.future.done():
                        req.future.set_result(ServeResponse(
                            key=req.key, status=status, reason=reason,
                            request_id=req.request_id,
                        ))
                        self.tracer.complete(req.trace, status)

    # -- factory -------------------------------------------------------------
    @classmethod
    def from_config(cls, cfg, engine: Optional[ServeEngine] = None,
                    **overrides) -> "Server":
        """Build from a :class:`~distributedpytorch_tpu.config.ServeConfig`.
        Pass ``engine`` to reuse one already compiled (bench sweeps reuse
        a single engine across server configurations); otherwise the
        checkpoint fields drive ``engine_from_checkpoint``."""
        if engine is None:
            from distributedpytorch_tpu.ops.kernels import get_kernel_policy
            from distributedpytorch_tpu.serve.engine import (
                engine_from_checkpoint,
            )

            engine = engine_from_checkpoint(
                cfg.checkpoint,
                checkpoint_dir=cfg.checkpoint_dir,
                image_size=cfg.image_size,
                model_arch=cfg.model_arch,
                model_widths=cfg.model_widths,
                s2d_levels=cfg.s2d_levels,
                quantize=getattr(cfg, "quantize", None),
                bucket_sizes=cfg.bucket_sizes,
                replicas=cfg.replicas,
                threshold=cfg.threshold,
                host_cache_mb=cfg.host_cache_mb,
                # resolve from the whole config so cfg.kernel_priors
                # (and the legacy/env fallbacks) gate engagement exactly
                # like training — the engine accepts a resolved policy
                kernels=get_kernel_policy(cfg),
                aot_cache=getattr(cfg, "aot_cache", None),
            )
        kwargs = dict(
            slo_ms=cfg.slo_ms,
            hard_cap_images=cfg.queue_cap_images,
            placement_depth=cfg.placement_depth,
            completion_workers=cfg.completion_workers,
            eager_when_idle=cfg.eager_when_idle,
            inflight_per_replica=cfg.inflight_per_replica,
            restart_limit=getattr(cfg, "restart_limit", 3),
            restart_backoff_s=getattr(cfg, "restart_backoff_s", 0.25),
            predict_cache_mb=getattr(cfg, "predict_cache_mb", 0),
            slow_request_ms=getattr(cfg, "slow_request_ms", 0.0),
            latency_slo_ms=getattr(cfg, "latency_slo_ms", None),
        )
        kwargs.update(overrides)
        server = cls(engine, **kwargs)
        server.config = cfg  # /healthz fingerprints the config it runs
        return server

    # -- observability -------------------------------------------------------
    def stats(self) -> dict:
        snap = self.metrics.snapshot()
        snap.update({
            "queue_depth_images": self.queue.depth_images,
            "queue_max_depth_images": self.queue.max_depth_seen,
            "queue_hard_cap_images": self.queue.hard_cap_images,
            "replicas": self.engine.num_replicas,
            "buckets": list(self.engine.planner.sizes),
            # fleet & rollout additions (docs/SERVING.md): which weight
            # generation answers, whether this core is between
            # incarnations, and the prediction cache's story
            "weights_version": self.engine.weights_version,
            "state": self._state,
            "core_restarts": self.core_restarts,
            "predict_cache": (
                self.predict_cache.snapshot()
                if self.predict_cache is not None else None
            ),
            # request-tracing additions (obs/reqtrace.py): per-phase
            # tail-latency attribution, slow-request count, SLO burn
            # state, and the p99 window's exemplar trace ids
            "attribution": self.tracer.snapshot_attribution(
                exemplars=self.metrics.p99_exemplars()
            ),
            # AOT executable store (utils/aotstore.py): this engine
            # build's cold-start story — hit/miss/skew per bucket
            # executable, plus how many compiles actually ran
            "aot_cache": self.engine.aot_cache_stats,
            # sustained A/B + autoscaler (absent as None when unused):
            # per-arm ledgers and the scale decisions with the plan
            # points they executed — the front door's /stats provenance
            "ab": (self.abtest.status()
                   if self.abtest is not None else None),
            "scaler": (self.scaler.status()
                       if self.scaler is not None else None),
        })
        return snap
