"""Padded-batch bucket planning for the serving tier.

An AOT-compiled executable accepts exactly one input shape, so the
server can only ever dispatch a small fixed set of batch shapes — the
*buckets*. Requests coalesce FIFO into a bucket, the batch pads up to
the bucket size with zero rows, and the compiled executable for that
exact shape runs; pad rows are sliced off before postprocessing.
The planner here is pure shape arithmetic (no jax): which bucket a
coalesced group rides, and how much padding that costs — the queue
(serve/queue.py) owns *when* to flush, the planner owns *what shape*.

Why a fixed ladder instead of compiling per observed batch size: every
novel shape is a fresh XLA compile — seconds to minutes on TPU — paid at
request time, exactly the latency cliff AOT compilation exists to
remove. ``len(bucket_sizes)`` compiles happen once at server start;
after that no request ever waits on a compiler.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np


class BucketPlanner:
    """An ascending ladder of batch sizes, e.g. ``(1, 2, 4, 8)``.

    ``bucket_for(n)`` → the smallest bucket holding ``n`` rows (None when
    ``n`` exceeds the largest bucket — the caller rejects such requests
    at admission, so an oversized batch can never reach a compiled
    executable and die on a shape mismatch mid-dispatch).
    """

    def __init__(self, bucket_sizes: Sequence[int]):
        sizes = sorted({int(b) for b in bucket_sizes})
        if not sizes or sizes[0] < 1:
            raise ValueError(f"bucket_sizes must be positive: {bucket_sizes!r}")
        self.sizes: Tuple[int, ...] = tuple(sizes)

    @property
    def max_size(self) -> int:
        return self.sizes[-1]

    def bucket_for(self, n: int) -> Optional[int]:
        """Smallest bucket with capacity >= n rows; None if n is too big."""
        for b in self.sizes:
            if n <= b:
                return b
        return None

    def largest_full_bucket(self, n: int) -> int:
        """Largest bucket that ``n`` rows can FILL (>= the smallest bucket
        even when n can't fill it — something must be dispatchable). The
        overload path uses this: padding is wasted compute, and under
        overload wasted compute is the thing being shed."""
        best = self.sizes[0]
        for b in self.sizes:
            if b <= n:
                best = b
        return best

    def padding_cost(self, n: int) -> int:
        """Pad rows a group of n rides with (0 when n is exactly a bucket)."""
        b = self.bucket_for(n)
        return 0 if b is None else b - n


def pad_batch(rows: np.ndarray, bucket: int) -> np.ndarray:
    """``(n, H, W, C)`` stacked rows → ``(bucket, H, W, C)`` with zero pad
    rows appended. The model is per-sample in eval mode (convs + eval
    BatchNorm never mix rows), so pad rows cost compute but cannot
    perturb real rows' results."""
    n = rows.shape[0]
    if n == bucket:
        return rows
    if n > bucket:
        raise ValueError(f"{n} rows cannot ride a {bucket}-row bucket")
    out = np.zeros((bucket,) + rows.shape[1:], dtype=rows.dtype)
    out[:n] = rows
    return out


def stack_group(images: List[np.ndarray], bucket: int) -> np.ndarray:
    """Stack per-request image rows and pad to the bucket shape in one
    allocation (the placement worker calls this off the dispatch loop)."""
    if not images:
        raise ValueError("empty group")
    first = images[0]
    out = np.zeros((bucket,) + first.shape, dtype=first.dtype)
    for i, img in enumerate(images):
        out[i] = img
    return out
