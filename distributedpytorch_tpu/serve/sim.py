"""The serve tier's discrete-event simulator: replay arrival traces
against the REAL queue policy with profiled service times.

The capacity question — "how many replicas for this traffic at this
SLO?" — only needed two inputs the live system wasn't exposing as
artifacts until PR 13: *when requests arrive* (the arrival trace) and
*how long a bucket takes on the device* (the per-bucket service-time
histograms in the ``dpt_serve_profile`` v1 artifact every bench_serve
leg now writes). Given both, a discrete-event simulation answers the
question on any CPU in milliseconds — the serve-tier analogue of PR
10's rank-on-CPU/spend-chips-on-winners planner, and the established
shape for LLM-serving capacity planning (Vidur, MLSys '24).

What is simulated, and how honestly:

* **policy** — admission and flushing call the *same* pure functions
  the live queue executes (``serve/policy.py``: full / deadline /
  eager / shed + the hard admission cap). There is no reimplementation
  to drift.
* **service times** — sampled per bucket from the profile's cumulative
  device-exec histograms by inverse-CDF interpolation
  (:class:`ServiceModel`). Buckets the profile never observed are
  scaled linearly in rows from the nearest observed bucket, and the
  model says so in ``notes`` (a plan built on scaled buckets is a
  what-if, not a calibration).
* **replicas** — each replica is modeled as ``inflight_per_replica``
  service CHANNELS (the live pipeline's in-flight slots: one bucket
  executing + one dispatched behind it). The channel, not the replica,
  is the unit the profile measures: the host-observed ``device_exec``
  span runs dispatched→device-done per SLOT, so where real in-flight
  buckets serialize on the accelerator the measured spans already
  stretch to absorb it, and where they genuinely overlap (H2D under
  compute; the CPU backend) the spans overlap too — channels × span
  reproduces live throughput either way. Flushed groups buffer
  ``dispatch_buffer`` deep ahead of the channels (the placement-depth
  analogue) so deadline flushes under load still leave the queue.
* **constant overheads** — decode + placement + drain medians from the
  profile's ``phase_medians_ms`` ride every completed request as a
  constant adder; queue_wait / dispatch_wait / device_exec are what the
  event loop itself produces.

Deterministic by construction: virtual time only, one seeded
``random.Random`` stream, no wall clock, no threads — the same trace +
profile + seed gives the bit-identical result the plan artifact test
pins. Jax-free and import-light (numpy only via serve/bucketing).

Workloads:

* :func:`poisson_arrivals` — open-loop Poisson at a fixed rate (the
  coordinated-omission-free real-traffic shape);
* :func:`load_arrival_trace` — a recorded ``dpt_serve_arrivals`` JSONL
  (the serve front's ``--record-arrivals``, bench_serve's per-leg
  recordings) replayed verbatim;
* ``closed_concurrency`` — C closed-loop clients, submit→wait→repeat
  (bench_serve's closed legs).
"""

from __future__ import annotations

import collections
import dataclasses
import heapq
import itertools
import json
import logging
import os
import random
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from distributedpytorch_tpu.serve import policy
from distributedpytorch_tpu.serve.bucketing import BucketPlanner

logger = logging.getLogger(__name__)

#: Recorded arrival-trace identity (first JSONL line is a header with
#: these — the planner-file idiom, same refusal rules as profiles).
TRACE_KIND = "dpt_serve_arrivals"
TRACE_VERSION = 1


# -- arrival traces: recording + loading + synthesis -------------------------
class ArrivalRecorder:
    """Bounded JSONL recorder for the serve front's ``--record-arrivals``:
    one line per ingress (wall-time, decoded rows/shape, covering
    bucket), capped at ``limit`` lines so a long-running server can't
    grow a trace file without bound — past the cap, recording stops
    with one logged note (the head of the traffic is the trace).

    Thread-safe (ingress runs on HTTP handler threads); writes ride the
    file object's buffering and flush on :meth:`close`.

    An existing non-empty trace is APPENDED to, not truncated: a
    supervised serve worker relaunched after a crash (the PR-12 drill)
    must not discard the offered load it recorded before dying. The
    loader skips the extra header lines later incarnations would write
    — only a fresh file gets one."""

    def __init__(self, path: str, limit: int = 200_000):
        self.path = str(path)
        self.limit = max(1, int(limit))
        self.recorded = 0
        self._lock = threading.Lock()
        parent = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(parent, exist_ok=True)
        fresh = not os.path.exists(self.path) or (
            os.path.getsize(self.path) == 0
        )
        self._f = open(self.path, "w" if fresh else "a")
        if fresh:
            self._f.write(json.dumps({
                "kind": TRACE_KIND, "version": TRACE_VERSION,
                "created_unix": round(time.time(), 3),
            }) + "\n")
        self._capped_logged = False

    def record(self, t_wall: float, rows: int,
               shape: Optional[Sequence[int]] = None,
               bucket: Optional[int] = None) -> None:
        with self._lock:
            if self._f is None:
                return
            if self.recorded >= self.limit:
                if not self._capped_logged:
                    self._capped_logged = True
                    logger.warning(
                        "arrival trace %s reached its %d-line cap — "
                        "recording stopped (the trace keeps the head of "
                        "the traffic)", self.path, self.limit,
                    )
                return
            rec = {"t": round(float(t_wall), 6), "rows": int(rows)}
            if shape is not None:
                rec["shape"] = [int(s) for s in shape]
            if bucket is not None:
                rec["bucket"] = int(bucket)
            self._f.write(json.dumps(rec) + "\n")
            self.recorded += 1

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


def load_arrival_trace(path: Optional[str]) -> Optional[List[Tuple[float, int]]]:
    """A recorded trace as ``[(t, rows), ...]`` with ``t`` normalized to
    start at 0, or None (with a logged note) for missing / unreadable /
    foreign files — the planner-file idiom: a torn or foreign trace must
    never silently shape a capacity plan. Individual malformed lines
    after a valid header are skipped (a crash mid-append loses the tail,
    not the trace)."""
    if not path:
        return None
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except OSError as exc:
        logger.warning("arrival trace %r unreadable (%s) — ignored",
                       path, type(exc).__name__)
        return None
    if not lines:
        logger.warning("arrival trace %r is empty — ignored", path)
        return None
    try:
        header = json.loads(lines[0])
    except ValueError:
        header = None
    if (
        not isinstance(header, dict)
        or header.get("kind") != TRACE_KIND
        or header.get("version") != TRACE_VERSION
    ):
        logger.warning(
            "arrival trace %r is not a %s v%d file — ignored (stale or "
            "foreign)", path, TRACE_KIND, TRACE_VERSION,
        )
        return None
    arrivals: List[Tuple[float, int]] = []
    for line in lines[1:]:
        try:
            rec = json.loads(line)
            arrivals.append((float(rec["t"]), max(1, int(rec["rows"]))))
        except (ValueError, KeyError, TypeError):
            continue  # torn tail line
    if not arrivals:
        logger.warning("arrival trace %r has a header but no arrivals — "
                       "ignored", path)
        return None
    arrivals.sort(key=lambda a: a[0])
    t0 = arrivals[0][0]
    return [(t - t0, rows) for t, rows in arrivals]


def poisson_arrivals(rate_rps: float, duration_s: float, seed: int = 0,
                     rows_per_request: int = 1) -> List[Tuple[float, int]]:
    """Open-loop Poisson arrivals: ``rate_rps`` requests/s for
    ``duration_s`` virtual seconds, deterministic per seed."""
    rng = random.Random(seed)
    rate = max(float(rate_rps), 1e-9)
    out: List[Tuple[float, int]] = []
    t = 0.0
    while True:
        t += rng.expovariate(rate)
        if t >= duration_s:
            return out
        out.append((t, int(rows_per_request)))


def scheduled_poisson_arrivals(
    schedule: Sequence[Tuple[float, float]], seed: int = 0,
    rows_per_request: int = 1,
) -> List[Tuple[float, int]]:
    """Piecewise-Poisson arrivals over a rate *schedule*:
    ``[(duration_s, rate_rps), ...]`` segments walked back-to-back with
    ONE seeded stream — the synthetic diurnal shape (ramp up, peak,
    ramp down, trough) the autoscaler tests replay. Deterministic per
    (schedule, seed); an interarrival gap that straddles a segment
    boundary keeps the old segment's rate (standard piecewise
    approximation — fine at the minutes-long segments we generate)."""
    rng = random.Random(seed)
    out: List[Tuple[float, int]] = []
    seg_start = 0.0
    for duration_s, rate_rps in schedule:
        rate = max(float(rate_rps), 1e-9)
        seg_end = seg_start + float(duration_s)
        t = seg_start
        while True:
            t += rng.expovariate(rate)
            if t >= seg_end:
                break
            out.append((t, int(rows_per_request)))
        seg_start = seg_end
    return out


def write_arrival_trace(path: str,
                        arrivals: Sequence[Tuple[float, int]],
                        created_unix: float = 0.0) -> str:
    """Write a synthetic arrivals list as a ``dpt_serve_arrivals`` v1
    JSONL — byte-deterministic for a fixed ``created_unix`` (checked-in
    fixture traces pin 0.0) so regenerating a committed trace is a
    no-op diff."""
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    with open(path, "w") as f:
        f.write(json.dumps({
            "kind": TRACE_KIND, "version": TRACE_VERSION,
            "created_unix": round(float(created_unix), 3),
        }) + "\n")
        for t, rows in arrivals:
            f.write(json.dumps(
                {"t": round(float(t), 6), "rows": int(rows)}
            ) + "\n")
    return path


# -- service-time model ------------------------------------------------------
class ServiceModel:
    """Per-bucket device-exec sampler calibrated from a loaded
    ``dpt_serve_profile`` payload: inverse-CDF interpolation over each
    bucket's cumulative histogram. ``overhead_s`` is the constant
    decode+placement+drain adder from the profile's phase medians."""

    def __init__(self, profile: dict):
        self.notes: List[str] = []
        self._segments: Dict[int, List[Tuple[float, float, int]]] = {}
        self._total: Dict[int, int] = {}
        self._mean: Dict[int, float] = {}
        for key, info in (profile.get("buckets") or {}).items():
            try:
                bucket = int(key)
                hist = info["device_exec_s"]["cumulative_buckets"]
                count = int(info["device_exec_s"]["count"])
                mean = info["device_exec_s"].get("mean")
            except (KeyError, TypeError, ValueError):
                continue
            if count < 1:
                continue
            segments: List[Tuple[float, float, int]] = []
            lo = 0.0
            prev_cum = 0
            last_finite = 0.0
            for bound, cum in hist:
                if bound == "+Inf":
                    # overflow mass: bounded at 2x the last finite bound
                    hi = max(last_finite * 2.0, last_finite + 1e-6)
                else:
                    hi = float(bound)
                    last_finite = hi
                seg_count = int(cum) - prev_cum
                prev_cum = int(cum)
                if seg_count > 0:
                    segments.append((lo, hi, seg_count))
                lo = hi if bound != "+Inf" else lo
            if not segments:
                continue
            self._segments[bucket] = segments
            self._total[bucket] = sum(c for _, _, c in segments)
            self._mean[bucket] = (
                float(mean) if mean is not None
                else sum((lo + hi) / 2 * c for lo, hi, c in segments)
                / self._total[bucket]
            )
        if not self._segments:
            raise ValueError(
                "profile has no usable per-bucket service-time histograms "
                "— nothing to calibrate a simulation from"
            )
        medians = profile.get("phase_medians_ms") or {}
        self.overhead_s = sum(
            (medians.get(phase) or 0.0) / 1e3
            for phase in ("decode", "placement", "drain")
        )
        self._scaled: Dict[int, int] = {}

    def buckets(self) -> List[int]:
        return sorted(self._segments)

    def _base_bucket(self, bucket: int) -> int:
        """Nearest profiled bucket (by row-count ratio) to scale an
        unprofiled bucket's sample from — noted once per bucket: plans
        leaning on scaled buckets are what-ifs, not calibrations."""
        cached = self._scaled.get(bucket)
        if cached is not None:
            return cached
        base = min(
            self._segments,
            key=lambda b: (abs(b - bucket), b),
        )
        self._scaled[bucket] = base
        self.notes.append(
            f"bucket {bucket} unprofiled — service times scaled "
            f"linearly in rows from profiled bucket {base}"
        )
        return base

    def sample(self, bucket: int, rng: random.Random) -> float:
        b = int(bucket)
        if b in self._segments:
            base, scale = b, 1.0
        else:
            base = self._base_bucket(b)
            scale = b / base
        u = rng.random() * self._total[base]
        acc = 0
        for lo, hi, count in self._segments[base]:
            if u <= acc + count:
                frac = (u - acc) / count
                return max(1e-9, (lo + (hi - lo) * frac) * scale)
            acc += count
        lo, hi, _count = self._segments[base][-1]
        return max(1e-9, hi * scale)

    def mean_service_s(self, bucket: int) -> float:
        b = int(bucket)
        if b in self._mean:
            return self._mean[b]
        base = self._base_bucket(b)
        return self._mean[base] * (b / base)

    def capacity_rows_per_s(self, bucket_sizes: Sequence[int],
                            replicas: int,
                            inflight_per_replica: int = 1) -> float:
        """Best-case steady-state throughput: every dispatch rides the
        largest bucket, fully packed, on every service channel — the
        planner's default rate-ladder anchor (``inflight_per_replica=1``
        keeps the anchor conservative)."""
        top = max(bucket_sizes)
        channels = replicas * max(1, int(inflight_per_replica))
        return channels * top / max(self.mean_service_s(top), 1e-9)


# -- the event loop ----------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class SimKnobs:
    """One grid point's queue/serving knobs — mirrors ``ServeConfig``'s
    batching+execution surface (bucket ladder, SLO, replica count,
    eager/shed via the shared policy, admission cap)."""

    bucket_sizes: Tuple[int, ...] = (1, 2, 4, 8)
    slo_s: float = 0.05
    replicas: int = 1
    eager: bool = True
    hard_cap_images: Optional[int] = None  # None → 4x largest bucket
    # dispatched-but-undrained buckets per replica (ServeConfig's
    # inflight_per_replica): the service channels — see module docstring
    inflight_per_replica: int = 2
    dispatch_buffer: int = 2  # flushed groups buffered ahead of channels
    seed: int = 0

    def resolved_cap(self) -> int:
        if self.hard_cap_images is not None:
            return int(self.hard_cap_images)
        return 4 * max(self.bucket_sizes)

    @property
    def channels(self) -> int:
        return max(1, int(self.replicas)) * max(
            1, int(self.inflight_per_replica)
        )


@dataclasses.dataclass
class SimResult:
    submitted: int
    completed: int
    completed_rows: int
    shed: int
    duration_s: float
    p50_ms: Optional[float]
    p99_ms: Optional[float]
    shed_rate: float
    imgs_per_s: float
    queue_depth_max: int
    utilization: float
    pad_ratio: float
    flush_mix: Dict[str, int]

    def payload(self) -> dict:
        """The deterministic dict the plan artifact embeds (rounded so
        formatting can't wobble across platforms)."""
        return {
            "submitted": self.submitted,
            "completed": self.completed,
            "completed_rows": self.completed_rows,
            "shed": self.shed,
            "duration_s": round(self.duration_s, 6),
            "p50_ms": None if self.p50_ms is None else round(self.p50_ms, 3),
            "p99_ms": None if self.p99_ms is None else round(self.p99_ms, 3),
            "shed_rate": round(self.shed_rate, 4),
            "imgs_per_s": round(self.imgs_per_s, 2),
            "queue_depth_max": self.queue_depth_max,
            "utilization": round(self.utilization, 4),
            "pad_ratio": round(self.pad_ratio, 4),
            "flush_mix": dict(sorted(self.flush_mix.items())),
        }


@dataclasses.dataclass
class _SimReq:
    rows: int
    t_arrive: float
    deadline_t: float
    client: Optional[int] = None  # closed-loop client id, else None


def simulate(model: ServiceModel, knobs: SimKnobs,
             arrivals: Optional[Sequence[Tuple[float, int]]] = None,
             closed_concurrency: Optional[int] = None,
             duration_s: Optional[float] = None) -> SimResult:
    """Run one scenario: either an open/recorded ``arrivals`` list of
    ``(t, rows)`` or ``closed_concurrency`` clients for ``duration_s``.
    Virtual time, one seeded RNG — bit-deterministic."""
    if (arrivals is None) == (closed_concurrency is None):
        raise ValueError("exactly one of arrivals / closed_concurrency")
    if closed_concurrency is not None and duration_s is None:
        raise ValueError("closed-loop simulation needs duration_s")
    planner = BucketPlanner(knobs.bucket_sizes)
    cap = knobs.resolved_cap()
    rng = random.Random(knobs.seed)
    seq = itertools.count()
    events: list = []  # (t, seq, kind, payload)

    def push(t: float, kind: str, payload=None) -> None:
        heapq.heappush(events, (t, next(seq), kind, payload))

    if arrivals is not None:
        load_end = max((t for t, _ in arrivals), default=0.0)
        for t, rows in arrivals:
            push(t, "arrival", _SimReq(rows, t, 0.0))
    else:
        load_end = float(duration_s)
        for client in range(int(closed_concurrency)):
            push(0.0, "arrival", _SimReq(1, 0.0, 0.0, client=client))
    # closed-loop rejection retry pause: the live bench worker's
    # submit→instant-reject→resubmit loop spins in sub-ms real time;
    # virtual time needs an explicit (tiny) pause or it never advances
    retry_s = max(1e-3, knobs.slo_s / 8.0)

    pending: collections.deque = collections.deque()
    pending_rows = 0
    dispatch_q: collections.deque = collections.deque()
    idle: List[int] = list(range(knobs.channels))
    busy_s = 0.0
    latencies: List[float] = []
    flush_mix: Dict[str, int] = {}
    submitted = completed = completed_rows = shed = 0
    depth_max = 0
    real_rows = pad_rows = 0
    last_t = 0.0

    def assign(now: float) -> None:
        nonlocal busy_s, completed, completed_rows, real_rows, pad_rows
        while idle and dispatch_q:
            bucket, group = dispatch_q.popleft()
            replica = idle.pop()
            service = model.sample(bucket, rng)
            done = now + service
            busy_s += service
            rows = sum(r.rows for r in group)
            real_rows += rows
            pad_rows += bucket - rows
            for req in group:
                latencies.append(done + model.overhead_s - req.t_arrive)
                completed += 1
                completed_rows += req.rows
                if req.client is not None and done < load_end:
                    push(done, "arrival",
                         _SimReq(1, done, 0.0, client=req.client))
            push(done, "free", replica)

    def try_flush(now: float) -> None:
        nonlocal pending_rows
        assign(now)
        while pending:
            idle_now = bool(idle)
            if not idle_now and len(dispatch_q) >= knobs.dispatch_buffer:
                break  # placement backpressure: nothing to flush into
            decision = policy.decide_flush(
                planner, [r.rows for r in pending], pending[0].deadline_t,
                pending_rows, now,
                eager=knobs.eager and idle_now,
            )
            if decision is None:
                break
            group = [pending.popleft() for _ in range(decision.count)]
            pending_rows -= decision.rows
            flush_mix[decision.kind] = flush_mix.get(decision.kind, 0) + 1
            dispatch_q.append((decision.bucket, group))
            assign(now)

    while events:
        now, _, kind, payload = heapq.heappop(events)
        last_t = max(last_t, now)
        if kind == "arrival":
            req: _SimReq = payload
            submitted += 1
            reason = policy.admit_decision(planner, pending_rows, req.rows,
                                           cap)
            if reason is not None:
                shed += 1
                if req.client is not None and now + retry_s < load_end:
                    push(now + retry_s, "arrival",
                         _SimReq(1, now + retry_s, 0.0, client=req.client))
            else:
                req.t_arrive = now
                req.deadline_t = now + knobs.slo_s
                pending.append(req)
                pending_rows += req.rows
                depth_max = max(depth_max, pending_rows)
                push(req.deadline_t, "poll")
            try_flush(now)
        elif kind == "poll":
            try_flush(now)
        elif kind == "free":
            idle.append(payload)
            try_flush(now)

    elapsed = max(last_t, load_end, 1e-9)
    latencies.sort()
    from distributedpytorch_tpu.obs.registry import nearest_rank

    dispatched = real_rows + pad_rows
    return SimResult(
        submitted=submitted,
        completed=completed,
        completed_rows=completed_rows,
        shed=shed,
        duration_s=elapsed,
        p50_ms=(nearest_rank(latencies, 50) * 1e3 if latencies else None),
        p99_ms=(nearest_rank(latencies, 99) * 1e3 if latencies else None),
        shed_rate=shed / submitted if submitted else 0.0,
        imgs_per_s=completed_rows / elapsed,
        queue_depth_max=depth_max,
        utilization=busy_s / (knobs.channels * elapsed),
        pad_ratio=pad_rows / dispatched if dispatched else 0.0,
        flush_mix=flush_mix,
    )
