"""The batching queue's flush/admission policy as PURE functions.

``serve/queue.py`` (the live continuous-batching queue) and
``serve/sim.py`` (the ``plan-serve`` discrete-event capacity simulator)
must make *identical* decisions — a simulator that reimplements the
flush policy drifts the first time someone tunes the shed rule, and a
drifted simulator emits capacity plans for a server that doesn't exist.
So the policy lives HERE, once, as pure functions of plain values
(sizes, deadlines, a clock reading), and both callers delegate:

* :func:`admit_decision` — should this request be admitted, or rejected
  (too large for any bucket / the hard cap is exhausted)?
* :func:`decide_flush`   — given the FIFO's row sizes, the head
  deadline, and the clock, which flush fires (full / deadline / eager /
  shed), into which bucket, taking how many head requests?

The semantics are documented in serve/queue.py's module docstring (the
four flush regimes + bounded admission); this module is the executable
version. Nothing here touches threads, clocks, or telemetry — the queue
owns locking and counters, the simulator owns virtual time.

Pure-Python + jax-free (the planner CLI runs with no backend at all).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

#: ``submit`` rejection reasons (stable strings — they surface in bench
#: reports and HTTP 503 bodies, so clients can switch on them).
#: ``overloaded`` means "this instance is shedding, back off and retry";
#: ``shutdown`` means "this instance is going away, retry elsewhere" —
#: conflating them would have clients hammering a stopping server.
REJECT_OVERLOAD = "overloaded"
REJECT_TOO_LARGE = "too-large"
REJECT_SHUTDOWN = "shutdown"


@dataclasses.dataclass(frozen=True)
class FlushDecision:
    """One flush: ``count`` head requests (``rows`` real rows total)
    leave the queue and ride a ``bucket``-row padded batch; ``kind`` is
    the regime that fired (full / deadline / eager / shed) — the same
    string the flush telemetry and the request trace ledgers record."""

    kind: str
    bucket: int
    count: int
    rows: int


def head_group(planner, sizes: Sequence[int]) -> Tuple[int, int]:
    """Longest FIFO prefix whose rows fit the largest bucket, as
    ``(count, rows)``. Strictly FIFO: a request that doesn't fit stops
    the scan (no reordering — within a bucket and across buckets,
    completion follows submission order for equal-capacity requests)."""
    count = 0
    total = 0
    for size in sizes:
        if total + size > planner.max_size:
            break
        count += 1
        total += size
    return count, total


def admit_decision(planner, pending_rows: int, size: int,
                   hard_cap_images: int) -> Optional[str]:
    """Admission for a ``size``-row request against ``pending_rows``
    already queued: None to admit, else the rejection reason. A request
    larger than the biggest bucket could never match a compiled
    executable (:data:`REJECT_TOO_LARGE`); beyond the hard cap, queue
    depth — and with it queueing latency — stays bounded by
    construction (:data:`REJECT_OVERLOAD`)."""
    if size > planner.max_size:
        return REJECT_TOO_LARGE
    if pending_rows + size > hard_cap_images:
        return REJECT_OVERLOAD
    return None


def decide_flush(planner, sizes: Sequence[int], head_deadline_t: float,
                 pending_rows: int, now: float,
                 eager: bool = False) -> Optional[FlushDecision]:
    """The flush policy: which group (if any) leaves the queue NOW.

    ``sizes`` are the pending requests' row counts in FIFO order,
    ``head_deadline_t`` the oldest request's SLO deadline,
    ``pending_rows`` the queued-row total (== ``sum(sizes)``), and
    ``eager`` means the caller has idle capacity in hand and will
    dispatch whatever it gets immediately. Returns None when nothing
    should flush yet."""
    if not sizes:
        return None
    count, total = head_group(planner, sizes)
    overloaded = pending_rows - total >= planner.max_size
    if total == planner.max_size or (count < len(sizes) and not overloaded):
        # head group fills (or next request overflows) the largest
        # bucket: the throughput path
        return FlushDecision("full", planner.bucket_for(total), count, total)
    if overloaded:
        # shed: more than a full bucket is backed up behind the head
        # group — drop to the largest bucket the head can FILL, so no
        # dispatched row is padding while real requests wait
        bucket = planner.largest_full_bucket(total)
        trimmed_count = 0
        trimmed_total = 0
        for size in sizes[:count]:
            if trimmed_total + size > bucket:
                break
            trimmed_count += 1
            trimmed_total += size
        if trimmed_count:
            count, total = trimmed_count, trimmed_total
        # an unsplittable head (single request bigger than the full
        # bucket) keeps its covering bucket, padding and all
        return FlushDecision("shed", planner.bucket_for(total), count, total)
    if head_deadline_t <= now or eager:
        # SLO flush / work-conserving flush: smallest covering bucket
        kind = "deadline" if head_deadline_t <= now else "eager"
        return FlushDecision(kind, planner.bucket_for(total), count, total)
    return None
