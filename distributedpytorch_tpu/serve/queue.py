"""The continuous-batching queue: coalesce concurrent requests into
padded bucket shapes under a latency SLO.

The serving problem (Orca, OSDI '22; Clipper, NSDI '17): one request at
a time starves the accelerator, but waiting to fill the biggest batch
starves the *user*. This queue holds the dial between them:

* **full flush** — the FIFO head fills the largest bucket → dispatch
  immediately at full batch (the throughput regime; under sustained
  load every dispatch rides the big bucket).
* **deadline flush** — the oldest request's SLO deadline arrives first
  → flush whatever is pending into the smallest covering bucket (pad
  rows bounded by the bucket ladder, latency bounded by the SLO).
* **eager flush** — the dispatcher reports an idle replica and the
  queue is non-empty → dispatch immediately (work-conserving
  continuous batching: batching never adds latency when there is spare
  capacity; batches *form on their own* exactly when capacity is the
  bottleneck).
* **overload shedding** — when the backlog holds more than one full
  bucket beyond the head group, flushes drop to the **largest bucket
  they can completely fill** instead of padding up: under overload,
  pad rows are pure wasted accelerator time, so padding is what gets
  shed. Admission is capped at ``hard_cap_images`` pending rows —
  beyond it ``submit`` returns :data:`REJECT_OVERLOAD` instead of
  queueing, so queue depth (and therefore queueing latency) is bounded
  by construction rather than by hope.

Requests are whole units: a k-image request coalesces into one bucket
and is never split across dispatches (its response stays one piece). A
request larger than the biggest bucket is rejected at admission with
:data:`REJECT_TOO_LARGE` — it could never match a compiled executable.

Determinism: all policy lives in ``serve/policy.py`` as PURE functions
(:func:`~distributedpytorch_tpu.serve.policy.decide_flush` /
:func:`~distributedpytorch_tpu.serve.policy.admit_decision`) that
``poll()``/``_poll_locked`` delegate to, driven by an injectable
``clock`` — the unit tests step a fake clock and never touch threads,
and the ``plan-serve`` capacity simulator (serve/sim.py) replays the
*same* policy functions against virtual time, so the simulated queue
cannot drift from this one. ``wait_for_work`` is the thin blocking
wrapper the server's dispatch thread uses (condition variable, woken by
``submit`` and by the next SLO deadline).
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Callable, Deque, List, Optional, Tuple

import numpy as np

from distributedpytorch_tpu.obs import defs as obsm
from distributedpytorch_tpu.obs import flight
from distributedpytorch_tpu.serve import policy
from distributedpytorch_tpu.serve.bucketing import BucketPlanner
from distributedpytorch_tpu.serve.policy import (  # noqa: F401 — re-exports
    REJECT_OVERLOAD,
    REJECT_SHUTDOWN,
    REJECT_TOO_LARGE,
)


@dataclasses.dataclass
class ServeRequest:
    """One admitted unit of work: ``images`` is a list of ``(H, W, C)``
    float32 rows (k >= 1 of them — a request is atomic w.r.t. batching).
    ``future`` resolves to the server's response object; the queue never
    touches it (rejection futures resolve at the submit site)."""

    images: List[np.ndarray]
    future: object = None
    key: str = ""
    size: int = 0  # rows; derived from images at submit
    enqueue_t: float = 0.0
    deadline_t: float = 0.0
    seq: int = 0
    # request-scoped tracing (obs/reqtrace.py): the ingress-assigned id
    # (echoed as X-Request-Id) and the span ledger the lifecycle stamps
    # into — None when tracing is off (DPT_OBS=0) or for bare-queue
    # tests; every mark site guards on it
    request_id: str = ""
    trace: Optional[object] = None
    # prediction-cache key (serve/cache.py) stamped at admission when
    # the cache is on — the completion drain stores the masks under it,
    # but only when the weights version the dispatch actually used
    # (read in the dispatch loop) still equals the version the key was
    # scoped to; a canary/rollback in between must not poison the cache
    cache_key: Optional[str] = None
    cache_version: int = 0
    # sustained-A/B arm ("" = unarmed): requests of different arms are
    # answered by disjoint replica groups serving different weight
    # versions, so a flushed batch must be arm-pure — the flush policy
    # only ever considers the head same-arm run (see _poll_locked)
    arm: str = ""


class BatchingQueue:
    """See module docstring for the flush/shed policy.

    ``hard_cap_images`` defaults to 4× the largest bucket: enough to keep
    every replica's next dispatch full under bursts, small enough that
    worst-case queueing delay stays a handful of service times.
    """

    def __init__(
        self,
        planner: BucketPlanner,
        slo_s: float = 0.05,
        hard_cap_images: Optional[int] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.planner = planner
        self.slo_s = float(slo_s)
        self.hard_cap_images = int(
            hard_cap_images if hard_cap_images is not None
            else 4 * planner.max_size
        )
        if self.hard_cap_images < planner.max_size:
            raise ValueError(
                f"hard_cap_images={self.hard_cap_images} cannot be smaller "
                f"than the largest bucket ({planner.max_size}) — the largest "
                f"bucket could never fill"
            )
        self.clock = clock
        self._pending: Deque[ServeRequest] = collections.deque()
        self._pending_images = 0
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._stopped = False
        self._seq = 0
        # observability (bench_serve samples these)
        self.max_depth_seen = 0
        self.submitted = 0
        self.rejected = 0

    # -- admission -----------------------------------------------------------
    def submit(self, req: ServeRequest) -> Optional[str]:
        """Admit a request; returns None on success or a rejection reason
        (the caller resolves the request's future — a rejection is a
        RESPONSE, not an exception, so load generators can count it)."""
        req.size = len(req.images)
        if req.size < 1:
            raise ValueError("empty request")
        with self._cond:
            if self._stopped:
                return REJECT_SHUTDOWN
            reason = policy.admit_decision(
                self.planner, self._pending_images, req.size,
                self.hard_cap_images,
            )
            if reason == REJECT_TOO_LARGE:
                # could never match a compiled executable: a CLIENT
                # error, not backpressure — no shed accounting
                return reason
            if reason is not None:
                self.rejected += 1
                # request-attributable shed record: a post-mortem can
                # name WHICH request was shed and why, not just count
                flight.record("queue_reject", reason=reason,
                              request_id=req.request_id,
                              rows=req.size, backlog=self._pending_images)
                return reason
            now = self.clock()
            req.enqueue_t = now
            req.deadline_t = now + self.slo_s
            req.seq = self._seq
            self._seq += 1
            if req.trace is not None:
                req.trace.mark("enqueued", now)
            self._pending.append(req)
            self._pending_images += req.size
            self.submitted += 1
            self.max_depth_seen = max(self.max_depth_seen, self._pending_images)
            obsm.SERVE_QUEUE_DEPTH.set(self._pending_images)
            self._cond.notify_all()
        return None

    # -- flush policy (pure — serve/policy.py; the simulator shares it) ------
    def _poll_locked(self, eager: bool = False):
        if not self._pending:
            return None
        now = self.clock()
        # arm-pure batching: the policy only sees the head same-arm run,
        # so a flush can never mix requests bound for different A/B
        # replica groups (one batch = one executable call = one weight
        # version). With no A/B every arm is "" and this is the whole
        # FIFO — bit-identical to the un-armed behavior.
        head_arm = self._pending[0].arm
        sizes: List[int] = []
        for req in self._pending:
            if req.arm != head_arm:
                break
            sizes.append(req.size)
        decision = policy.decide_flush(
            self.planner,
            sizes,
            self._pending[0].deadline_t,
            self._pending_images,
            now,
            eager=eager,
        )
        if decision is None:
            return None
        kind, bucket = decision.kind, decision.bucket
        take: List[ServeRequest] = []
        for _ in range(decision.count):
            req = self._pending.popleft()
            take.append(req)
            if req.trace is not None:
                # flush mark + reason: queue_wait ends here, and the
                # ledger records WHY this group left the queue
                req.trace.mark_flushed(now, kind, bucket)
        self._pending_images -= decision.rows
        total = decision.rows
        # flush-decision telemetry (docs/OBSERVABILITY.md): a counter inc
        # + one ring slot — no allocation growth, nothing blocks
        obsm.SERVE_FLUSHES.labels(kind=kind).inc()
        obsm.SERVE_QUEUE_DEPTH.set(self._pending_images)
        flight.record("queue_flush", flush=kind, bucket=bucket, rows=total,
                      backlog=self._pending_images)
        return bucket, take

    def poll(self, eager: bool = False):
        """Non-blocking: ``(bucket_size, [requests])`` ready to dispatch,
        or None. ``eager=True`` = the caller has idle capacity in hand and
        will dispatch whatever it gets immediately."""
        with self._lock:
            return self._poll_locked(eager=eager)

    def wait_for_work(self, timeout: float = 0.25, eager=False):
        """Blocking ``poll`` for the dispatch thread: waits until a group
        is dispatchable, the queue stops, or ``timeout`` elapses — waking
        early for the oldest request's SLO deadline. ``eager`` may be a
        bool or a zero-arg callable re-evaluated on every wake: capacity
        that frees up mid-wait (a completion returning a replica slot —
        see :meth:`kick`) must flip the work-conserving path on without
        waiting out the rest of the SLO."""
        eager_fn = eager if callable(eager) else (lambda: eager)
        limit = self.clock() + timeout
        with self._cond:
            while not self._stopped:
                got = self._poll_locked(eager=eager_fn())
                if got is not None:
                    return got
                now = self.clock()
                wait = limit - now
                if self._pending:
                    wait = min(wait, self._pending[0].deadline_t - now)
                if wait <= 0:
                    return None
                self._cond.wait(wait)
            return None

    def kick(self) -> None:
        """Wake ``wait_for_work`` waiters without submitting anything —
        called when serving capacity frees (a replica slot returns) so an
        idle-capacity eager flush happens NOW, not at the SLO deadline."""
        with self._cond:
            self._cond.notify_all()

    # -- lifecycle / observability ------------------------------------------
    def stop(self) -> List[ServeRequest]:
        """Stop admitting and wake waiters; returns the still-pending
        requests so the server can resolve their futures (shutdown is a
        rejection, not a hang)."""
        with self._cond:
            self._stopped = True
            drained = list(self._pending)
            self._pending.clear()
            self._pending_images = 0
            # the gauge must not freeze at the pre-stop backlog: the
            # process-wide /metrics would report a phantom queue forever
            obsm.SERVE_QUEUE_DEPTH.set(0)
            self._cond.notify_all()
        return drained

    @property
    def depth_images(self) -> int:
        with self._lock:
            return self._pending_images

    def __len__(self) -> int:
        with self._lock:
            return len(self._pending)
