"""Health-gated zero-downtime weight rollout: canary → watch → promote
or roll back.

Weights are AOT executable *arguments*, not baked constants
(serve/engine.py), so a newly trained — or freshly int8-quantized —
checkpoint rolls into the running engine as a ``device_put`` with no
recompile and no drain. What makes that safe to do mid-traffic is the
canary state machine this module owns:

1. **load** — the candidate loads off the serving path
   (``engine.bundle_loader`` pins the engine's model identity and
   quantization; a tree-shape mismatch fails HERE, not inside a live
   dispatch);
2. **canary** — the new weights swap onto the first
   ``canary_replicas`` replica group(s) only; the rest keep serving the
   promoted version (the prediction cache bypasses itself while the
   groups disagree);
3. **watch** — over ``window_s`` the manager scores the canary on the
   PR-7 gauges (error-response and shed deltas, p99 against the
   pre-canary baseline) plus a **pinned-sample Dice probe**: the probe
   images run through the canary replica directly (no queue capacity
   consumed) and their masks must score within ``dice_margin`` of the
   old weights' masks (or of explicit reference masks, when given);
4. **promote / roll back** — pass → the remaining groups swap and the
   promoted ``weights_version`` bumps (``/stats``, ``/metrics``); fail
   → the canary group's old device trees (never freed — rollback is a
   pointer flip) are restored and the old version keeps serving.

Every transition lands in the flight-recorder ring and the
``dpt_serve_rollouts_total``/``dpt_serve_rollout_canary`` families. The
``swap_crash`` chaos site (utils/faults.py) fires inside the swap
itself, so the crash-mid-rollout path is deterministically drillable on
CPU (tests/test_serve_fleet.py).

``--watch-checkpoint`` mode (:class:`CheckpointWatcher`) polls a
checkpoint path and triggers this exact state machine whenever the
trainer (or tools/quantize.py) replaces the file — continuous delivery
for weights, gated by the same canary.
"""

from __future__ import annotations

import logging
import os
import threading
import time
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from distributedpytorch_tpu.obs import defs as obsm
from distributedpytorch_tpu.obs import flight
from distributedpytorch_tpu.serve import control

logger = logging.getLogger(__name__)

# The state machine itself — names, legal transitions, and the restore
# scope each failure edge must apply — is the PURE table in
# serve/control.py (rollout_transition), which analysis/protocol.py
# model-checks; this module is its actuator.
STATE_IDLE = control.ROLLOUT_IDLE
STATE_LOADING = control.ROLLOUT_LOADING
STATE_CANARY = control.ROLLOUT_CANARY
STATE_PROMOTING = control.ROLLOUT_PROMOTING

OUTCOME_PROMOTED = control.ROLLOUT_PROMOTED
OUTCOME_ROLLED_BACK = control.ROLLOUT_ROLLED_BACK
OUTCOME_SWAP_FAILED = control.ROLLOUT_SWAP_FAILED
OUTCOME_LOAD_FAILED = control.ROLLOUT_LOAD_FAILED


class RolloutInProgress(RuntimeError):
    """``start`` refused: a rollout is already in flight (one at a
    time — two concurrent canaries would fight over the same replicas)."""


def mask_dice(a: np.ndarray, b: np.ndarray) -> float:
    """Dice overlap of two served masks (``{0, 255} uint8`` or bool);
    both-empty scores 1.0 (identical answers must never read as
    regression)."""
    fa = np.asarray(a) > 0
    fb = np.asarray(b) > 0
    total = int(fa.sum()) + int(fb.sum())
    if total == 0:
        return 1.0
    return 2.0 * int((fa & fb).sum()) / total


class RolloutManager:
    """One server's rollout state machine (see module docstring).

    ``probe_rows`` are pre-decoded ``(H, W, C) float32`` inputs; when
    ``probe_refs`` is None the references are the OLD weights' masks on
    those rows (gate: agreement >= 1 - ``dice_margin``), otherwise the
    gate is canary Dice >= baseline Dice - ``dice_margin`` against the
    explicit references (e.g. ground-truth masks).
    """

    def __init__(
        self,
        server,
        probe_rows: Optional[Sequence[np.ndarray]] = None,
        probe_refs: Optional[Sequence[np.ndarray]] = None,
        window_s: float = 5.0,
        dice_margin: float = 0.02,
        p99_factor: float = 3.0,
        p99_floor_ms: float = 250.0,
        max_error_responses: int = 0,
        max_shed: Optional[int] = None,
        canary_replicas: int = 1,
        clock=time.monotonic,
    ):
        self.server = server
        self.engine = server.engine
        self.probe_rows = list(probe_rows) if probe_rows else []
        self.probe_refs = list(probe_refs) if probe_refs else None
        self.window_s = float(window_s)
        self.dice_margin = float(dice_margin)
        self.p99_factor = float(p99_factor)
        # p99 regressions under this absolute floor never fail a canary:
        # at single-digit-ms latencies the factor gate is pure noise
        self.p99_floor_ms = float(p99_floor_ms)
        self.max_error_responses = int(max_error_responses)
        self.max_shed = max_shed
        self.canary_replicas = max(1, int(canary_replicas))
        self.clock = clock
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._state = STATE_IDLE
        self.last_outcome: Optional[str] = None
        self.last_reason: str = ""
        self.history: List[dict] = []  # bounded transition log (status())

    # -- status --------------------------------------------------------------
    @property
    def state(self) -> str:
        return self._state

    @property
    def canarying(self) -> bool:
        """True while a canary is being health-watched — what flips the
        HTTP front's readiness to false (docs/SERVING.md)."""
        return self._state in (STATE_CANARY, STATE_PROMOTING)

    def status(self) -> dict:
        return {
            "state": self._state,
            "weights_version": self.engine.weights_version,
            "last_outcome": self.last_outcome,
            "last_reason": self.last_reason,
            "history": self.history[-10:],
        }

    # -- lifecycle -----------------------------------------------------------
    def start(self, source, label: str = "") -> None:
        """Begin a rollout. ``source`` is a checkpoint path/name (loaded
        through ``engine.bundle_loader``) or a ``(params, model_state)``
        tuple (tests, in-process callers). Returns once the worker
        thread is launched; raises :class:`RolloutInProgress` if one is
        already running. ``wait()`` blocks for the verdict."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                raise RolloutInProgress(
                    f"a rollout is already {self._state}"
                )
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, args=(source, label or str(source)[:120]),
                name="dpt-serve-rollout", daemon=True,
            )
            self._thread.start()

    def wait(self, timeout: float = 60.0) -> Optional[str]:
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)
        return self.last_outcome

    def stop(self) -> None:
        """Abort the watch window (an in-flight canary rolls back — an
        un-judged candidate must not stay promoted-by-default)."""
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=10.0)

    # -- internals -----------------------------------------------------------
    def _transition(self, state: str, **fields) -> None:
        self._state = state
        entry = {"state": state, "t": time.time(), **fields}
        self.history.append(entry)
        del self.history[:-50]  # bounded
        flight.record("rollout", **{k: v for k, v in entry.items()
                                    if k != "t"})
        logger.info("rollout: %s %s", state,
                    " ".join(f"{k}={v}" for k, v in fields.items()))

    def _load(self, source) -> Tuple[object, object]:
        if isinstance(source, tuple):
            params, model_state = source
            return params, model_state
        loader = self.engine.bundle_loader
        if loader is None:
            raise ValueError(
                "this engine was built from raw arrays (no checkpoint "
                "context) — pass a (params, model_state) tuple instead "
                "of a checkpoint path"
            )
        bundle = loader(str(source))
        return bundle.params, bundle.model_state

    def _probe_masks(self, replica_index: int) -> List[np.ndarray]:
        """Pinned-sample masks straight off one replica's executables —
        no queue admission, no capacity consumed, same code path as a
        served request's forward + postprocess."""
        masks: List[np.ndarray] = []
        chunk = self.engine.planner.max_size
        for i in range(0, len(self.probe_rows), chunk):
            batch = np.stack(self.probe_rows[i:i + chunk])
            out = self.engine.infer(batch, replica_index=replica_index)
            masks.extend(self.engine.postprocess(out[j])
                         for j in range(out.shape[0]))
        return masks

    def _probe_dice(self, replica_index: int,
                    refs: Sequence[np.ndarray]) -> float:
        masks = self._probe_masks(replica_index)
        return float(np.mean([
            mask_dice(m, r) for m, r in zip(masks, refs)
        ]))

    def _finish(self, outcome: str, reason: str = "", **fields) -> None:
        self.last_outcome = outcome
        self.last_reason = reason
        obsm.SERVE_ROLLOUTS.labels(outcome=outcome).inc()
        obsm.SERVE_ROLLOUT_CANARY.set(0)
        # the AOT invariant the hot-swap design rests on: weights are
        # executable ARGUMENTS, so a rollout — load, canary, promote or
        # roll back — performs ZERO recompiles (the engine's bucket
        # executables, store-loaded or not, keep serving). Stamped into
        # the transition log + flight ring so a recompile ever showing
        # up here reads as the regression it is.
        fields.setdefault(
            "recompiles",
            getattr(self.engine, "aot_compiles", 0)
            - getattr(self, "_compiles_at_start", 0),
        )
        self._transition(STATE_IDLE, outcome=outcome, reason=reason,
                         **fields)

    def _apply_restore(self, step: "control.RolloutStep",
                       old: Dict[int, tuple],
                       canary_idx: Sequence[int]) -> None:
        """Apply the restore scope the pure transition table REQUIRES of
        this edge (control.RolloutStep.restore): the canary subset when
        the rest never swapped, the whole snapshot when a promote-time
        crash could leave the fleet split across versions."""
        if step.restore == control.RESTORE_CANARY:
            self.engine.restore_weights({i: old[i] for i in canary_idx})
        elif step.restore == control.RESTORE_ALL:
            self.engine.restore_weights(old)

    def _run(self, source, label: str) -> None:
        self._compiles_at_start = getattr(self.engine, "aot_compiles", 0)
        step = control.rollout_transition(self._state, "start")
        self._transition(step.state, label=label)
        try:
            params, model_state = self._load(source)
        except BaseException as exc:  # noqa: BLE001 — a bad candidate is
            # a verdict, never a crash of the serving process
            logger.exception("rollout: candidate failed to load")
            step = control.rollout_transition(self._state, "load_failed")
            self._finish(step.outcome, reason=str(exc)[:300])
            return

        n = self.engine.num_replicas
        canary_idx = list(range(min(self.canary_replicas, n)))
        rest_idx = [i for i in range(n) if i not in canary_idx]
        # monotonic across rollbacks: a rejected candidate's number is
        # never reused, so its (version-scoped) prediction-cache entries
        # can never be mistaken for a later candidate's
        version = self.engine.next_weights_version()
        old = self.engine.snapshot_weights()  # rollback is a pointer flip

        # pre-canary baselines: the gauges' zero point + the probe refs
        base = self.server.metrics.snapshot()
        refs = self.probe_refs
        baseline_dice = 1.0
        if self.probe_rows:
            if refs is None:
                refs = self._probe_masks(canary_idx[0])  # old weights
            else:
                baseline_dice = self._probe_dice(canary_idx[0], refs)

        obsm.SERVE_ROLLOUT_CANARY.set(1)
        step = control.rollout_transition(self._state, "load_ok")
        self._transition(step.state, version=version, label=label,
                         canary_replicas=len(canary_idx))
        try:
            self.engine.swap_weights(params, model_state, version=version,
                                     replica_indices=canary_idx)
        except BaseException as exc:  # noqa: BLE001 — swap_crash site +
            # real device_put failures: partially-swapped canaries
            # restore, the old version never stopped serving
            logger.exception("rollout: canary swap failed")
            step = control.rollout_transition(self._state, "swap_failed")
            self._apply_restore(step, old, canary_idx)
            self._finish(step.outcome, reason=str(exc)[:300],
                         version=version)
            return

        # the health window: real traffic keeps flowing through the
        # canary group while the clock runs
        deadline = self.clock() + self.window_s
        while self.clock() < deadline and not self._stop.is_set():
            time.sleep(min(0.05, max(self.window_s / 20.0, 0.005)))

        reason = self._judge(base, canary_idx[0], refs, baseline_dice)
        if self._stop.is_set() and reason is None:
            reason = "rollout aborted (stop requested)"
        if reason is not None:
            step = control.rollout_transition(self._state, "judge_fail")
            self._apply_restore(step, old, canary_idx)
            self._finish(step.outcome, reason=reason,
                         version=version)
            return

        step = control.rollout_transition(self._state, "judge_pass")
        self._transition(step.state, version=version)
        try:
            if rest_idx:
                self.engine.swap_weights(params, model_state,
                                         version=version,
                                         replica_indices=rest_idx)
        except BaseException as exc:  # noqa: BLE001 — a promote-time
            # crash rolls EVERYTHING back: a fleet split across versions
            # must never be the steady state
            logger.exception("rollout: promote swap failed — rolling back")
            step = control.rollout_transition(self._state, "swap_failed")
            self._apply_restore(step, old, canary_idx)
            self._finish(step.outcome,
                         reason=f"promote failed: {str(exc)[:250]}",
                         version=version)
            return
        step = control.rollout_transition(self._state, "swap_ok")
        obsm.SERVE_WEIGHTS_VERSION.set(version)
        self._finish(step.outcome, version=version, label=label)

    def _judge(self, base: dict, canary_replica: int,
               refs: Optional[Sequence[np.ndarray]],
               baseline_dice: float) -> Optional[str]:
        """None = the canary passes; otherwise the rollback reason."""
        snap = self.server.metrics.snapshot()
        failed_delta = snap["requests_failed"] - base["requests_failed"]
        if failed_delta > self.max_error_responses:
            return (f"{failed_delta} error response(s) during the canary "
                    f"window (budget {self.max_error_responses})")
        if self.max_shed is not None:
            shed_delta = (
                snap["rejected"].get("overloaded", 0)
                - base["rejected"].get("overloaded", 0)
            )
            if shed_delta > self.max_shed:
                return (f"{shed_delta} request(s) shed during the canary "
                        f"window (budget {self.max_shed})")
        base_p99, p99 = base.get("p99_ms"), snap.get("p99_ms")
        if (base_p99 and p99 and p99 > self.p99_floor_ms
                and p99 > self.p99_factor * base_p99):
            return (f"p99 {p99:.1f} ms vs baseline {base_p99:.1f} ms "
                    f"(> {self.p99_factor:g}x)")
        if self.probe_rows and refs is not None:
            canary_dice = self._probe_dice(canary_replica, refs)
            if canary_dice < baseline_dice - self.dice_margin:
                return (f"pinned-sample Dice {canary_dice:.4f} vs "
                        f"baseline {baseline_dice:.4f} "
                        f"(margin {self.dice_margin:g})")
        return None


AB_ARM_A = "a"
AB_ARM_B = "b"


def ab_arm_for(request_id: str, split: float) -> str:
    """Deterministic request-id → A/B arm (crc32 split; ``split`` is
    arm "b"'s traffic fraction). One function, run identically by the
    router (to stamp ``X-AB-Arm``) and every worker (to arm unstamped
    requests), so a request keeps its arm across retries, hedges, and
    workers with zero shared state."""
    h = zlib.crc32(str(request_id).encode("utf-8")) & 0xFFFFFFFF
    return AB_ARM_B if (h / 2.0 ** 32) < float(split) else AB_ARM_A


def merge_fleet_verdict(per_worker: Dict[str, dict]) -> dict:
    """Fold each worker's ``/admin/ab`` verdict into ONE fleet verdict
    (the router's ``{"action": "verdict"}`` fan-in).

    Deterministic given the per-worker payloads: workers merge in
    sorted-address order, counters sum exactly, and every number keeps
    its provenance — per-arm p99 is reported worst-of-fleet alongside
    the per-worker values it came from.

    The Dice term is the subtle one: a worker with no pinned probe rows
    reports ``inter_arm_dice: null`` (no evidence), and the fleet mean
    averages ONLY workers that produced a value — excluded addresses
    are named, never silently zero-averaged (a 0.0 would claim the arms
    fully disagree on a worker that never compared them).
    """
    arms: Dict[str, dict] = {}
    dice_by_worker: Dict[str, Optional[float]] = {}
    merged: List[str] = []
    unmergeable: List[str] = []
    for addr in sorted(per_worker):
        verdict = per_worker[addr]
        if not isinstance(verdict, dict) or "arms" not in verdict:
            unmergeable.append(addr)
            continue
        merged.append(addr)
        dice_by_worker[addr] = verdict.get("inter_arm_dice")
        for arm, row in sorted(verdict.get("arms", {}).items()):
            agg = arms.setdefault(arm, {
                "requests_ok": 0, "requests_failed": 0,
                "images_ok": 0, "rejected": 0,
                "weights_versions": [],
                "p99_ms": None, "p99_ms_by_worker": {},
            })
            for key in ("requests_ok", "requests_failed",
                        "images_ok", "rejected"):
                agg[key] += int(row.get(key) or 0)
            version = row.get("weights_version")
            if version is not None and version not in agg[
                    "weights_versions"]:
                agg["weights_versions"].append(version)
            p99 = row.get("p99_ms")
            if p99 is not None:
                agg["p99_ms_by_worker"][addr] = p99
                agg["p99_ms"] = (p99 if agg["p99_ms"] is None
                                 else max(agg["p99_ms"], p99))
    dice_vals = [d for d in dice_by_worker.values() if d is not None]
    return {
        "workers": merged,
        "unmergeable": unmergeable,
        "arms": arms,
        "dice": {
            "fleet_mean": (round(sum(dice_vals) / len(dice_vals), 4)
                           if dice_vals else None),
            "per_worker": dice_by_worker,
            "excluded": sorted(addr for addr, d in dice_by_worker.items()
                               if d is None),
        },
    }


class ABTest:
    """Sustained weight A/B over disjoint replica groups.

    Where :class:`RolloutManager` is a *transient* judge (canary a few
    seconds, then converge the fleet to one version), an A/B pins TWO
    promoted versions side by side for as long as the experiment runs:
    arm ``a`` keeps the incumbent weights on the first half of the
    replica groups, arm ``b`` gets the candidate on the rest. Traffic
    splits by a deterministic hash of the request id (``arm_for`` —
    stable across processes, so the router and every worker agree on a
    request's arm without coordination), the batching queue keeps
    batches arm-pure (serve/queue.py), and the server's placement pins
    each arm's batches to its own replica group
    (``Server._claim_replica``). Per-arm Dice/latency/shed ledgers
    accumulate in ``ServeMetrics`` until ``verdict()`` is asked.

    Mixed versions automatically force the prediction cache to bypass
    itself (engine ``versions_mixed``), and the autoscaler holds while
    arms are pinned — resizing would tear a group boundary.

    ``stop(winner=...)`` promotes the winning arm's weights fleet-wide
    (a device-to-device pointer flip via ``engine.clone_weights``, zero
    recompiles) and unpins the groups. A bare ``stop()`` — the
    server-shutdown teardown path — just unpins.
    """

    def __init__(self, server,
                 probe_rows: Optional[Sequence[np.ndarray]] = None,
                 split: float = 0.5, clock=time.monotonic):
        self.server = server
        self.engine = server.engine
        self.probe_rows = list(probe_rows) if probe_rows else []
        # fraction of traffic routed to arm "b" (the candidate)
        self.split = min(max(float(split), 0.0), 1.0)
        self.clock = clock
        self._lock = threading.Lock()
        self.active = False
        self.label = ""
        self.arms: Dict[str, List[int]] = {}
        self.versions: Dict[str, int] = {}
        self.started_t: Optional[float] = None
        self.last_verdict: Optional[dict] = None
        self.history: List[dict] = []

    # -- deterministic request → arm split -----------------------------------
    def arm_for(self, request_id: str) -> str:
        """crc32-hash split: the SAME function runs in the router (to
        stamp ``X-AB-Arm``) and in every worker (to arm unstamped
        requests), so a request keeps its arm across retries, hedges,
        and workers without any shared state."""
        return ab_arm_for(request_id, self.split)

    # -- lifecycle -----------------------------------------------------------
    def start(self, source, label: str = "") -> dict:
        """Pin ``source`` (checkpoint path or ``(params, model_state)``
        tuple) as arm "b" on the back half of the replica groups.
        Synchronous — the load/swap happens off the serving path and
        the arms are live when this returns."""
        with self._lock:
            if self.active:
                raise RolloutInProgress("an A/B test is already running")
            rollout = getattr(self.server, "rollout", None)
            n = self.engine.num_replicas
            # the one-experiment-at-a-time guard is the pure rule the
            # protocol explorer model-checks (control.ab_may_start)
            refusal = control.ab_may_start(
                rollout_state=(rollout.state if rollout is not None
                               else STATE_IDLE),
                replica_groups=n,
            )
            if refusal is not None:
                if "rollout" in refusal:
                    raise RolloutInProgress(refusal)
                raise ValueError(refusal)
            params, model_state = self._load(source)
            a_idx = list(range(n - n // 2))
            b_idx = list(range(n - n // 2, n))
            version = self.engine.next_weights_version()
            old = self.engine.snapshot_weights(b_idx)
            # arms pin BEFORE the swap: from the first moment the groups
            # can disagree, placement and batching already honor them
            self.arms = {AB_ARM_A: a_idx, AB_ARM_B: b_idx}
            self.versions = {
                AB_ARM_A: self.engine.replicas[a_idx[0]].weights_version,
                AB_ARM_B: version,
            }
            self.label = label or str(source)[:120]
            self.server.ab_arms = {
                AB_ARM_A: frozenset(a_idx), AB_ARM_B: frozenset(b_idx),
            }
            self.active = True
            try:
                self.engine.swap_weights(params, model_state,
                                         version=version,
                                         replica_indices=b_idx)
            except BaseException as exc:  # noqa: BLE001 — swap_crash site
                # + real device_put failures: unpin and restore, the
                # incumbent never stopped serving
                logger.exception("ab: candidate swap failed")
                self.engine.restore_weights(old)
                self._teardown_locked()
                raise RuntimeError(
                    f"A/B candidate swap failed: {str(exc)[:250]}"
                ) from exc
            self.started_t = self.clock()
            obsm.SERVE_AB_ACTIVE.set(1)
            self._record("start", label=self.label, version_b=version,
                         arm_a=a_idx, arm_b=b_idx)
            return self.status()

    def verdict(self) -> dict:
        """The live scorecard: per-arm request/latency/shed aggregates
        from the server's A/B ledgers, plus — when probe rows were
        pinned — the inter-arm Dice agreement of the two versions on
        the same inputs (run straight off one replica per arm, no queue
        capacity consumed)."""
        with self._lock:
            if not self.active:
                return {"active": False, "last_verdict": self.last_verdict}
            return self._verdict_locked()

    def _verdict_locked(self) -> dict:
        ab = self.server.metrics.ab_snapshot()
        out = {
            "active": True,
            "label": self.label,
            "split": self.split,
            "elapsed_s": round(self.clock() - self.started_t, 3),
            "arms": {
                arm: {
                    "replicas": list(idx),
                    "weights_version": self.versions.get(arm),
                    **ab.get(arm, {}),
                }
                for arm, idx in sorted(self.arms.items())
            },
        }
        if self.probe_rows:
            masks_a = self._probe_masks(self.arms[AB_ARM_A][0])
            masks_b = self._probe_masks(self.arms[AB_ARM_B][0])
            out["inter_arm_dice"] = round(float(np.mean([
                mask_dice(ma, mb) for ma, mb in zip(masks_a, masks_b)
            ])), 4)
        else:
            # no probe rows pinned on THIS worker → no Dice evidence.
            # null, never 0.0: a fleet merge averaging in a zero would
            # read "the arms disagree completely" where the truth is
            # "this worker has nothing to say" (merge_fleet_verdict
            # excludes null from the fleet Dice mean).
            out["inter_arm_dice"] = None
        return out

    def stop(self, winner: Optional[str] = None) -> dict:
        """End the experiment. ``winner`` "a"/"b" promotes that arm's
        weights onto every replica group (pointer flip, no recompile,
        no drain) before unpinning; None — the bare teardown
        ``Server.stop()`` calls — leaves each group's weights as they
        stand and just unpins."""
        with self._lock:
            if not self.active:
                return {"active": False, "note": "no A/B running"}
            if winner not in (None, AB_ARM_A, AB_ARM_B):
                raise ValueError(f"winner must be 'a', 'b', or None "
                                 f"(got {winner!r})")
            final = self._verdict_locked()
            if winner is not None:
                src = self.arms[winner][0]
                dst = [i for idx in self.arms.values() for i in idx]
                self.engine.clone_weights(src, dst)
                obsm.SERVE_WEIGHTS_VERSION.set(
                    self.versions.get(winner, 0))
            self._record("stop", winner=winner,
                         version=self.versions.get(winner))
            self._teardown_locked()
            self.last_verdict = {**final, "active": False,
                                 "winner": winner}
            return {"stopped": True, "winner": winner, "verdict": final}

    def status(self) -> dict:
        return {
            "active": self.active,
            "label": self.label if self.active else None,
            "split": self.split,
            "arms": {
                arm: {"replicas": list(idx),
                      "weights_version": self.versions.get(arm)}
                for arm, idx in sorted(self.arms.items())
            } if self.active else None,
            "metrics": self.server.metrics.ab_snapshot() or None,
            "last_verdict": self.last_verdict,
            "history": self.history[-10:],
        }

    # -- internals -----------------------------------------------------------
    def _teardown_locked(self) -> None:
        self.server.ab_arms = None
        self.active = False
        self.arms = {}
        self.versions = {}
        self.started_t = None
        obsm.SERVE_AB_ACTIVE.set(0)

    def _record(self, event: str, **fields) -> None:
        entry = {"event": event, "t": time.time(), **fields}
        self.history.append(entry)
        del self.history[:-50]
        flight.record("ab_test", **{k: v for k, v in entry.items()
                                    if k != "t"})
        logger.info("ab: %s %s", event,
                    " ".join(f"{k}={v}" for k, v in fields.items()))

    def _load(self, source) -> Tuple[object, object]:
        if isinstance(source, tuple):
            return source[0], source[1]
        loader = self.engine.bundle_loader
        if loader is None:
            raise ValueError(
                "this engine was built from raw arrays (no checkpoint "
                "context) — pass a (params, model_state) tuple instead "
                "of a checkpoint path"
            )
        bundle = loader(str(source))
        return bundle.params, bundle.model_state

    def _probe_masks(self, replica_index: int) -> List[np.ndarray]:
        masks: List[np.ndarray] = []
        chunk = self.engine.planner.max_size
        for i in range(0, len(self.probe_rows), chunk):
            batch = np.stack(self.probe_rows[i:i + chunk])
            out = self.engine.infer(batch, replica_index=replica_index)
            masks.extend(self.engine.postprocess(out[j])
                         for j in range(out.shape[0]))
        return masks


class CheckpointWatcher:
    """``--watch-checkpoint``: poll one checkpoint path and run the
    rollout state machine whenever the file is replaced (the trainer's
    writes are atomic tmp+rename, so a changed mtime is a complete
    file; one extra stable poll guards non-atomic writers). The gate is
    the manager's — a watched checkpoint that regresses the canary rolls
    back exactly like a ``POST /admin/rollout`` one."""

    def __init__(self, manager: RolloutManager, path: str,
                 poll_s: float = 2.0):
        self.manager = manager
        self.path = str(path)
        self.poll_s = max(0.05, float(poll_s))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._seen_mtime = self._mtime()
        self.triggered = 0

    def _mtime(self) -> Optional[float]:
        try:
            return os.stat(self.path).st_mtime
        except OSError:
            return None

    def start(self) -> "CheckpointWatcher":
        self._thread = threading.Thread(
            target=self._run, name="dpt-ckpt-watch", daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def _run(self) -> None:
        pending: Optional[float] = None
        while not self._stop.wait(self.poll_s):
            mtime = self._mtime()
            if mtime is None or mtime == self._seen_mtime:
                pending = None
                continue
            if pending is None or mtime != pending:
                pending = mtime  # first sight — wait one poll for quiet
                continue
            self._seen_mtime = mtime
            pending = None
            self.triggered += 1
            logger.info("checkpoint watcher: %s changed — starting a "
                        "canaried rollout", self.path)
            try:
                self.manager.start(self.path, label="watch-checkpoint")
            except RolloutInProgress:
                logger.warning(
                    "checkpoint watcher: rollout already in flight — "
                    "will retry at the next change"
                )
                self._seen_mtime = None  # re-trigger on the next poll
