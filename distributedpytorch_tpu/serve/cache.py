"""Clipper-style prediction cache: exact-match masks, in front of the
queue.

Clipper (NSDI '17) put a prediction cache between the frontend and the
model containers: repeated traffic over identical inputs — the shape a
CDN miss storm or a hot object produces — answers from memory instead
of spending accelerator time. This is that layer for the serve tier:

* **keyed on the decoded-input hash** — the request's decoded float32
  rows (the same bytes the ``SampleCache`` decode path produces), so
  two byte-different JPEGs that decode to the same tensor still hit,
  and a path-keyed and an inline-upload of the same image share an
  entry;
* **versioned** — the key includes the engine's promoted
  ``weights_version``, so a weight rollout implicitly invalidates every
  cached mask (stale entries become unreachable and LRU-age out), and
  lookups are bypassed entirely while a canary has the replica groups
  serving *different* versions (one key, two answers);
* **bounded** — an LRU over a byte budget (``--predict-cache-mb``):
  masks are ``(H, W) uint8``, so the budget translates directly to
  entries; a long-running server never grows memory per distinct input.

Thread-safe: HTTP handler threads look up concurrently while completion
workers insert. Hit/miss counters ride the process-wide registry
(``dpt_serve_predict_cache_total{result=...}`` in ``/metrics``) and the
per-server ``/stats`` snapshot.
"""

from __future__ import annotations

import collections
import hashlib
import threading
from typing import List, Optional, Sequence

import numpy as np

from distributedpytorch_tpu.obs import defs as obsm


def request_key(rows: Sequence[np.ndarray], weights_version: int) -> str:
    """The exact-match cache key: sha256 over the decoded rows' bytes +
    shapes, scoped to the weights version that would answer it."""
    h = hashlib.sha256()
    h.update(f"v{int(weights_version)}".encode())
    for row in rows:
        arr = np.ascontiguousarray(row)
        h.update(str(arr.shape).encode())
        h.update(arr.tobytes())
    return h.hexdigest()


class PredictionCache:
    """Bounded-byte LRU of served masks, keyed by :func:`request_key`."""

    def __init__(self, budget_bytes: int):
        self.budget_bytes = int(budget_bytes)
        self._items: "collections.OrderedDict[str, List[np.ndarray]]" = (
            collections.OrderedDict()
        )
        self._lock = threading.Lock()
        self.used_bytes = 0
        self.hits = 0
        self.misses = 0
        self.bypasses = 0

    @staticmethod
    def _nbytes(masks: List[np.ndarray]) -> int:
        return sum(int(m.nbytes) for m in masks)

    def get(self, key: str) -> Optional[List[np.ndarray]]:
        with self._lock:
            masks = self._items.get(key)
            if masks is None:
                self.misses += 1
                obsm.SERVE_PREDICT_CACHE.labels(result="miss").inc()
                return None
            self._items.move_to_end(key)  # LRU touch
            self.hits += 1
            obsm.SERVE_PREDICT_CACHE.labels(result="hit").inc()
            return masks

    def put(self, key: str, masks: List[np.ndarray]) -> bool:
        """Store (evicting LRU entries past the budget); returns whether
        it was stored. Oversized single entries are refused rather than
        flushing the whole cache for one giant request."""
        size = self._nbytes(masks)
        if size > self.budget_bytes:
            return False
        with self._lock:
            old = self._items.pop(key, None)
            if old is not None:
                self.used_bytes -= self._nbytes(old)
            self._items[key] = masks
            self.used_bytes += size
            while self.used_bytes > self.budget_bytes and self._items:
                _k, evicted = self._items.popitem(last=False)
                self.used_bytes -= self._nbytes(evicted)
        return True

    def record_bypass(self) -> None:
        """A lookup skipped because replica groups serve mixed weight
        versions (rollout canary in flight): counted so the fleet pane
        can attribute a hit-rate dip — and a shed burst — to the bypass
        window instead of guessing."""
        with self._lock:
            self.bypasses += 1
        obsm.SERVE_PREDICT_CACHE.labels(result="bypass").inc()

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "bypasses": self.bypasses,
                "entries": len(self._items),
                "bytes": self.used_bytes,
            }
