"""The replica executor: AOT-compiled eval executables per bucket shape,
replicated data-parallel over the mesh's devices.

**AOT, not JIT.** ``jax.jit`` compiles on first call — a 20-40 s stall
on TPU that would land on whichever unlucky request first rides each
bucket shape. The serving tier instead compiles every (bucket × replica)
executable at *startup* via the same ``.lower(...).compile()`` path the
analyzer's ``--hlo`` tier exercises (analysis/collectives.py): inputs
are ``ShapeDtypeStruct``s carrying a ``SingleDeviceSharding``, so each
executable is built for — and pinned to — its replica's device, and the
first request pays exactly zero compiler time. A compiled executable
also *rejects* any shape it wasn't built for, which converts a bucket
accounting bug from silent recompilation into a loud TypeError.

**Replica groups.** Serving is embarrassingly data-parallel: N devices
serve N concurrent buckets with no cross-device collective (the static
preflight accordingly treats serve configs as non-collective). Each
replica holds its own device-resident copy of the weights and its own
per-bucket executables; the server round-robins flushed buckets across
free replicas. ``replicas`` clamps to the devices actually present, so
the same config serves a laptop CPU and an 8-chip host.

**Host-side decode cache.** Path-keyed requests decode through the PR-1
``SampleCache`` — the serving analogue of Clipper's prediction-adjacent
caching: repeated traffic over the same objects (the common case behind
a CDN miss storm) skips PIL/libjpeg entirely on the request path.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from distributedpytorch_tpu.data.dataset import SampleCache
from distributedpytorch_tpu.serve.bucketing import BucketPlanner
from distributedpytorch_tpu.serve.infer import (
    InferenceBundle,
    bundle_variables,
    make_forward,
    postprocess_mask,
    preprocess_image,
)

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class Replica:
    """One device's serving state: weights resident on ``device`` and one
    compiled executable per bucket size."""

    index: int
    device: object
    sharding: object
    variables: object
    compiled: Dict[int, object]


class ServeEngine:
    """Build with an :class:`InferenceBundle` (checkpoint path) or raw
    ``(model, params, model_state)`` pieces (tests / bench fresh-init)."""

    def __init__(
        self,
        model,
        params,
        model_state,
        input_hw: Tuple[int, int],
        bucket_sizes: Sequence[int] = (1, 2, 4, 8),
        replicas: int = 1,
        threshold: float = 0.5,
        host_cache_mb: int = 0,
        channels: int = 3,
        quantized: bool = False,
        kernels="xla",
    ):
        import jax

        from distributedpytorch_tpu.ops.kernels import get_kernel_policy

        self.planner = BucketPlanner(bucket_sizes)
        self.input_hw = (int(input_hw[0]), int(input_hw[1]))
        self.threshold = float(threshold)
        self.channels = int(channels)
        self.cache = (
            SampleCache(host_cache_mb * 2**20) if host_cache_mb > 0 else None
        )
        self.stateful = bool(getattr(model, "is_stateful", False))
        # int8 weights-only serving (ops/quant.py): `params` is the
        # quantized tree; each replica's device-resident weights stay one
        # byte per element and the forward dequantizes in-trace
        self.quantized = bool(quantized)
        # kernel policy (--kernels, ops/kernels.py): with serve_mask
        # engaged the AOT bucket executables threshold ON DEVICE through
        # the fused sigmoid/threshold kernel and return uint8 masks —
        # postprocess() then passes them through untouched (bit-identical
        # to the host threshold at the same operating point)
        self.kernel_policy = get_kernel_policy(kernels)
        self.mask_on_device = self.kernel_policy.serve_mask
        self._fwd = make_forward(
            model,
            quantized=self.quantized,
            mask_threshold=self.threshold if self.mask_on_device else None,
        )
        variables = bundle_variables(model, params, model_state)

        devices = jax.devices()
        n = max(1, min(int(replicas), len(devices)))
        if replicas > len(devices):
            logger.warning(
                "requested %d replicas but only %d devices — serving with %d",
                replicas, len(devices), n,
            )
        t0 = time.monotonic()
        self.replicas: List[Replica] = [
            self._build_replica(i, devices[i], variables) for i in range(n)
        ]
        logger.info(
            "AOT-compiled %d bucket executables (%s) x %d replica(s) in "
            "%.1f s — first-request latency pays no JIT",
            len(self.planner.sizes), list(self.planner.sizes), n,
            time.monotonic() - t0,
        )

    @classmethod
    def from_bundle(cls, bundle: InferenceBundle, **kwargs) -> "ServeEngine":
        kwargs.setdefault("quantized", bundle.quantized)
        return cls(
            bundle.model, bundle.params, bundle.model_state,
            input_hw=bundle.input_hw, **kwargs,
        )

    def _build_replica(self, index: int, device, variables) -> Replica:
        import jax
        import jax.numpy as jnp
        from jax.sharding import SingleDeviceSharding

        sharding = SingleDeviceSharding(device)
        vars_dev = jax.device_put(variables, sharding)
        h, w = self.input_hw
        jitted = jax.jit(self._fwd)
        compiled: Dict[int, object] = {}
        for b in self.planner.sizes:
            x_sds = jax.ShapeDtypeStruct(
                (b, h, w, self.channels), jnp.float32, sharding=sharding
            )
            compiled[b] = jitted.lower(vars_dev, x_sds).compile()
        return Replica(
            index=index, device=device, sharding=sharding,
            variables=vars_dev, compiled=compiled,
        )

    @property
    def num_replicas(self) -> int:
        return len(self.replicas)

    # -- request path pieces (the server wires these together) ---------------
    def place(self, replica: Replica, batch: np.ndarray):
        """Host batch → replica's device. Non-blocking on async runtimes;
        the server runs it on the placement worker (pipelined_placement)
        so the H2D of bucket N+1 rides under bucket N's dispatch."""
        import jax

        return jax.device_put(batch, replica.sharding)

    def run(self, replica: Replica, x_dev):
        """Dispatch the bucket's compiled executable. Raises KeyError for
        a batch shape no executable was built for — bucket accounting
        bugs fail loudly instead of recompiling silently."""
        return replica.compiled[x_dev.shape[0]](replica.variables, x_dev)

    def infer(self, batch: np.ndarray, replica_index: int = 0) -> np.ndarray:
        """Synchronous single-bucket inference (tests, warmup): pads to
        the smallest covering bucket, runs, returns the REAL rows'
        probabilities as host float32 ``(n, H, W)`` — or, with the
        serve-mask kernel engaged, the ``(n, H, W) uint8`` masks the
        executable thresholded on device."""
        from distributedpytorch_tpu.serve.bucketing import pad_batch

        n = batch.shape[0]
        bucket = self.planner.bucket_for(n)
        if bucket is None:
            raise ValueError(
                f"batch of {n} exceeds the largest bucket "
                f"({self.planner.max_size})"
            )
        replica = self.replicas[replica_index]
        x = self.place(replica, pad_batch(np.asarray(batch, np.float32), bucket))
        return np.asarray(self.run(replica, x))[:n]

    def warmup(self) -> None:
        """Execute every (replica, bucket) once on zeros: allocator pools
        and any lazy runtime setup warm before traffic (compiles already
        happened at construction)."""
        h, w = self.input_hw
        for replica in self.replicas:
            for b in self.planner.sizes:
                x = self.place(
                    replica, np.zeros((b, h, w, self.channels), np.float32)
                )
                np.asarray(self.run(replica, x))

    # -- host-side decode (ingress; SampleCache-backed) ----------------------
    def preprocess(self, source, cache_key=None) -> np.ndarray:
        """One image source → a model input row ``(H, W, C) float32``.
        ``source`` may be a ready array (validated), a PIL image, or a
        path (decoded through the cache when one is configured —
        ``cache_key`` defaults to the path)."""
        h, w = self.input_hw
        if isinstance(source, np.ndarray):
            if source.shape != (h, w, self.channels):
                raise ValueError(
                    f"expected ({h}, {w}, {self.channels}) input row, got "
                    f"{source.shape}"
                )
            return np.asarray(source, np.float32)
        if isinstance(source, str):
            key = cache_key if cache_key is not None else (source, (w, h))
            if self.cache is not None:
                hit = self.cache.get(key)
                if hit is not None:
                    return hit["image"]
            from distributedpytorch_tpu.serve.infer import load_image

            row = load_image(source, (w, h))
            if self.cache is not None:
                self.cache.put(key, {"image": row})
            return row
        # PIL image (duck-typed: anything with .convert/.resize)
        return preprocess_image(source, (w, h))

    def postprocess(self, probs: np.ndarray) -> np.ndarray:
        return postprocess_mask(probs, self.threshold)


def engine_from_checkpoint(
    checkpoint: str,
    checkpoint_dir: str = "./checkpoints",
    image_size: Sequence[int] = (960, 640),
    model_arch: str = "unet",
    model_widths: Optional[Sequence[int]] = None,
    s2d_levels: int = -1,
    quantize: Optional[str] = None,
    **engine_kwargs,
) -> ServeEngine:
    """Checkpoint name/path → a ready (AOT-compiled) engine.
    ``quantize="int8"`` serves weights-only int8 (see
    serve/infer.load_inference_bundle for the file-vs-on-load rules)."""
    from distributedpytorch_tpu.serve.infer import load_inference_bundle

    bundle = load_inference_bundle(
        checkpoint, checkpoint_dir=checkpoint_dir, image_size=image_size,
        model_arch=model_arch, model_widths=model_widths,
        s2d_levels=s2d_levels, quantize=quantize,
    )
    return ServeEngine.from_bundle(bundle, **engine_kwargs)
