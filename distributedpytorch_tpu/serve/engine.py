"""The replica executor: AOT-compiled eval executables per bucket shape,
replicated data-parallel over the mesh's devices.

**AOT, not JIT.** ``jax.jit`` compiles on first call — a 20-40 s stall
on TPU that would land on whichever unlucky request first rides each
bucket shape. The serving tier instead compiles every (bucket × replica)
executable at *startup* via the same ``.lower(...).compile()`` path the
analyzer's ``--hlo`` tier exercises (analysis/collectives.py): inputs
are ``ShapeDtypeStruct``s carrying a ``SingleDeviceSharding``, so each
executable is built for — and pinned to — its replica's device, and the
first request pays exactly zero compiler time. A compiled executable
also *rejects* any shape it wasn't built for, which converts a bucket
accounting bug from silent recompilation into a loud TypeError.

**Replica groups.** Serving is embarrassingly data-parallel: N devices
serve N concurrent buckets with no cross-device collective (the static
preflight accordingly treats serve configs as non-collective). Each
replica holds its own device-resident copy of the weights and its own
per-bucket executables; the server round-robins flushed buckets across
free replicas. ``replicas`` clamps to the devices actually present, so
the same config serves a laptop CPU and an 8-chip host.

**Host-side decode cache.** Path-keyed requests decode through the PR-1
``SampleCache`` — the serving analogue of Clipper's prediction-adjacent
caching: repeated traffic over the same objects (the common case behind
a CDN miss storm) skips PIL/libjpeg entirely on the request path.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from distributedpytorch_tpu.data.dataset import SampleCache
from distributedpytorch_tpu.serve.bucketing import BucketPlanner
from distributedpytorch_tpu.serve.infer import (
    InferenceBundle,
    bundle_variables,
    make_forward,
    postprocess_mask,
    preprocess_image,
)

logger = logging.getLogger(__name__)


def serve_jit(fn):
    """The engine's ONE jit wrapper: every serve executable — every
    bucket of every replica, and therefore every entry admitted to the
    AOT store — lowers through here. It must NEVER donate: serve
    executables re-read their weights operand on every request, and a
    store-shared executable additionally re-reads buffers that sibling
    processes rehydrate — a donated operand is freed after the first
    call and the next request reads poisoned memory (the CPU-backend
    SIGABRT class). Kept as a named module-level seam so the donation
    pass (analysis/donation.py) can lower THROUGH the exact wrapper the
    engine uses, and its mutation tests can donate here and prove the
    pass catches it."""
    import jax

    return jax.jit(fn)


@dataclasses.dataclass
class Replica:
    """One device's serving state: weights resident on ``device`` and one
    compiled executable per bucket size. ``weights_version`` tracks which
    hot-swap generation this replica serves (0 = the startup weights) —
    during a rollout canary the groups legitimately diverge."""

    index: int
    device: object
    sharding: object
    variables: object
    compiled: Dict[int, object]
    weights_version: int = 0


class ServeEngine:
    """Build with an :class:`InferenceBundle` (checkpoint path) or raw
    ``(model, params, model_state)`` pieces (tests / bench fresh-init)."""

    def __init__(
        self,
        model,
        params,
        model_state,
        input_hw: Tuple[int, int],
        bucket_sizes: Sequence[int] = (1, 2, 4, 8),
        replicas: int = 1,
        threshold: float = 0.5,
        host_cache_mb: int = 0,
        channels: int = 3,
        quantized: bool = False,
        kernels="xla",
        aot_cache=None,
        engine_fingerprint: Optional[str] = None,
    ):
        import jax

        from distributedpytorch_tpu.ops.kernels import get_kernel_policy
        from distributedpytorch_tpu.utils.aotstore import AOTStore

        self.planner = BucketPlanner(bucket_sizes)
        self.model = model
        self.input_hw = (int(input_hw[0]), int(input_hw[1]))
        self.threshold = float(threshold)
        self.channels = int(channels)
        # set by engine_from_checkpoint: loads a NEW checkpoint with this
        # engine's exact model identity/quantization for a weight rollout
        # (serve/rollout.py); raw-built engines swap via arrays directly
        self.bundle_loader = None
        # monotonic over the engine's lifetime and NEVER rewound by a
        # rollback — version numbers are cache-key material (serve/
        # cache.py), so a rejected candidate's number must not be reused
        # by the next candidate
        self._version_counter = 0
        self.cache = (
            SampleCache(host_cache_mb * 2**20) if host_cache_mb > 0 else None
        )
        self.stateful = bool(getattr(model, "is_stateful", False))
        # int8 weights-only serving (ops/quant.py): `params` is the
        # quantized tree; each replica's device-resident weights stay one
        # byte per element and the forward dequantizes in-trace
        self.quantized = bool(quantized)
        # kernel policy (--kernels, ops/kernels.py): with serve_mask
        # engaged the AOT bucket executables threshold ON DEVICE through
        # the fused sigmoid/threshold kernel and return uint8 masks —
        # postprocess() then passes them through untouched (bit-identical
        # to the host threshold at the same operating point)
        self.kernel_policy = get_kernel_policy(kernels)
        self.mask_on_device = self.kernel_policy.serve_mask
        self._fwd = make_forward(
            model,
            quantized=self.quantized,
            mask_threshold=self.threshold if self.mask_on_device else None,
        )
        variables = bundle_variables(model, params, model_state)

        # content-addressed AOT executable store (utils/aotstore.py):
        # on hit each bucket executable LOADS instead of compiling; a
        # raw-built engine without a model fingerprint disables the
        # store — a key missing the model identity could load a
        # wrong program (engine_from_checkpoint always computes one)
        self.fingerprint = engine_fingerprint
        self.aot_store = AOTStore.resolve(aot_cache)
        if self.aot_store is not None and not self.fingerprint:
            logger.warning(
                "AOT executable store at %s DISABLED for this engine: "
                "no engine fingerprint (pass engine_fingerprint=... for "
                "raw-built engines)", self.aot_store.root,
            )
            self.aot_store = None
        # lifetime _compile_bucket invocations — the compile-count spy
        # seam (tests) and the rollout path's zero-recompile accounting
        self.aot_compiles = 0

        devices = jax.devices()
        n = max(1, min(int(replicas), len(devices)))
        if replicas > len(devices):
            logger.warning(
                "requested %d replicas but only %d devices — serving with %d",
                replicas, len(devices), n,
            )
        t0 = time.monotonic()
        self.replicas: List[Replica] = [
            self._build_replica(i, devices[i], variables) for i in range(n)
        ]
        loaded = self.aot_store.stats["hit"] if self.aot_store else 0
        logger.info(
            "AOT-compiled %d + store-loaded %d bucket executables (%s) "
            "x %d replica(s) in %.1f s — first-request latency pays "
            "no JIT",
            self.aot_compiles, loaded, list(self.planner.sizes), n,
            time.monotonic() - t0,
        )

    @classmethod
    def from_bundle(cls, bundle: InferenceBundle, **kwargs) -> "ServeEngine":
        kwargs.setdefault("quantized", bundle.quantized)
        return cls(
            bundle.model, bundle.params, bundle.model_state,
            input_hw=bundle.input_hw, **kwargs,
        )

    def _build_replica(self, index: int, device, variables) -> Replica:
        import jax
        import jax.numpy as jnp
        from jax.sharding import SingleDeviceSharding

        sharding = SingleDeviceSharding(device)
        vars_dev = jax.device_put(variables, sharding)
        h, w = self.input_hw
        jitted = serve_jit(self._fwd)
        compiled: Dict[int, object] = {}
        for b in self.planner.sizes:
            x_sds = jax.ShapeDtypeStruct(
                (b, h, w, self.channels), jnp.float32, sharding=sharding
            )
            key = meta = exe = None
            if self.aot_store is not None:
                key, meta = self._entry_key(b, device)
                exe = self.aot_store.load(key, meta)
            if exe is None:
                exe = self._compile_bucket(jitted, vars_dev, x_sds)
                if self.aot_store is not None:
                    self.aot_store.save(key, meta, exe)
            compiled[b] = exe
        return Replica(
            index=index, device=device, sharding=sharding,
            variables=vars_dev, compiled=compiled,
        )

    def _compile_bucket(self, jitted, vars_dev, x_sds):
        """The engine's ONLY compile site — store hits never reach it,
        which is what the compile-count spy tests pin."""
        self.aot_compiles += 1
        if self.aot_store is None:
            return jitted.lower(vars_dev, x_sds).compile()
        # The result is about to be persisted to the AOT store — and an
        # executable rehydrated from the persistent XLA compilation
        # cache serializes WITHOUT its backend kernel symbols, so the
        # store entry would be refused ("Symbols not found") by every
        # sibling process that tries to load it. Codegen fresh: the AOT
        # store replaces exactly what the XLA cache would have saved.
        # (no_xla_compilation_cache also resets jax's memoized
        # is-cache-used state — a bare flag flip is silently ignored
        # after the process's first compile.)
        from distributedpytorch_tpu.utils.aotstore import (
            no_xla_compilation_cache,
        )

        with no_xla_compilation_cache():
            return jitted.lower(vars_dev, x_sds).compile()

    def _entry_key(self, bucket: int, device) -> Tuple[str, dict]:
        """Store key for one bucket executable on one device. The
        on-device mask threshold is key material (it is baked into the
        trace); the device is too — each executable carries a
        ``SingleDeviceSharding`` and deserializes pinned to it. The
        device component goes through ``device_key`` so
        ``$DPT_AOT_KEY_SCHEME=kind`` can relax the full decorated
        string to a kind+ordinal scheme that identical chips share."""
        from distributedpytorch_tpu.utils.aotstore import (
            device_key,
            entry_key,
        )

        h, w = self.input_hw
        return entry_key(
            self.fingerprint,
            bucket,
            (bucket, h, w, self.channels),
            "float32",
            kernels=self.kernel_policy.name,
            mask_threshold=(
                self.threshold if self.mask_on_device else None
            ),
            quantized=self.quantized,
            stateful=self.stateful,
            device=device_key(device),
        )

    @property
    def aot_cache_stats(self) -> dict:
        """The store's cold-start story for THIS engine build (the
        serve ``/stats`` ``aot_cache`` block; the process-wide view is
        the ``dpt_aot_cache_total`` counter family)."""
        base = {"enabled": False, "dir": None,
                "hit": 0, "miss": 0, "skew": 0}
        if self.aot_store is not None:
            base.update({"enabled": True, "dir": self.aot_store.root,
                         **self.aot_store.stats})
        base["compiles"] = self.aot_compiles
        return base

    @property
    def num_replicas(self) -> int:
        return len(self.replicas)

    # -- live replica-group scaling (serve/scaler.py drives this) ------------
    def add_replica(self) -> Replica:
        """Grow the replica group by one device — the autoscaler's grow
        actuator. The new replica's weights come from replica 0's
        device-resident tree (the host tree is not retained past
        ``__init__``; a cross-device ``device_put`` re-homes it), so it
        joins at the currently promoted version. With a warm AOT store
        every bucket executable is a load, not a compile — which is
        what makes in-process scale-up cheap enough to actuate."""
        import jax

        devices = jax.devices()
        if self.num_replicas >= len(devices):
            raise RuntimeError(
                f"cannot grow past {len(devices)} device(s) "
                f"(already at {self.num_replicas} replicas)"
            )
        src = self.replicas[0]
        index = self.num_replicas
        replica = self._build_replica(index, devices[index], src.variables)
        replica.weights_version = src.weights_version
        self.replicas.append(replica)
        return replica

    def retire_replica(self) -> Replica:
        """Shrink the replica group by one — pops the highest-index
        replica. The caller (``Server.resize_replicas``) must have
        drained that replica's dispatch slots first; the device tree
        and executables are simply dropped (executables stay in the AOT
        store, so the next grow re-loads them)."""
        if self.num_replicas <= 1:
            raise RuntimeError("cannot retire the last replica")
        return self.replicas.pop()

    # -- zero-downtime weight hot-swap (serve/rollout.py drives this) --------
    @property
    def weights_version(self) -> int:
        """The version serving on EVERY replica group — what ``/stats``
        reports. During a canary the groups diverge; the promoted
        version is the fleet-wide floor."""
        return min(r.weights_version for r in self.replicas)

    @property
    def versions_mixed(self) -> bool:
        """True while replica groups serve different weight versions (a
        rollout canary is in flight) — the prediction cache bypasses
        itself then, since one key would map to two answers."""
        versions = {r.weights_version for r in self.replicas}
        return len(versions) > 1

    def next_weights_version(self) -> int:
        """A fresh, never-reused version number for a rollout candidate
        (rollbacks rewind replica versions, never this counter)."""
        return self._version_counter + 1

    def swap_weights(self, params, model_state=None, version: int = 0,
                     replica_indices: Optional[Sequence[int]] = None) -> None:
        """``device_put`` a new weight tree into the running replicas —
        no recompile, no drain: the AOT executables take ``variables`` as
        an *argument*, so the next dispatch simply passes the new tree
        (an in-flight dispatch keeps its old reference — the swap is a
        host-side pointer flip, atomic per replica).

        ``params`` must match the engine's compiled tree structure: a
        float engine takes float params, an int8 engine takes a
        quantized tree (``bundle_loader`` enforces this for checkpoint
        sources). The ``swap_crash`` chaos site fires per replica BEFORE
        its assignment, so an injected crash leaves that replica — and
        every later one — still serving the old weights."""
        import jax

        from distributedpytorch_tpu.utils import faults

        variables = bundle_variables(self.model, params, model_state)
        indices = (list(range(self.num_replicas))
                   if replica_indices is None else list(replica_indices))
        self._version_counter = max(self._version_counter, int(version))
        for i in indices:
            replica = self.replicas[i]
            if faults.fire("swap_crash", step=i):
                raise faults.InjectedFault(
                    f"injected swap_crash at replica {i}"
                )
            vars_dev = jax.device_put(variables, replica.sharding)
            # version BEFORE variables, matching the dispatch loop's
            # variables-then-version read order: the racing pair can
            # then read (old vars, new version) — a skipped cache put —
            # but never (new vars, old version), which would cache a
            # candidate's mask under the promoted version's key
            replica.weights_version = int(version)
            replica.variables = vars_dev

    def clone_weights(self, src_index: int,
                      dst_indices: Sequence[int]) -> None:
        """Copy one replica's device-resident weights (and version) onto
        other replicas — a device-to-device ``device_put``, no disk, no
        recompile. Same version-before-variables write order as
        ``swap_weights``. The sustained-A/B stop path promotes the
        winning arm's weights fleet-wide through this."""
        import jax

        src = self.replicas[int(src_index)]
        for i in dst_indices:
            replica = self.replicas[i]
            if replica is src:
                continue
            vars_dev = jax.device_put(src.variables, replica.sharding)
            replica.weights_version = src.weights_version
            replica.variables = vars_dev

    def restore_weights(self, saved: Dict[int, tuple]) -> None:
        """Roll back replicas to snapshots taken by
        :meth:`snapshot_weights` (the canary-rollback path — the old
        device trees were never freed, so this is another pointer flip).
        Same version-before-variables write order as ``swap_weights``;
        the version counter never rewinds."""
        for i, (variables, version) in saved.items():
            replica = self.replicas[i]
            replica.weights_version = version
            replica.variables = variables

    def snapshot_weights(
        self, replica_indices: Optional[Sequence[int]] = None
    ) -> Dict[int, tuple]:
        indices = (list(range(self.num_replicas))
                   if replica_indices is None else list(replica_indices))
        return {
            i: (self.replicas[i].variables, self.replicas[i].weights_version)
            for i in indices
        }

    # -- request path pieces (the server wires these together) ---------------
    def place(self, replica: Replica, batch: np.ndarray):
        """Host batch → replica's device. Non-blocking on async runtimes;
        the server runs it on the placement worker (pipelined_placement)
        so the H2D of bucket N+1 rides under bucket N's dispatch."""
        import jax

        return jax.device_put(batch, replica.sharding)

    def run(self, replica: Replica, x_dev):
        """Dispatch the bucket's compiled executable. Raises KeyError for
        a batch shape no executable was built for — bucket accounting
        bugs fail loudly instead of recompiling silently."""
        return replica.compiled[x_dev.shape[0]](replica.variables, x_dev)

    def infer(self, batch: np.ndarray, replica_index: int = 0) -> np.ndarray:
        """Synchronous single-bucket inference (tests, warmup): pads to
        the smallest covering bucket, runs, returns the REAL rows'
        probabilities as host float32 ``(n, H, W)`` — or, with the
        serve-mask kernel engaged, the ``(n, H, W) uint8`` masks the
        executable thresholded on device."""
        from distributedpytorch_tpu.serve.bucketing import pad_batch

        n = batch.shape[0]
        bucket = self.planner.bucket_for(n)
        if bucket is None:
            raise ValueError(
                f"batch of {n} exceeds the largest bucket "
                f"({self.planner.max_size})"
            )
        replica = self.replicas[replica_index]
        x = self.place(replica, pad_batch(np.asarray(batch, np.float32), bucket))
        return np.asarray(self.run(replica, x))[:n]

    def warmup(self) -> None:
        """Execute every (replica, bucket) once on zeros: allocator pools
        and any lazy runtime setup warm before traffic (compiles already
        happened at construction)."""
        h, w = self.input_hw
        for replica in self.replicas:
            for b in self.planner.sizes:
                x = self.place(
                    replica, np.zeros((b, h, w, self.channels), np.float32)
                )
                np.asarray(self.run(replica, x))

    # -- host-side decode (ingress; SampleCache-backed) ----------------------
    def preprocess(self, source, cache_key=None) -> np.ndarray:
        """One image source → a model input row ``(H, W, C) float32``.
        ``source`` may be a ready array (validated), a PIL image, or a
        path (decoded through the cache when one is configured —
        ``cache_key`` defaults to the path)."""
        h, w = self.input_hw
        if isinstance(source, np.ndarray):
            if source.shape != (h, w, self.channels):
                raise ValueError(
                    f"expected ({h}, {w}, {self.channels}) input row, got "
                    f"{source.shape}"
                )
            return np.asarray(source, np.float32)
        if isinstance(source, str):
            key = cache_key if cache_key is not None else (source, (w, h))
            if self.cache is not None:
                hit = self.cache.get(key)
                if hit is not None:
                    return hit["image"]
            from distributedpytorch_tpu.serve.infer import load_image

            row = load_image(source, (w, h))
            if self.cache is not None:
                self.cache.put(key, {"image": row})
            return row
        # PIL image (duck-typed: anything with .convert/.resize)
        return preprocess_image(source, (w, h))

    def postprocess(self, probs: np.ndarray) -> np.ndarray:
        return postprocess_mask(probs, self.threshold)


def engine_from_checkpoint(
    checkpoint: str,
    checkpoint_dir: str = "./checkpoints",
    image_size: Sequence[int] = (960, 640),
    model_arch: str = "unet",
    model_widths: Optional[Sequence[int]] = None,
    s2d_levels: int = -1,
    quantize: Optional[str] = None,
    **engine_kwargs,
) -> ServeEngine:
    """Checkpoint name/path → a ready (AOT-compiled) engine.
    ``quantize="int8"`` serves weights-only int8 (see
    serve/infer.load_inference_bundle for the file-vs-on-load rules)."""
    from distributedpytorch_tpu.obs.reqtrace import engine_fingerprint
    from distributedpytorch_tpu.serve.infer import load_inference_bundle

    # checkpoint-built engines always carry their model fingerprint —
    # the AOT store key material (and what bench_serve profiles stamp);
    # a caller-supplied one (tests faking skew) wins
    kernels = engine_kwargs.get("kernels", "xla")
    engine_kwargs.setdefault("engine_fingerprint", engine_fingerprint(
        model_arch=model_arch,
        image_size=image_size,
        model_widths=model_widths,
        s2d_levels=s2d_levels,
        quantize=quantize,
        kernels=getattr(kernels, "name", None) or str(kernels),
    ))
    bundle = load_inference_bundle(
        checkpoint, checkpoint_dir=checkpoint_dir, image_size=image_size,
        model_arch=model_arch, model_widths=model_widths,
        s2d_levels=s2d_levels, quantize=quantize,
    )
    engine = ServeEngine.from_bundle(bundle, **engine_kwargs)

    def _load_for_swap(new_checkpoint: str):
        """Load a rollout candidate with THIS engine's model identity and
        quantization (a float engine must not be handed an int8 tree —
        the compiled executables' argument structure would mismatch)."""
        new = load_inference_bundle(
            new_checkpoint, checkpoint_dir=checkpoint_dir,
            image_size=image_size, model_arch=model_arch,
            model_widths=model_widths, s2d_levels=s2d_levels,
            quantize="int8" if engine.quantized else None,
        )
        if new.quantized != engine.quantized:
            raise ValueError(
                f"{new_checkpoint} is "
                f"{'int8' if new.quantized else 'float'} but the engine "
                f"serves {'int8' if engine.quantized else 'float'} "
                f"weights — a hot-swap cannot change the executable's "
                f"argument structure"
            )
        return new

    engine.bundle_loader = _load_for_swap
    return engine
