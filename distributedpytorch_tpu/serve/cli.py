"""``python -m distributedpytorch_tpu serve``: the production serving
entry point — HTTP over the in-process :class:`Server`.

Stdlib-only transport (``http.server.ThreadingHTTPServer``): each
connection gets a handler thread that decodes, submits, and blocks on
the request's future — the continuous-batching queue coalesces across
handler threads, which is exactly the concurrency shape the batching
layer exists for. Endpoints:

* ``POST /predict`` — body: one image (any PIL-decodable format) →
  ``image/png`` mask ({0, 255}); ``503`` + JSON when shed capacity is
  exhausted (body carries the rejection reason), ``400`` on an
  undecodable body.
* ``GET /healthz``  — liveness + the compiled bucket/replica inventory,
  ``uptime_s``, and the build/config fingerprint.
* ``GET /stats``    — the metrics snapshot (p50/p99, imgs/s, queue
  depth, per-bucket dispatch counts, pad ratio). Schema pinned by
  tests/test_serve.py — dashboards depend on it.
* ``GET /metrics``  — Prometheus text exposition of the process-wide
  telemetry registry (distributedpytorch_tpu/obs, docs/OBSERVABILITY.md).

Example:
    python -m distributedpytorch_tpu serve -c singleGPU --port 8008 \\
        --buckets 1 2 4 8 --slo-ms 50 --replicas 4
    curl -s --data-binary @car.jpg localhost:8008/predict > mask.png
"""

from __future__ import annotations

import argparse
import concurrent.futures
import io
import json
import logging
import threading
from typing import Optional

logger = logging.getLogger(__name__)


def get_args(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m distributedpytorch_tpu serve",
        description="Serve mask predictions over HTTP with AOT-compiled "
                    "continuous batching",
    )
    parser.add_argument("--checkpoint", "-c", required=True,
                        help="Checkpoint name (e.g. singleGPU) or path "
                             "(.ckpt/.pth)")
    parser.add_argument("--checkpoint-dir", default="./checkpoints")
    parser.add_argument("--image-size", type=int, nargs=2, default=(960, 640),
                        metavar=("W", "H"))
    parser.add_argument("--model", dest="model_arch", type=str,
                        default="unet", choices=["unet", "milesial"],
                        help="Model family the checkpoint was trained with")
    parser.add_argument("--model-widths", type=int, nargs="+", default=None)
    parser.add_argument("--s2d-levels", type=int, default=-1)
    parser.add_argument("--quantize", type=str, default=None,
                        choices=["int8"],
                        help="Serve weights-only int8 (per-out-channel "
                             "symmetric, ops/quant.py): device-resident "
                             "weight bytes quartered vs f32, dequantized "
                             "inside the AOT-compiled forward. Accepts a "
                             "tools/quantize.py file or quantizes a "
                             "regular checkpoint on load")
    parser.add_argument("--threshold", "-t", type=float, default=0.5)
    parser.add_argument("--kernels", type=str, default="xla",
                        choices=["xla", "pallas"],
                        help="Kernel-engagement policy (ops/kernels.py): "
                             "pallas traces the fused sigmoid/threshold "
                             "mask kernel into every AOT bucket "
                             "executable — uint8 masks come back from "
                             "the device (1 byte/pixel D2H, no host "
                             "threshold pass), bit-identical at the "
                             "operating threshold; honors the Mosaic "
                             "probe priors ($DPT_KERNEL_PRIORS)")
    parser.add_argument("--kernel-priors", type=str, default=None,
                        help="Per-chip Mosaic probe priors file "
                             "(tools/probe_kernels.py): kernels the "
                             "chip's compiler rejected disengage loudly")
    parser.add_argument("--buckets", type=int, nargs="+", default=(1, 2, 4, 8),
                        help="Padded batch bucket ladder — one AOT compile "
                             "per bucket per replica at startup")
    parser.add_argument("--slo-ms", type=float, default=50.0,
                        help="Batching latency SLO: a request waits at most "
                             "this long for its bucket to fill")
    parser.add_argument("--replicas", type=int, default=1,
                        help="Data-parallel replica groups (clamps to the "
                             "devices present)")
    parser.add_argument("--queue-cap", type=int, default=None,
                        help="Pending-image hard cap (default 4x the "
                             "largest bucket); beyond it requests are shed "
                             "with HTTP 503")
    parser.add_argument("--placement-depth", type=int, default=2,
                        help="Buckets stacked+placed ahead of dispatch "
                             "(0 = synchronous placement)")
    parser.add_argument("--inflight-per-replica", type=int, default=2,
                        help="Dispatched-but-undrained buckets per replica "
                             "(bounds work-in-system under overload)")
    parser.add_argument("--completion-workers", type=int, default=None)
    parser.add_argument("--host-cache-mb", type=int, default=256,
                        help="SampleCache budget for path-keyed request "
                             "decode (0 = off)")
    parser.add_argument("--no-eager", action="store_true",
                        help="Disable work-conserving dispatch: wait for "
                             "full buckets or the SLO even when replicas "
                             "are idle (throughput-biased)")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8008)
    return parser.parse_args(argv)


def to_config(args):
    """argparse namespace → :class:`ServeConfig` (single source of knob
    names between the CLI and the bench's programmatic construction)."""
    from distributedpytorch_tpu.config import ServeConfig

    return ServeConfig(
        checkpoint=args.checkpoint,
        checkpoint_dir=args.checkpoint_dir,
        image_size=tuple(args.image_size),
        model_arch=args.model_arch,
        model_widths=tuple(args.model_widths) if args.model_widths else None,
        s2d_levels=args.s2d_levels,
        quantize=args.quantize,
        threshold=args.threshold,
        kernels=args.kernels,
        kernel_priors=args.kernel_priors,
        bucket_sizes=tuple(args.buckets),
        slo_ms=args.slo_ms,
        eager_when_idle=not args.no_eager,
        queue_cap_images=args.queue_cap,
        replicas=args.replicas,
        placement_depth=args.placement_depth,
        inflight_per_replica=args.inflight_per_replica,
        completion_workers=args.completion_workers,
        host_cache_mb=args.host_cache_mb,
        host=args.host,
        port=args.port,
    )


def build_server(args):
    """args → started-able :class:`Server` (engine AOT-compiles here)."""
    from distributedpytorch_tpu.serve.server import Server

    return Server.from_config(to_config(args))


def make_http_server(server, host: str = "127.0.0.1", port: int = 0,
                     request_timeout_s: float = 30.0):
    """Wrap a started :class:`Server` in a ThreadingHTTPServer (port 0 =
    ephemeral; read the bound port off ``.server_address``)."""
    import time

    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from PIL import Image

    from distributedpytorch_tpu.obs.http import (
        build_fingerprint,
        healthz_payload,
        metrics_response,
    )
    from distributedpytorch_tpu.serve.server import (
        STATUS_REJECTED,
        STATUS_SHUTDOWN,
    )

    # make_http_server builds an HTTP handler class, not a jitted fn —
    # the make_* trace heuristic doesn't apply to this host-only module
    started_t = time.monotonic()  # dptlint: disable=trace-nondeterminism
    fingerprint = build_fingerprint(getattr(server, "config", None))

    class Handler(BaseHTTPRequestHandler):
        def _json(self, code: int, obj: dict) -> None:
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 — http.server's contract
            if self.path == "/healthz":
                # shared body builder (obs/http.py: status + uptime +
                # fingerprint) + this front's compiled inventory
                self._json(200, healthz_payload(
                    started_t, fingerprint,
                    buckets=list(server.engine.planner.sizes),
                    replicas=server.engine.num_replicas,
                ))
            elif self.path == "/stats":
                self._json(200, server.stats())
            elif self.path == "/metrics":
                body, ctype = metrics_response()
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self._json(404, {"error": f"no route {self.path}"})

        def do_POST(self):  # noqa: N802
            if self.path != "/predict":
                self._json(404, {"error": f"no route {self.path}"})
                return
            length = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(length)
            try:
                img = Image.open(io.BytesIO(body))
                img.load()
            except Exception:  # noqa: BLE001 — undecodable body → 400
                self._json(400, {"error": "body is not a decodable image"})
                return
            try:
                response = server.submit(img).result(
                    timeout=request_timeout_s
                )
            except concurrent.futures.TimeoutError:
                # a wedged request must get an HTTP answer, not a
                # handler traceback + dropped connection
                self._json(504, {
                    "status": "error",
                    "reason": f"no result within {request_timeout_s:.0f} s",
                })
                return
            if not response.ok:
                # rejection/shutdown = "service unavailable, retry"
                # (the reason says whether HERE or elsewhere); anything
                # else is this server's fault
                code = (503 if response.status
                        in (STATUS_REJECTED, STATUS_SHUTDOWN) else 500)
                self._json(code, {
                    "status": response.status, "reason": response.reason,
                })
                return
            buf = io.BytesIO()
            Image.fromarray(response.masks[0]).save(buf, format="PNG")
            data = buf.getvalue()
            self.send_response(200)
            self.send_header("Content-Type", "image/png")
            self.send_header("Content-Length", str(len(data)))
            self.send_header(
                "X-Serve-Latency-Ms", f"{response.latency_ms:.2f}"
            )
            self.end_headers()
            self.wfile.write(data)

        def log_message(self, fmt, *fmt_args):  # route through logging
            logger.debug("http: " + fmt, *fmt_args)

    return ThreadingHTTPServer((host, port), Handler)


def main(argv=None) -> int:
    args = get_args(argv)
    logging.basicConfig(level=logging.INFO, format="%(message)s")
    server = build_server(args).start()
    httpd = make_http_server(server, host=args.host, port=args.port)
    host, port = httpd.server_address[:2]
    logger.info(
        "serving on http://%s:%d (buckets %s, slo %.0f ms, %d replica(s)) — "
        "POST /predict, GET /healthz, GET /stats",
        host, port, list(server.engine.planner.sizes), args.slo_ms,
        server.engine.num_replicas,
    )
    threading.Thread(  # Ctrl-C must interrupt serve_forever, not a join
        target=httpd.serve_forever, daemon=True,
    ).start()
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        logger.info("shutting down (draining queue)")
    finally:
        httpd.shutdown()
        server.stop(drain=True)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
