"""``python -m distributedpytorch_tpu serve``: the production serving
entry point — HTTP over the in-process :class:`Server`.

Stdlib-only transport (``http.server.ThreadingHTTPServer``): each
connection gets a handler thread that decodes, submits, and blocks on
the request's future — the continuous-batching queue coalesces across
handler threads, which is exactly the concurrency shape the batching
layer exists for. Endpoints:

* ``POST /predict`` — body: one image (any PIL-decodable format) →
  ``image/png`` mask ({0, 255}); ``503`` + JSON (with a ``Retry-After``
  header) when shed or mid-relaunch (body carries the rejection
  reason), ``400`` on an undecodable body. Request-scoped tracing
  (obs/reqtrace.py): a W3C ``traceparent`` header's trace-id is
  adopted, else an id is assigned at ingress; EVERY answer echoes it
  as ``X-Request-Id``, and its span ledger is attributable via
  ``/stats`` exemplars, the slow-request log, and the flight ring.
* ``GET /healthz``  — **readiness**: 200 + the compiled bucket/replica
  inventory, ``uptime_s``, ``weights_version``, and the build/config
  fingerprint while serving; **503 + ``ready: false``** while the
  dispatch core is relaunching or a rollout canary is in flight.
* ``GET /livez``    — pure liveness: 200 as long as the process answers.
* ``GET /stats``    — the metrics snapshot (p50/p99, imgs/s, queue
  depth, per-bucket dispatch counts, pad ratio, ``weights_version``,
  ``state``, prediction-cache counters). Schema pinned by
  tests/test_serve.py — dashboards depend on it.
* ``GET /metrics``  — Prometheus text exposition of the process-wide
  telemetry registry (distributedpytorch_tpu/obs, docs/OBSERVABILITY.md).
* ``POST /admin/rollout`` — ``{"checkpoint": <path>}``: hot-swap a new
  checkpoint into the running engine through the canary state machine
  (serve/rollout.py) — 202 accepted, 409 if one is already in flight.
  ``GET`` returns the rollout status.
* ``POST /admin/ab`` — sustained weight A/B (serve/rollout.py:ABTest):
  ``{"action": "start", "checkpoint": ..., "split": 0.5}`` pins the
  candidate to half the replica groups; ``{"action": "verdict"}``
  returns per-arm latency/shed + inter-arm Dice; ``{"action": "stop",
  "winner": "a"|"b"}`` promotes the winner fleet-wide. ``GET`` returns
  the A/B status. Behind a router (serve/router.py) the same route
  fans out to every worker.

Example:
    python -m distributedpytorch_tpu serve -c singleGPU --port 8008 \\
        --buckets 1 2 4 8 --slo-ms 50 --replicas 4
    curl -s --data-binary @car.jpg localhost:8008/predict > mask.png

Supervised fleet launch (dist/elastic.py — a dead worker is a
relaunch, not an outage; worker R binds ``--port base+R``):
    python -m distributedpytorch_tpu elastic --workload serve -n 4 -- \\
        -c singleGPU --port 8008 --replicas 1
"""

from __future__ import annotations

import argparse
import concurrent.futures
import io
import json
import logging
import threading
from typing import Optional

logger = logging.getLogger(__name__)


def get_args(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m distributedpytorch_tpu serve",
        description="Serve mask predictions over HTTP with AOT-compiled "
                    "continuous batching",
    )
    parser.add_argument("--checkpoint", "-c", required=True,
                        help="Checkpoint name (e.g. singleGPU) or path "
                             "(.ckpt/.pth)")
    parser.add_argument("--checkpoint-dir", default="./checkpoints")
    parser.add_argument("--image-size", type=int, nargs=2, default=(960, 640),
                        metavar=("W", "H"))
    parser.add_argument("--model", dest="model_arch", type=str,
                        default="unet", choices=["unet", "milesial"],
                        help="Model family the checkpoint was trained with")
    parser.add_argument("--model-widths", type=int, nargs="+", default=None)
    parser.add_argument("--s2d-levels", type=int, default=-1)
    parser.add_argument("--quantize", type=str, default=None,
                        choices=["int8"],
                        help="Serve weights-only int8 (per-out-channel "
                             "symmetric, ops/quant.py): device-resident "
                             "weight bytes quartered vs f32, dequantized "
                             "inside the AOT-compiled forward. Accepts a "
                             "tools/quantize.py file or quantizes a "
                             "regular checkpoint on load")
    parser.add_argument("--threshold", "-t", type=float, default=0.5)
    parser.add_argument("--kernels", type=str, default="xla",
                        choices=["xla", "pallas"],
                        help="Kernel-engagement policy (ops/kernels.py): "
                             "pallas traces the fused sigmoid/threshold "
                             "mask kernel into every AOT bucket "
                             "executable — uint8 masks come back from "
                             "the device (1 byte/pixel D2H, no host "
                             "threshold pass), bit-identical at the "
                             "operating threshold; honors the Mosaic "
                             "probe priors ($DPT_KERNEL_PRIORS)")
    parser.add_argument("--kernel-priors", type=str, default=None,
                        help="Per-chip Mosaic probe priors file "
                             "(tools/probe_kernels.py): kernels the "
                             "chip's compiler rejected disengage loudly")
    parser.add_argument("--aot-cache", type=str, default=None,
                        help="Content-addressed AOT executable store "
                             "directory (utils/aotstore.py; default "
                             "$DPT_AOT_CACHE, unset = off): startup "
                             "loads serialized bucket executables "
                             "instead of compiling on hit, compiles-"
                             "and-persists on miss; corrupt/skewed "
                             "entries are refused loudly and "
                             "recompiled (docs/PERFORMANCE.md)")
    parser.add_argument("--buckets", type=int, nargs="+", default=(1, 2, 4, 8),
                        help="Padded batch bucket ladder — one AOT compile "
                             "per bucket per replica at startup")
    parser.add_argument("--slo-ms", type=float, default=50.0,
                        help="Batching latency SLO: a request waits at most "
                             "this long for its bucket to fill")
    parser.add_argument("--replicas", type=int, default=1,
                        help="Data-parallel replica groups (clamps to the "
                             "devices present)")
    parser.add_argument("--queue-cap", type=int, default=None,
                        help="Pending-image hard cap (default 4x the "
                             "largest bucket); beyond it requests are shed "
                             "with HTTP 503")
    parser.add_argument("--placement-depth", type=int, default=2,
                        help="Buckets stacked+placed ahead of dispatch "
                             "(0 = synchronous placement)")
    parser.add_argument("--inflight-per-replica", type=int, default=2,
                        help="Dispatched-but-undrained buckets per replica "
                             "(bounds work-in-system under overload)")
    parser.add_argument("--completion-workers", type=int, default=None)
    parser.add_argument("--host-cache-mb", type=int, default=256,
                        help="SampleCache budget for path-keyed request "
                             "decode (0 = off)")
    parser.add_argument("--no-eager", action="store_true",
                        help="Disable work-conserving dispatch: wait for "
                             "full buckets or the SLO even when replicas "
                             "are idle (throughput-biased)")
    parser.add_argument("--predict-cache-mb", type=int, default=0,
                        help="Clipper-style prediction cache budget "
                             "(MiB): exact-match masks keyed on the "
                             "decoded-input hash + weights version; "
                             "0 = off")
    parser.add_argument("--restart-limit", type=int, default=3,
                        help="In-process dispatch-core relaunches before "
                             "the worker goes terminal (a process "
                             "supervisor owns the next level)")
    parser.add_argument("--restart-backoff", type=float, default=0.25,
                        help="Base core-relaunch backoff seconds "
                             "(doubles per consecutive restart)")
    parser.add_argument("--canary-replicas", type=int, default=1,
                        help="Replica groups a rollout canaries on "
                             "before promoting to the rest")
    parser.add_argument("--rollout-window", type=float, default=5.0,
                        help="Canary health-watch window (seconds)")
    parser.add_argument("--rollout-probe", type=str, nargs="+",
                        default=None, metavar="IMAGE",
                        help="Pinned probe images: a rollout candidate's "
                             "masks must score within --rollout-dice-"
                             "margin of the old weights' masks on these")
    parser.add_argument("--rollout-dice-margin", type=float, default=0.02)
    parser.add_argument("--watch-checkpoint", type=str, nargs="?",
                        const="", default=None, metavar="PATH",
                        help="Poll a checkpoint file and roll it out "
                             "(canaried) whenever it is replaced; "
                             "without PATH, watches the serving "
                             "checkpoint's own file")
    parser.add_argument("--watch-poll", type=float, default=2.0,
                        help="Checkpoint-watch poll cadence (seconds)")
    parser.add_argument("--autoscale-interval", type=float, default=30.0,
                        help="Cadence of the replica-count "
                             "recommendation (gauge + log line). 0 = off")
    parser.add_argument("--autoscale-act", action="store_true",
                        help="ACT on the replica hint: grow/shrink the "
                             "live replica group without a restart "
                             "(serve/scaler.py; needs --autoscale-"
                             "interval > 0)")
    parser.add_argument("--serve-plan", type=str, default=None,
                        metavar="PLAN_JSON",
                        help="plan-serve artifact (dpt_serve_plan): "
                             "each scale decision cites the grid point "
                             "it executes")
    parser.add_argument("--min-replicas", type=int, default=1,
                        help="Autoscaler floor")
    parser.add_argument("--max-replicas", type=int, default=None,
                        help="Autoscaler ceiling (default: the devices "
                             "present)")
    parser.add_argument("--ab-split", type=float, default=0.5,
                        help="Default arm-b traffic fraction for "
                             "POST /admin/ab starts")
    parser.add_argument("--latency-slo-ms", type=float, default=None,
                        help="End-to-end good-request latency bound for "
                             "the SLO burn-rate gauges (default 2x "
                             "--slo-ms)")
    parser.add_argument("--slow-request-ms", type=float, default=0.0,
                        help="Structured-log threshold: served requests "
                             "slower than this log one JSON line with "
                             "their id + span ledger (<= 0 = 2x the "
                             "latency SLO)")
    parser.add_argument("--trace-timeline", type=str, default=None,
                        metavar="PATH",
                        help="Append per-request span JSONL here (rank R "
                             "writes PATH.rankR under a supervisor); "
                             "merge to Perfetto via obs/trace_hub.py")
    parser.add_argument("--record-arrivals", type=str, default=None,
                        metavar="PATH",
                        help="Record a bounded JSONL arrival trace here "
                             "(ingress wall-time, decoded rows/shape, "
                             "covering bucket per request; rank R of a "
                             "supervised fleet writes PATH.rankR) — the "
                             "recorded-trace input `plan-serve` replays "
                             "for capacity planning (docs/SERVING.md)")
    parser.add_argument("--record-arrivals-limit", type=int,
                        default=200_000,
                        help="Arrival-trace line cap: past it recording "
                             "stops (the trace keeps the head of the "
                             "traffic; the file stays bounded)")
    parser.add_argument("--heartbeat-dir", type=str, default=None,
                        help="Write per-rank beat files here for the "
                             "elastic supervisor (normally armed by "
                             "elastic --workload serve)")
    parser.add_argument("--heartbeat-interval", type=float, default=0.5)
    parser.add_argument("--inject-fault", action="append", default=[],
                        metavar="SITE[:EPOCH:STEP[:COUNT]]",
                        help="Arm a deterministic chaos fault "
                             "(utils/faults.py serve sites: "
                             "serve_dispatch_death, serve_replica_wedge, "
                             "serve_decode, swap_crash)")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8008)
    return parser.parse_args(argv)


def to_config(args):
    """argparse namespace → :class:`ServeConfig` (single source of knob
    names between the CLI and the bench's programmatic construction)."""
    from distributedpytorch_tpu.config import ServeConfig

    return ServeConfig(
        checkpoint=args.checkpoint,
        checkpoint_dir=args.checkpoint_dir,
        image_size=tuple(args.image_size),
        model_arch=args.model_arch,
        model_widths=tuple(args.model_widths) if args.model_widths else None,
        s2d_levels=args.s2d_levels,
        quantize=args.quantize,
        threshold=args.threshold,
        kernels=args.kernels,
        kernel_priors=args.kernel_priors,
        aot_cache=args.aot_cache,
        bucket_sizes=tuple(args.buckets),
        slo_ms=args.slo_ms,
        eager_when_idle=not args.no_eager,
        queue_cap_images=args.queue_cap,
        replicas=args.replicas,
        placement_depth=args.placement_depth,
        inflight_per_replica=args.inflight_per_replica,
        completion_workers=args.completion_workers,
        host_cache_mb=args.host_cache_mb,
        predict_cache_mb=args.predict_cache_mb,
        restart_limit=args.restart_limit,
        restart_backoff_s=args.restart_backoff,
        canary_replicas=args.canary_replicas,
        rollout_window_s=args.rollout_window,
        rollout_probe=tuple(args.rollout_probe or ()),
        rollout_dice_margin=args.rollout_dice_margin,
        watch_checkpoint=args.watch_checkpoint,
        watch_poll_s=args.watch_poll,
        autoscale_interval_s=args.autoscale_interval,
        autoscale_act=args.autoscale_act,
        serve_plan=args.serve_plan,
        min_replicas=args.min_replicas,
        max_replicas=args.max_replicas,
        ab_split=args.ab_split,
        latency_slo_ms=args.latency_slo_ms,
        slow_request_ms=args.slow_request_ms,
        trace_timeline=args.trace_timeline,
        record_arrivals=args.record_arrivals,
        record_arrivals_limit=args.record_arrivals_limit,
        heartbeat_dir=args.heartbeat_dir,
        heartbeat_interval_s=args.heartbeat_interval,
        inject_faults=tuple(args.inject_fault),
        host=args.host,
        port=args.port,
    )


def build_server(args):
    """args → started-able :class:`Server` (engine AOT-compiles here),
    with the fleet components attached: rollout manager (+ optional
    checkpoint watcher), autoscale hint, armed chaos faults, and — when
    ``--trace-timeline`` is set — the per-request span JSONL (rank R of
    a supervised fleet appends ``.rankR``, the trace-hub convention)."""
    import os

    from distributedpytorch_tpu.serve.server import Server

    cfg = to_config(args)
    if cfg.inject_faults:
        from distributedpytorch_tpu.utils import faults

        faults.install(cfg.inject_faults)
    timeline = None
    if cfg.trace_timeline:
        from distributedpytorch_tpu.utils.trace import StepTimeline

        rank = int(os.environ.get("RANK", "0"))
        path = (cfg.trace_timeline if rank == 0
                else f"{cfg.trace_timeline}.rank{rank}")
        timeline = StepTimeline(path, rank=rank)
    server = Server.from_config(cfg, timeline=timeline)
    if cfg.record_arrivals:
        from distributedpytorch_tpu.serve.sim import ArrivalRecorder

        # rank-suffixed like --trace-timeline: N supervised workers
        # must not truncate/interleave one shared trace file
        rank = int(os.environ.get("RANK", "0"))
        path = (cfg.record_arrivals if rank == 0
                else f"{cfg.record_arrivals}.rank{rank}")
        server.arrival_recorder = ArrivalRecorder(
            path, limit=cfg.record_arrivals_limit,
        )
    attach_fleet(server, cfg)
    return server


def attach_fleet(server, cfg) -> None:
    """Wire the rollout manager, checkpoint watcher, sustained-A/B
    controller, autoscale hint, and — when opted into — the replica
    scaler onto a built server (split out so tests and the bench can
    attach to servers they construct directly). Components start with
    the server and stop with ``server.stop()``."""
    from distributedpytorch_tpu.serve.rollout import (
        ABTest,
        CheckpointWatcher,
        RolloutManager,
    )

    probe_rows = [
        server.engine.preprocess(path) for path in (cfg.rollout_probe or ())
    ]
    server.rollout = RolloutManager(
        server,
        probe_rows=probe_rows or None,
        window_s=cfg.rollout_window_s,
        dice_margin=cfg.rollout_dice_margin,
        canary_replicas=cfg.canary_replicas,
    )
    # always attached (inert until POST /admin/ab start): sharing the
    # rollout probe rows gives the verdict its inter-arm Dice half
    server.abtest = ABTest(
        server, probe_rows=probe_rows or None,
        split=getattr(cfg, "ab_split", 0.5),
    )
    watch = cfg.watch_checkpoint
    if watch is not None:
        if watch == "":  # --watch-checkpoint without a path: watch the
            # serving checkpoint's own resolved file
            from distributedpytorch_tpu.checkpoint import resolve_checkpoint

            watch = resolve_checkpoint(cfg.checkpoint, cfg.checkpoint_dir)
        server.watcher = CheckpointWatcher(
            server.rollout, watch, poll_s=cfg.watch_poll_s
        ).start()
    if cfg.autoscale_interval_s and cfg.autoscale_interval_s > 0:
        from distributedpytorch_tpu.serve.autoscale import AutoscaleHint

        server.autoscale = AutoscaleHint(
            server, interval_s=cfg.autoscale_interval_s
        ).start()
        if getattr(cfg, "autoscale_act", False):
            from distributedpytorch_tpu.serve.scaler import ReplicaScaler

            server.scaler = ReplicaScaler(
                server, server.autoscale,
                plan=getattr(cfg, "serve_plan", None),
                min_replicas=getattr(cfg, "min_replicas", 1),
                max_replicas=getattr(cfg, "max_replicas", None),
                cooldown_windows=getattr(cfg, "scale_cooldown_windows",
                                         None),
            ).start()


def make_http_server(server, host: str = "127.0.0.1", port: int = 0,
                     request_timeout_s: float = 30.0):
    """Wrap a started :class:`Server` in a ThreadingHTTPServer (port 0 =
    ephemeral; read the bound port off ``.server_address``)."""
    import time

    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from PIL import Image

    from distributedpytorch_tpu.obs.http import (
        build_fingerprint,
        healthz_payload,
        metrics_response,
    )
    from distributedpytorch_tpu.obs.reqtrace import (
        new_request_id,
        request_id_from_headers,
    )
    from distributedpytorch_tpu.serve.server import (
        STATUS_REJECTED,
        STATUS_SHUTDOWN,
    )

    started_t = time.monotonic()
    fingerprint = build_fingerprint(getattr(server, "config", None))

    class Handler(BaseHTTPRequestHandler):
        def _json(self, code: int, obj: dict,
                  retry_after: Optional[int] = None,
                  request_id: Optional[str] = None) -> None:
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            if retry_after is not None:
                # every 503 carries the back-off hint: "relaunching" and
                # "overloaded" mean retry HERE after this many seconds
                self.send_header("Retry-After", str(int(retry_after)))
            if request_id:
                self.send_header("X-Request-Id", request_id)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 — http.server's contract
            if self.path == "/healthz":
                # READINESS (the LB signal): 503 + ready:false while the
                # dispatch core is between incarnations or a rollout
                # canary is in flight — /livez stays 200 (don't restart
                # a process that is busy healing itself)
                ready = server.ready
                self._json(
                    200 if ready else 503,
                    healthz_payload(
                        started_t, fingerprint, ready=ready,
                        state=server.state,
                        weights_version=server.engine.weights_version,
                        buckets=list(server.engine.planner.sizes),
                        replicas=server.engine.num_replicas,
                    ),
                    retry_after=None if ready else 1,
                )
            elif self.path == "/livez":
                self._json(200, {"status": "alive"})
            elif self.path == "/stats":
                self._json(200, server.stats())
            elif self.path == "/admin/rollout":
                manager = server.rollout
                if manager is None:
                    self._json(404, {"error": "no rollout manager "
                                              "attached to this server"})
                else:
                    self._json(200, manager.status())
            elif self.path == "/admin/ab":
                abtest = server.abtest
                if abtest is None:
                    self._json(404, {"error": "no A/B controller "
                                              "attached to this server"})
                else:
                    self._json(200, abtest.status())
            elif self.path == "/metrics":
                # burn gauges decay with their windows: re-derive at
                # scrape time so a quiet worker's burn reads 0, not the
                # last error burst's value frozen forever
                server.tracer.refresh_burn_gauges()
                body, ctype = metrics_response()
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self._json(404, {"error": f"no route {self.path}"})

        def _admin_rollout(self, body: bytes) -> None:
            from distributedpytorch_tpu.serve.rollout import (
                RolloutInProgress,
            )

            manager = server.rollout
            if manager is None:
                self._json(404, {"error": "no rollout manager attached "
                                          "to this server"})
                return
            try:
                spec = json.loads(body or b"{}")
                checkpoint = spec["checkpoint"]
            except (ValueError, KeyError, TypeError):
                self._json(400, {
                    "error": 'body must be JSON: {"checkpoint": <path>}',
                })
                return
            try:
                manager.start(checkpoint, label=str(checkpoint))
            except RolloutInProgress as exc:
                self._json(409, {"error": str(exc),
                                 "status": manager.status()})
                return
            self._json(202, {"accepted": True, "status": manager.status()})

        def _admin_ab(self, body: bytes) -> None:
            """Sustained A/B lifecycle (serve/rollout.py:ABTest) —
            ``{"action": "start", "checkpoint": ..., "split": 0.5}`` /
            ``{"action": "verdict"}`` / ``{"action": "stop",
            "winner": "a"|"b"}``."""
            from distributedpytorch_tpu.serve.rollout import (
                RolloutInProgress,
            )

            abtest = server.abtest
            if abtest is None:
                self._json(404, {"error": "no A/B controller attached "
                                          "to this server"})
                return
            try:
                spec = json.loads(body or b"{}")
                action = spec["action"]
            except (ValueError, KeyError, TypeError):
                self._json(400, {
                    "error": 'body must be JSON with an "action" of '
                             'start|verdict|stop',
                })
                return
            try:
                if action == "start":
                    if "split" in spec:
                        abtest.split = min(max(float(spec["split"]), 0.0),
                                           1.0)
                    status = abtest.start(
                        spec["checkpoint"],
                        label=str(spec.get("label", spec["checkpoint"])),
                    )
                    self._json(202, {"accepted": True, "status": status})
                elif action == "verdict":
                    self._json(200, abtest.verdict())
                elif action == "stop":
                    self._json(200, abtest.stop(spec.get("winner")))
                else:
                    self._json(400, {"error": f"unknown action "
                                              f"{action!r}"})
            except RolloutInProgress as exc:
                self._json(409, {"error": str(exc),
                                 "status": abtest.status()})
            except KeyError as exc:
                self._json(400, {"error": f"missing field {exc}"})
            except (ValueError, RuntimeError) as exc:
                self._json(409, {"error": str(exc)[:300]})

        def do_POST(self):  # noqa: N802
            length = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(length)
            if self.path == "/admin/rollout":
                self._admin_rollout(body)
                return
            if self.path == "/admin/ab":
                self._admin_ab(body)
                return
            if self.path != "/predict":
                self._json(404, {"error": f"no route {self.path}"})
                return
            # request-scoped tracing (obs/reqtrace.py): a W3C
            # traceparent's trace-id (or an explicit X-Request-Id) is
            # adopted for cross-service correlation, else one is
            # assigned HERE — every answer, 4xx/5xx included, echoes it
            rid = (request_id_from_headers(self.headers)
                   or new_request_id())
            try:
                img = Image.open(io.BytesIO(body))
                img.load()
            except Exception:  # noqa: BLE001 — undecodable body → 400
                self._json(400, {"error": "body is not a decodable image",
                                 "request_id": rid}, request_id=rid)
                return
            # router-stamped A/B arm (X-AB-Arm): with no header the
            # server derives the SAME arm from the request id, so the
            # stamp is an optimization + an invariant, not a requirement
            arm = self.headers.get("X-AB-Arm", "")
            try:
                response = server.submit(
                    img, request_id=rid, arm=arm
                ).result(timeout=request_timeout_s)
            except concurrent.futures.TimeoutError:
                # a wedged request must get an HTTP answer, not a
                # handler traceback + dropped connection
                self._json(504, {
                    "status": "error",
                    "reason": f"no result within {request_timeout_s:.0f} s",
                    "request_id": rid,
                }, request_id=rid)
                return
            rid = response.request_id or rid
            if not response.ok:
                # rejection/shutdown = "service unavailable, retry"
                # (the reason says whether HERE or elsewhere); anything
                # else is this server's fault
                code = (503 if response.status
                        in (STATUS_REJECTED, STATUS_SHUTDOWN) else 500)
                self._json(code, {
                    "status": response.status, "reason": response.reason,
                    "request_id": rid,
                }, retry_after=(
                    server.retry_after_s(response.reason)
                    if code == 503 else None
                ), request_id=rid)
                return
            buf = io.BytesIO()
            Image.fromarray(response.masks[0]).save(buf, format="PNG")
            data = buf.getvalue()
            self.send_response(200)
            self.send_header("Content-Type", "image/png")
            self.send_header("Content-Length", str(len(data)))
            self.send_header(
                "X-Serve-Latency-Ms", f"{response.latency_ms:.2f}"
            )
            self.send_header("X-Request-Id", rid)
            self.end_headers()
            self.wfile.write(data)

        def log_message(self, fmt, *fmt_args):  # route through logging
            logger.debug("http: " + fmt, *fmt_args)

    return ThreadingHTTPServer((host, port), Handler)


def main(argv=None) -> int:
    import os

    args = get_args(argv)
    logging.basicConfig(level=logging.INFO, format="%(message)s")
    heartbeat = None
    if args.heartbeat_dir:
        # beat FIRST — the engine's AOT compiles take long enough that a
        # supervisor would otherwise read "no beat within the spawn
        # window" for a perfectly healthy worker
        from distributedpytorch_tpu.dist.health import Heartbeat

        heartbeat = Heartbeat(
            args.heartbeat_dir,
            rank=int(os.environ.get("RANK", "0")),
            interval_s=args.heartbeat_interval,
        ).start()
    server = build_server(args)
    server.heartbeat = heartbeat
    if heartbeat is not None:
        # steady state begins AFTER the engine's AOT compiles (the line
        # above): refresh progress first, THEN arm the progress-timeout
        # verdict — flipping `timed` before/during a long cold compile
        # would read as "hung" and kill-loop a healthy starting worker
        heartbeat.update(0, 0)
        heartbeat.timed = True
    server.start()
    httpd = make_http_server(server, host=args.host, port=args.port)
    host, port = httpd.server_address[:2]
    logger.info(
        "serving on http://%s:%d (buckets %s, slo %.0f ms, %d replica(s)) — "
        "POST /predict, GET /healthz, GET /stats",
        host, port, list(server.engine.planner.sizes), args.slo_ms,
        server.engine.num_replicas,
    )
    threading.Thread(  # Ctrl-C must interrupt serve_forever, not a join
        target=httpd.serve_forever, daemon=True,
    ).start()
    # SIGTERM is the fleet scaler's retire signal (dist/elastic.py
    # retire_fleet_worker: routers drain first, then SIGTERM): exit the
    # wait loop and drain the queue in the finally — a retire must
    # finish the work it already admitted, same as Ctrl-C
    import signal as _signal

    sigterm = threading.Event()
    try:
        _signal.signal(_signal.SIGTERM, lambda *_: sigterm.set())
    except ValueError:
        pass  # not the main thread (embedded in a test harness)
    rc = 0
    try:
        # wake periodically: a server whose in-process restart budget is
        # spent is TERMINAL — exit nonzero so the process supervisor
        # (elastic --workload serve) relaunches the whole worker
        from distributedpytorch_tpu.serve.server import STATE_STOPPED

        while server.state != STATE_STOPPED:
            if sigterm.wait(0.5):
                logger.info("SIGTERM: retiring (draining queue)")
                break
        else:
            logger.error("serve worker terminal (dispatch-core restart "
                         "budget spent) — exiting for relaunch")
            rc = 1
    except KeyboardInterrupt:
        logger.info("shutting down (draining queue)")
    finally:
        httpd.shutdown()
        server.stop(drain=True)
        if heartbeat is not None:
            heartbeat.stop()
    return rc


if __name__ == "__main__":
    import sys

    sys.exit(main())
