"""The serving tier: AOT-compiled, continuous-batching inference.

``python -m distributedpytorch_tpu serve`` — the first inference-side
subsystem in the codebase, and the second workload the elastic
supervisor can keep alive. Architecture (docs/SERVING.md):

* ``infer.py``      — shared preprocess/forward/postprocess, used
                      verbatim by the offline ``predict.py`` CLI (the
                      parity test pins the two surfaces bit-identical);
* ``bucketing.py``  — the padded-batch bucket ladder (one AOT compile
                      per bucket per replica, at startup);
* ``queue.py``      — the continuous-batching queue: full/deadline/
                      eager flushes under a latency SLO, overload
                      shedding to smaller full buckets, bounded
                      admission with explicit rejection;
* ``engine.py``     — per-replica AOT executables over the mesh's
                      devices + the SampleCache-backed decode path;
* ``server.py``     — the dispatch pipeline (pipelined_placement on
                      the request path; completion drain owns every
                      device→host sync — dptlint's ``serve-hot-path``
                      rule enforces the boundary) wrapped in the
                      in-process supervisor that relaunches a dead
                      dispatch core instead of dying with it;
* ``cache.py``      — the Clipper-style exact-match prediction cache
                      (decoded-input hash + weights version, bounded
                      LRU) in front of the queue;
* ``rollout.py``    — health-gated zero-downtime weight rollout:
                      canary → gauge/Dice watch → promote or roll
                      back, plus the ``--watch-checkpoint`` poller;
* ``autoscale.py``  — the replica-count *hint* (recommendation only)
                      from queue-depth/shed hysteresis;
* ``metrics.py``    — async per-request accounting (p50/p99, imgs/s);
* ``cli.py``        — the stdlib HTTP surface.

This module is import-light: pieces with a jax dependency import it
lazily, so queue/bucketing tests and the jax-free supervisor can load
the package without a backend.
"""

from distributedpytorch_tpu.serve.bucketing import BucketPlanner  # noqa: F401
from distributedpytorch_tpu.serve.metrics import ServeMetrics  # noqa: F401
from distributedpytorch_tpu.serve.queue import (  # noqa: F401
    REJECT_OVERLOAD,
    REJECT_SHUTDOWN,
    REJECT_TOO_LARGE,
    BatchingQueue,
    ServeRequest,
)
