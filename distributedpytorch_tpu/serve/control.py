"""The scaling control law, shared by both capacity actuators.

Two actuators move serve capacity, at different granularities:

* ``serve/scaler.py:ReplicaScaler`` resizes the live replica group
  *inside* one worker process (device-level: add/retire a replica,
  AOT-store-backed, no restart);
* ``dist/elastic.py:FleetScaler`` spawns/retires *whole serve
  workers* from the supervisor (process-level: the loop plan-serve
  actually sizes).

Both make the SAME kind of decision — "the observed load says run N
units; I run M" — and both must cite the ``dpt_serve_plan`` grid point
their decision executes. This module is that one control law, extracted
so the two actuators cannot drift: the decision record
(:class:`ScaleDecision`), the pure decide step (:func:`decide_scale` —
clamp, pin-hold, cooldown, direction), and the plan citation
(:func:`plan_point_for` — observed rate → nearest simulated poisson
scenario at or above it → grid point key at the base knobs).

Deliberately jax-free: the fleet actuator runs inside the supervisor
process, which never initializes a device runtime. Anything that needs
a backend (the replica scaler's default device cap) stays in the
caller.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

DIR_UP = "up"
DIR_DOWN = "down"
DIR_HOLD = "hold"


@dataclasses.dataclass
class ScaleDecision:
    """One control-loop verdict: what to do, and which plan point says
    it's the right thing to do."""

    direction: str              # up | down | hold
    current: int
    target: int
    reason: str
    plan_point: Optional[str] = None    # grid point key this executes
    plan_replicas: Optional[int] = None  # the plan's own recommendation
    rate_rps: Optional[float] = None    # observed rate matched to the plan

    def payload(self) -> dict:
        return dataclasses.asdict(self)


def plan_point_for(plan: Optional[dict], target: int,
                   rate_rps: Optional[float],
                   ) -> Tuple[Optional[str], Optional[int]]:
    """Cite the plan: the grid point key at the base knobs whose
    (scenario, replicas) matches what a decision executes, plus the
    scenario's own recommended replica count. The scenario is the
    nearest simulated poisson rate at or above the observed arrival
    rate (the conservative match: plan for at least the load you see);
    with no observed rate, the scenario whose recommendation equals the
    target."""
    if not plan:
        return None, None
    scenarios = [s for s in plan.get("scenarios", [])
                 if s.get("kind") == "poisson"
                 and s.get("rate_rps") is not None]
    recs = plan.get("recommendations", [])
    label = None
    if scenarios and rate_rps is not None:
        geq = [s for s in scenarios
               if float(s["rate_rps"]) >= float(rate_rps) - 1e-9]
        pick = (min(geq, key=lambda s: float(s["rate_rps"])) if geq
                else max(scenarios, key=lambda s: float(s["rate_rps"])))
        label = pick["label"]
    elif recs:
        for rec in recs:
            if rec.get("replicas") == target:
                label = rec["scenario"]
                break
        if label is None:
            label = recs[0]["scenario"]
    if label is None:
        return None, None
    plan_replicas = next(
        (rec.get("replicas") for rec in recs
         if rec.get("scenario") == label), None)
    grid = plan.get("grid", {})
    base_ladder = (grid.get("bucket_ladders") or [[]])[0]
    base_eager = (grid.get("eager") or [True])[0]
    base_cap = (grid.get("queue_caps") or [None])[0]
    for p in plan.get("points", []):
        if (p.get("scenario") == label
                and p.get("replicas") == target
                and p.get("bucket_sizes") == base_ladder
                and p.get("eager") == base_eager
                and p.get("queue_cap_images") == base_cap):
            return p.get("key"), plan_replicas
    return None, plan_replicas


def plan_recommendation(plan: Optional[dict],
                        rate_rps: Optional[float]) -> Optional[int]:
    """The plan's own replica recommendation for the observed rate
    (nearest poisson scenario at or above it) — what the fleet actuator
    uses as its recommendation signal, where the in-process scaler has
    the queue-depth/shed hysteresis hint instead."""
    if not plan or rate_rps is None:
        return None
    scenarios = [s for s in plan.get("scenarios", [])
                 if s.get("kind") == "poisson"
                 and s.get("rate_rps") is not None]
    if not scenarios:
        return None
    geq = [s for s in scenarios
           if float(s["rate_rps"]) >= float(rate_rps) - 1e-9]
    pick = (min(geq, key=lambda s: float(s["rate_rps"])) if geq
            else max(scenarios, key=lambda s: float(s["rate_rps"])))
    return next(
        (rec.get("replicas") for rec in plan.get("recommendations", [])
         if rec.get("scenario") == pick["label"]), None)


def decide_scale(
    current: int,
    recommendation: Optional[int],
    *,
    min_units: int,
    max_units: int,
    windows_since_action: int,
    cooldown_windows: int,
    hold_reason: Optional[str] = None,
    rate_rps: Optional[float] = None,
    plan: Optional[dict] = None,
) -> ScaleDecision:
    """The pure decide step both actuators share: no actuation, no
    counters. ``hold_reason`` is the caller's pin (a sustained A/B, a
    rollout in flight) — non-None holds unconditionally."""
    if recommendation is None:
        return ScaleDecision(DIR_HOLD, current, current,
                             "no hint observed yet")
    if hold_reason is not None:
        return ScaleDecision(DIR_HOLD, current, current, hold_reason)
    target = min(max(int(recommendation), int(min_units)), int(max_units))
    plan_point, plan_replicas = plan_point_for(plan, target, rate_rps)
    if target == current:
        return ScaleDecision(DIR_HOLD, current, current,
                             "hint matches live replica count",
                             plan_point, plan_replicas, rate_rps)
    if windows_since_action < cooldown_windows:
        return ScaleDecision(
            DIR_HOLD, current, current,
            f"cooldown ({windows_since_action}/"
            f"{cooldown_windows} windows since last action)",
            plan_point, plan_replicas, rate_rps)
    direction = DIR_UP if target > current else DIR_DOWN
    return ScaleDecision(
        direction, current, target,
        f"hint {recommendation} vs live {current}",
        plan_point, plan_replicas, rate_rps)
