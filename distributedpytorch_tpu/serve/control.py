"""The serve control plane's pure decision laws, shared by the live
actuators AND the static protocol explorer.

Two actuators move serve capacity, at different granularities:

* ``serve/scaler.py:ReplicaScaler`` resizes the live replica group
  *inside* one worker process (device-level: add/retire a replica,
  AOT-store-backed, no restart);
* ``dist/elastic.py:FleetScaler`` spawns/retires *whole serve
  workers* from the supervisor (process-level: the loop plan-serve
  actually sizes).

Both make the SAME kind of decision — "the observed load says run N
units; I run M" — and both must cite the ``dpt_serve_plan`` grid point
their decision executes. This module is that one control law, extracted
so the two actuators cannot drift: the decision record
(:class:`ScaleDecision`), the pure decide step (:func:`decide_scale` —
clamp, pin-hold, cooldown, direction), and the plan citation
(:func:`plan_point_for` — observed rate → nearest simulated poisson
scenario at or above it → grid point key at the base knobs).

Beyond scaling, this module now holds EVERY control-plane transition
rule the fleet's protocols rest on, extracted pure (the plan-serve
pattern that produced :func:`decide_scale`):

* :func:`decide_ha` — the router active/standby epoch arbitration
  (serve/router.py ``ha_once`` consumes it verbatim);
* :func:`rollout_transition` / :func:`ab_may_start` — the rollout
  canary state machine and the one-experiment-at-a-time guard
  (serve/rollout.py consumes them verbatim);
* :func:`scale_hold_reason` — why a scaler must hold while replica
  groups are pinned (serve/scaler.py consumes it verbatim);
* :func:`fleet_spawn_rank` / :func:`fleet_retire_rank` — the fleet
  grow/shrink rank selection (dist/elastic.py consumes them verbatim).

Because the live code calls these exact functions, the explicit-state
model checker in ``analysis/protocol.py`` explores the SAME transition
rules the fleet executes — a mutated comparison here (or in a consumer
that stops calling the seam) is a static finding, not a 3 a.m. outage.

Deliberately jax-free: the fleet actuator runs inside the supervisor
process, which never initializes a device runtime. Anything that needs
a backend (the replica scaler's default device cap) stays in the
caller.
"""

from __future__ import annotations

import dataclasses
from typing import FrozenSet, Optional, Sequence, Tuple

DIR_UP = "up"
DIR_DOWN = "down"
DIR_HOLD = "hold"


@dataclasses.dataclass
class ScaleDecision:
    """One control-loop verdict: what to do, and which plan point says
    it's the right thing to do."""

    direction: str              # up | down | hold
    current: int
    target: int
    reason: str
    plan_point: Optional[str] = None    # grid point key this executes
    plan_replicas: Optional[int] = None  # the plan's own recommendation
    rate_rps: Optional[float] = None    # observed rate matched to the plan

    def payload(self) -> dict:
        return dataclasses.asdict(self)


def plan_point_for(plan: Optional[dict], target: int,
                   rate_rps: Optional[float],
                   ) -> Tuple[Optional[str], Optional[int]]:
    """Cite the plan: the grid point key at the base knobs whose
    (scenario, replicas) matches what a decision executes, plus the
    scenario's own recommended replica count. The scenario is the
    nearest simulated poisson rate at or above the observed arrival
    rate (the conservative match: plan for at least the load you see);
    with no observed rate, the scenario whose recommendation equals the
    target."""
    if not plan:
        return None, None
    scenarios = [s for s in plan.get("scenarios", [])
                 if s.get("kind") == "poisson"
                 and s.get("rate_rps") is not None]
    recs = plan.get("recommendations", [])
    label = None
    if scenarios and rate_rps is not None:
        geq = [s for s in scenarios
               if float(s["rate_rps"]) >= float(rate_rps) - 1e-9]
        pick = (min(geq, key=lambda s: float(s["rate_rps"])) if geq
                else max(scenarios, key=lambda s: float(s["rate_rps"])))
        label = pick["label"]
    elif recs:
        for rec in recs:
            if rec.get("replicas") == target:
                label = rec["scenario"]
                break
        if label is None:
            label = recs[0]["scenario"]
    if label is None:
        return None, None
    plan_replicas = next(
        (rec.get("replicas") for rec in recs
         if rec.get("scenario") == label), None)
    grid = plan.get("grid", {})
    base_ladder = (grid.get("bucket_ladders") or [[]])[0]
    base_eager = (grid.get("eager") or [True])[0]
    base_cap = (grid.get("queue_caps") or [None])[0]
    for p in plan.get("points", []):
        if (p.get("scenario") == label
                and p.get("replicas") == target
                and p.get("bucket_sizes") == base_ladder
                and p.get("eager") == base_eager
                and p.get("queue_cap_images") == base_cap):
            return p.get("key"), plan_replicas
    return None, plan_replicas


def plan_recommendation(plan: Optional[dict],
                        rate_rps: Optional[float]) -> Optional[int]:
    """The plan's own replica recommendation for the observed rate
    (nearest poisson scenario at or above it) — what the fleet actuator
    uses as its recommendation signal, where the in-process scaler has
    the queue-depth/shed hysteresis hint instead."""
    if not plan or rate_rps is None:
        return None
    scenarios = [s for s in plan.get("scenarios", [])
                 if s.get("kind") == "poisson"
                 and s.get("rate_rps") is not None]
    if not scenarios:
        return None
    geq = [s for s in scenarios
           if float(s["rate_rps"]) >= float(rate_rps) - 1e-9]
    pick = (min(geq, key=lambda s: float(s["rate_rps"])) if geq
            else max(scenarios, key=lambda s: float(s["rate_rps"])))
    return next(
        (rec.get("replicas") for rec in plan.get("recommendations", [])
         if rec.get("scenario") == pick["label"]), None)


def decide_scale(
    current: int,
    recommendation: Optional[int],
    *,
    min_units: int,
    max_units: int,
    windows_since_action: int,
    cooldown_windows: int,
    hold_reason: Optional[str] = None,
    rate_rps: Optional[float] = None,
    plan: Optional[dict] = None,
) -> ScaleDecision:
    """The pure decide step both actuators share: no actuation, no
    counters. ``hold_reason`` is the caller's pin (a sustained A/B, a
    rollout in flight) — non-None holds unconditionally."""
    if recommendation is None:
        return ScaleDecision(DIR_HOLD, current, current,
                             "no hint observed yet")
    if hold_reason is not None:
        return ScaleDecision(DIR_HOLD, current, current, hold_reason)
    target = min(max(int(recommendation), int(min_units)), int(max_units))
    plan_point, plan_replicas = plan_point_for(plan, target, rate_rps)
    if target == current:
        return ScaleDecision(DIR_HOLD, current, current,
                             "hint matches live replica count",
                             plan_point, plan_replicas, rate_rps)
    if windows_since_action < cooldown_windows:
        return ScaleDecision(
            DIR_HOLD, current, current,
            f"cooldown ({windows_since_action}/"
            f"{cooldown_windows} windows since last action)",
            plan_point, plan_replicas, rate_rps)
    direction = DIR_UP if target > current else DIR_DOWN
    return ScaleDecision(
        direction, current, target,
        f"hint {recommendation} vs live {current}",
        plan_point, plan_replicas, rate_rps)


def scale_hold_reason(*, ab_pinned: bool,
                      versions_mixed: bool) -> Optional[str]:
    """Why a capacity actuator must HOLD regardless of the load hint:
    replica groups pinned by a sustained A/B, or weight versions mixed
    (a rollout canary in flight — resizing would retire or spawn groups
    out from under the experiment). None = free to act."""
    if ab_pinned:
        return "replica groups pinned by a sustained A/B"
    if versions_mixed:
        return "weight versions mixed (rollout in flight)"
    return None


# -- router active/standby HA arbitration ------------------------------------
HA_TAKE_OVER = "take_over"
HA_DEMOTE = "demote"
HA_SYNC = "sync"
HA_HOLD = "hold"


@dataclasses.dataclass(frozen=True)
class HaDecision:
    """One HA-exchange verdict: what to do, the epoch this router holds
    AFTER doing it, and the reason the logs/flight ring stamp."""

    action: str                 # take_over | demote | sync | hold
    epoch: int
    reason: str


def takeover_epoch(epoch: int, peer_epoch_seen: int) -> int:
    """The fencing rule: a takeover must claim an epoch STRICTLY above
    every epoch this router has ever held or seen its peer hold, so a
    relaunched ex-active (epoch reset to 0) can never outrank the
    router that took over from it."""
    return max(int(epoch), int(peer_epoch_seen)) + 1


def decide_ha(
    *,
    role: str,
    epoch: int,
    primary: bool,
    peer_epoch_seen: int,
    peer_reachable: bool,
    peer_role: Optional[str] = None,
    peer_epoch: int = 0,
) -> HaDecision:
    """One router's HA-exchange decision, pure. Mirrors the prose
    contract in serve/router.py: standby + dead active → take over on
    THIS missed probe; both active → the higher epoch keeps the role,
    the born-active primary wins ties; both standby → the primary
    promotes; standby + reachable active → pull its snapshot and adopt
    its epoch. ``peer_epoch_seen`` is the highest epoch the peer has
    EVER shown this router (before folding in this probe's
    ``peer_epoch``)."""
    if not peer_reachable:
        if role == "standby":
            return HaDecision(
                HA_TAKE_OVER, takeover_epoch(epoch, peer_epoch_seen),
                "active router missed a probe",
            )
        return HaDecision(HA_HOLD, int(epoch),
                          "peer unreachable; already active")
    seen = max(int(peer_epoch_seen), int(peer_epoch))
    if role == "active" and peer_role == "active":
        if peer_epoch > epoch or (peer_epoch == epoch and not primary):
            return HaDecision(HA_DEMOTE, max(int(epoch), int(peer_epoch)),
                              "peer is active at a higher epoch")
        return HaDecision(HA_HOLD, int(epoch),
                          "dual-active: this router's epoch wins")
    if role == "standby" and peer_role == "standby":
        if primary:
            return HaDecision(
                HA_TAKE_OVER, takeover_epoch(epoch, seen),
                "both routers standby; primary promotes",
            )
        return HaDecision(HA_HOLD, int(epoch),
                          "both standby; waiting for the primary")
    if role == "standby":
        return HaDecision(HA_SYNC, max(int(epoch), int(peer_epoch)),
                          "pulling the active peer's snapshot")
    return HaDecision(HA_HOLD, int(epoch), "active with a standby peer")


# -- rollout canary state machine --------------------------------------------
#: The canonical state/outcome names (serve/rollout.py re-exports them;
#: they appear verbatim in /admin/rollout payloads and the flight ring).
ROLLOUT_IDLE = "idle"
ROLLOUT_LOADING = "loading"
ROLLOUT_CANARY = "canary"
ROLLOUT_PROMOTING = "promoting"

ROLLOUT_PROMOTED = "promoted"
ROLLOUT_ROLLED_BACK = "rolled_back"
ROLLOUT_SWAP_FAILED = "swap_failed"
ROLLOUT_LOAD_FAILED = "load_failed"

#: Which snapshot a transition must restore before it completes:
#: ``canary`` = only the canary groups (the rest never swapped),
#: ``all`` = every group (a promote-time crash must not leave the fleet
#: split across versions as the steady state).
RESTORE_NONE = "none"
RESTORE_CANARY = "canary"
RESTORE_ALL = "all"


@dataclasses.dataclass(frozen=True)
class RolloutStep:
    """One legal rollout transition: the next state, the terminal
    outcome (when the next state is idle), and the restore scope the
    transition is REQUIRED to apply before finishing."""

    state: str
    outcome: Optional[str]
    restore: str


_ROLLOUT_TABLE = {
    (ROLLOUT_IDLE, "start"):
        RolloutStep(ROLLOUT_LOADING, None, RESTORE_NONE),
    (ROLLOUT_LOADING, "load_ok"):
        RolloutStep(ROLLOUT_CANARY, None, RESTORE_NONE),
    (ROLLOUT_LOADING, "load_failed"):
        RolloutStep(ROLLOUT_IDLE, ROLLOUT_LOAD_FAILED, RESTORE_NONE),
    (ROLLOUT_CANARY, "swap_failed"):
        RolloutStep(ROLLOUT_IDLE, ROLLOUT_SWAP_FAILED, RESTORE_CANARY),
    (ROLLOUT_CANARY, "judge_fail"):
        RolloutStep(ROLLOUT_IDLE, ROLLOUT_ROLLED_BACK, RESTORE_CANARY),
    (ROLLOUT_CANARY, "judge_pass"):
        RolloutStep(ROLLOUT_PROMOTING, None, RESTORE_NONE),
    (ROLLOUT_PROMOTING, "swap_failed"):
        RolloutStep(ROLLOUT_IDLE, ROLLOUT_SWAP_FAILED, RESTORE_ALL),
    (ROLLOUT_PROMOTING, "swap_ok"):
        RolloutStep(ROLLOUT_IDLE, ROLLOUT_PROMOTED, RESTORE_NONE),
}

#: Events the explorer enumerates per state (table key view).
ROLLOUT_EVENTS = tuple(sorted({e for _s, e in _ROLLOUT_TABLE}))


def rollout_transition(state: str, event: str) -> RolloutStep:
    """The rollout state machine, pure. Raises ``ValueError`` on an
    illegal (state, event) pair — the live manager only ever takes legal
    edges, and the model checker treats an illegal edge it can reach as
    a finding."""
    try:
        return _ROLLOUT_TABLE[(state, event)]
    except KeyError:
        raise ValueError(
            f"illegal rollout transition: event {event!r} in state "
            f"{state!r}"
        ) from None


def ab_may_start(*, rollout_state: str,
                 replica_groups: int) -> Optional[str]:
    """The one-experiment-at-a-time guard, pure: None = a sustained A/B
    may start; otherwise the refusal reason. A canaried rollout owns
    the replica groups (pinning arms under it would judge the canary
    against a moving fleet), and disjoint arms need two groups."""
    if rollout_state in (ROLLOUT_CANARY, ROLLOUT_PROMOTING):
        return ("a canaried rollout is in flight — one experiment owns "
                "the replica groups at a time")
    if int(replica_groups) < 2:
        return (f"sustained A/B needs >= 2 replica groups to pin "
                f"disjoint arms (have {replica_groups}) — scale up first")
    return None


# -- fleet grow/shrink rank selection ----------------------------------------
def fleet_spawn_rank(active_ranks: Sequence[int],
                     retired_ranks: FrozenSet[int]) -> int:
    """Which rank slot a fleet grow claims: the LOWEST retired slot
    (its port base+R and heartbeat slot come back with it) or a fresh
    appended rank. Pure — dist/elastic.py's ``spawn_fleet_worker``
    actuates exactly this choice."""
    if retired_ranks:
        return min(retired_ranks)
    return len(active_ranks) + len(retired_ranks)


def fleet_retire_rank(active_ranks: Sequence[int]) -> Optional[int]:
    """Which rank a fleet shrink retires: the HIGHEST active rank, or
    None when only one worker remains (a scale-down must never take the
    fleet to zero). Pure — dist/elastic.py's ``retire_fleet_worker``
    actuates exactly this choice."""
    ranks = sorted(int(r) for r in active_ranks)
    if len(ranks) <= 1:
        return None
    return ranks[-1]


#: The retire actuation ORDER the supervisor must follow — routers stop
#: placing onto the worker BEFORE its process dies, and in-flight
#: requests drain between the two; any other order is a lost-request
#: window the protocol explorer rejects.
FLEET_RETIRE_ORDER = ("eject_from_routers", "drain_inflight", "sigterm")
