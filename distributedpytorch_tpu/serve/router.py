"""One front door for the fleet: a retrying, load-aware HTTP router.

The elastic supervisor (dist/elastic.py) already makes a dead serve
worker a *relaunch* instead of an outage — but clients still had to
know every worker's port and implement their own retry dance against
``503 + Retry-After``. This module closes that gap with the classic
tail-tolerance toolkit (Dean & Barroso, "The Tail at Scale", CACM '13):

* **Load-aware placement** — each proxied request goes to the worker
  with the lowest score = router-local in-flight + last-scraped queue
  depth (a stale scrape reads as pressure, not absence). Policies:
  ``least`` (scan all) or ``p2c`` (power-of-two-choices, Mitzenmacher
  '01 — two random candidates, pick the less loaded; avoids the
  thundering-herd-on-the-idle-worker failure mode of global-least at
  scale).
* **Transparent retry** — a worker's ``503`` (shed / relaunching) is
  honored by resubmitting to a *sibling* after a bounded exponential
  backoff; a connection failure (SIGKILLed worker) ejects the worker
  from the pool and retries immediately. The client sees ONE answer:
  200 if anyone could serve it within the budget, else a single 503
  whose body merges the worst per-worker reason and whose
  ``Retry-After`` is the soonest any worker advertised.
* **Hedging** (opt-in) — past a deadline derived from the router's own
  observed p99, a duplicate request (same id, same A/B arm) fires to a
  sibling; first answer wins, the loser's connection is torn down and
  its response is never recorded — the router's ledger counts each
  request exactly once.
* **Eject / readmit** — a connection-dead worker leaves the pool and a
  probe thread re-admits it when its ``/healthz`` answers ready again
  (the supervisor relaunching it is exactly this path).
* **Active/standby HA** — the router itself must not be the last
  single point of failure, so the supervisor runs TWO of these
  (``elastic --router-port P --router-standby-port Q``). Everything a
  router knows is *reconstructible by construction*: placement state
  (depths, stale flags) re-derives from the fleet metrics sweep both
  routers ingest, eject/readmit re-derives from each router's own
  probes, and the rest (A/B split + per-arm ledger, the hedge
  deadline's p99 window, the retired set) rides a periodic
  ``/admin/state`` snapshot the standby pulls from the active. Both
  routers proxy ``/predict`` at all times — the role only governs who
  owns mutable state and which way snapshots flow — so the client
  contract is two addresses and failover on connection refusal
  (docs/SERVING.md "Front door HA"; no VIP assumed). The standby
  health-probes the active every probe interval and takes over on the
  FIRST missed probe; a relaunched ex-active sees the higher takeover
  epoch and demotes itself to standby.

Fleet elasticity rides the same pool: ``ensure_worker`` admits a
worker the supervisor's FleetScaler just spawned, ``retire_worker``
drains one it is about to SIGTERM (unroutable → wait out in-flight).

Sustained A/B (serve/rollout.py:ABTest): the router stamps each
request's arm (``X-AB-Arm``, from the same deterministic request-id
hash the workers use), fans ``POST /admin/ab`` out to every worker,
and keeps its own per-arm ledger — authoritative for the verdict's
traffic half, because hedge losers never land in it.

Deliberately **jax-free and stdlib-only** (http.client/http.server +
the obs registry): it runs inside the supervisor process, which must
never initialize a device runtime.
"""

from __future__ import annotations

import collections
import http.client
import json
import logging
import queue as queue_mod
import random
import re
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from distributedpytorch_tpu.obs import defs as obsm
from distributedpytorch_tpu.obs import flight
from distributedpytorch_tpu.serve import control
from distributedpytorch_tpu.serve.metrics import percentile
from distributedpytorch_tpu.serve.rollout import (
    ab_arm_for,
    merge_fleet_verdict,
)

logger = logging.getLogger(__name__)

# 503 reasons ranked by how bad the fleet-wide story is: when EVERY
# worker sheds, the client's single 503 carries the worst one
_REASON_SEVERITY = ("overloaded", "relaunching", "shutdown", "unreachable")

_DEPTH_RE = re.compile(
    r"^dpt_serve_queue_depth_images(?:\{[^}]*\})?\s+([0-9.eE+-]+)\s*$",
    re.MULTILINE,
)


def _worse_reason(a: Optional[str], b: Optional[str]) -> Optional[str]:
    if a is None:
        return b
    if b is None:
        return a
    rank = {r: i for i, r in enumerate(_REASON_SEVERITY)}
    return a if rank.get(a, -1) >= rank.get(b, -1) else b


class WorkerState:
    """Router-side view of one serve worker."""

    def __init__(self, name: str, host: str, port: int):
        self.name = name
        self.host = host
        self.port = int(port)
        self.healthy = True
        self.stale = False          # healthy but not answering scrapes
        self.retired = False        # deliberately drained out of the pool
        self.inflight = 0           # router-local in-flight requests
        self.depth = 0              # last-scraped queue depth (images)
        self.last_scrape_t: Optional[float] = None
        self.last_shed_reason: Optional[str] = None
        self.last_retry_after: Optional[int] = None
        self.ejected_t: Optional[float] = None

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def score(self, stale_penalty: int) -> int:
        """Placement load score: local in-flight + scraped backlog,
        plus a penalty while the worker's numbers are stale — a wedged
        worker must look BUSY, not idle (the scrape blind spot)."""
        return self.inflight + self.depth + (
            stale_penalty if self.stale else 0
        )

    def payload(self) -> dict:
        return {
            "address": self.address, "healthy": self.healthy,
            "stale": self.stale, "retired": self.retired,
            "inflight": self.inflight,
            "depth": self.depth,
            "last_shed_reason": self.last_shed_reason,
        }


class Router:
    """See module docstring. ``workers`` is ``[(host, port), ...]``."""

    def __init__(
        self,
        workers: Sequence[Tuple[str, int]],
        policy: str = "p2c",
        retry_budget: int = 3,
        backoff_base_s: float = 0.05,
        backoff_cap_s: float = 2.0,
        request_timeout_s: float = 60.0,
        hedge: bool = False,
        hedge_factor: float = 3.0,
        hedge_floor_ms: float = 250.0,
        probe_interval_s: float = 1.0,
        stale_after_s: float = 5.0,
        stale_penalty: int = 1_000_000,
        seed: int = 0,
        clock=time.monotonic,
        role: str = "active",
        peer: Optional[Tuple[str, int]] = None,
    ):
        if role not in ("active", "standby"):
            raise ValueError(f"role must be active|standby, not {role!r}")
        if policy not in ("least", "p2c"):
            raise ValueError(f"unknown placement policy {policy!r}")
        self.workers = [
            WorkerState(f"worker{i}", host, port)
            for i, (host, port) in enumerate(workers)
        ]
        if not self.workers:
            raise ValueError("a router needs at least one worker")
        self.policy = policy
        self.retry_budget = max(0, int(retry_budget))
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self.request_timeout_s = float(request_timeout_s)
        self.hedge = bool(hedge)
        self.hedge_factor = float(hedge_factor)
        self.hedge_floor_ms = float(hedge_floor_ms)
        self.probe_interval_s = max(0.05, float(probe_interval_s))
        self.stale_after_s = float(stale_after_s)
        self.stale_penalty = int(stale_penalty)
        self.clock = clock
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # router-local latency window: the hedge deadline's p99 source
        # and /stats' story (window-bounded like ServeMetrics)
        self._latencies_s: collections.deque = collections.deque(maxlen=4096)
        self.requests_ok = 0
        self.requests_failed = 0
        self.retries = 0
        self.hedges_fired = 0
        self.hedge_wins = 0
        # sustained A/B: router-side split + per-arm ledger (the verdict
        # half hedge losers can never pollute)
        self.ab_active = False
        self.ab_split = 0.5
        self.ab_label = ""
        self._ab_ledger: Dict[str, dict] = {}
        # active/standby HA: role governs state ownership + snapshot
        # flow, NOT routing — both roles proxy /predict at all times.
        # ha_primary remembers which router was BORN active: it wins
        # epoch ties and promotes first out of a both-standby state.
        self.role = role
        self.peer = (peer[0], int(peer[1])) if peer is not None else None
        self.ha_primary = role == "active"
        self.ha_epoch = 0
        self.takeovers = 0
        self.ha_syncs = 0
        self._peer_epoch_seen = 0   # highest epoch the peer has shown us

    # -- pool management -----------------------------------------------------
    def _healthy(self) -> List[WorkerState]:
        return [w for w in self.workers if w.healthy and not w.retired]

    def _pick(self, exclude=()) -> Optional[WorkerState]:
        with self._lock:
            pool = [w for w in self.workers
                    if w.healthy and not w.retired and w not in exclude]
            if not pool:
                return None
            if self.policy == "p2c" and len(pool) > 2:
                pool = self._rng.sample(pool, 2)
            best = min(pool, key=lambda w: w.score(self.stale_penalty))
            best.inflight += 1
            return best

    def _release(self, worker: WorkerState) -> None:
        with self._lock:
            worker.inflight = max(0, worker.inflight - 1)

    def _eject(self, worker: WorkerState) -> None:
        with self._lock:
            if not worker.healthy:
                return
            worker.healthy = False
            worker.ejected_t = self.clock()
            worker.last_shed_reason = "unreachable"
        obsm.ROUTER_WORKER_EVENTS.labels(event="eject").inc()
        obsm.ROUTER_HEALTHY_WORKERS.set(len(self._healthy()))
        flight.record("router_worker", event="eject", worker=worker.address)
        logger.warning("router: ejected %s (connection failure)",
                       worker.address)

    def _readmit(self, worker: WorkerState) -> None:
        with self._lock:
            if worker.healthy:
                return
            worker.healthy = True
            worker.stale = False
            worker.ejected_t = None
            worker.last_shed_reason = None
        obsm.ROUTER_WORKER_EVENTS.labels(event="readmit").inc()
        obsm.ROUTER_HEALTHY_WORKERS.set(len(self._healthy()))
        flight.record("router_worker", event="readmit",
                      worker=worker.address)
        logger.info("router: readmitted %s (/healthz ready)",
                    worker.address)

    def ensure_worker(self, host: str, port: int,
                      healthy: bool = True) -> WorkerState:
        """Admit a worker the fleet actuator just spawned (or un-retire
        a slot it is reusing). Idempotent by address."""
        port = int(port)
        with self._lock:
            for worker in self.workers:
                if worker.host == host and worker.port == port:
                    worker.retired = False
                    break
            else:
                worker = WorkerState(
                    f"worker{len(self.workers)}", host, port)
                self.workers.append(worker)
            worker.healthy = bool(healthy)
            worker.stale = False
            worker.ejected_t = None
            worker.last_shed_reason = None
        obsm.ROUTER_WORKER_EVENTS.labels(event="admit").inc()
        obsm.ROUTER_HEALTHY_WORKERS.set(len(self._healthy()))
        flight.record("router_worker", event="admit", worker=worker.address)
        logger.info("router: admitted %s (fleet spawn)", worker.address)
        return worker

    def retire_worker(self, address: str,
                      drain_timeout_s: float = 10.0) -> bool:
        """Drain a worker the fleet actuator is about to SIGTERM: make
        it unroutable, then wait out its router-local in-flight
        requests. Returns True once drained (a missing address is
        trivially drained)."""
        target = next(
            (w for w in self.workers if w.address == address), None)
        if target is None:
            return True
        with self._lock:
            target.retired = True
        obsm.ROUTER_WORKER_EVENTS.labels(event="retire").inc()
        obsm.ROUTER_HEALTHY_WORKERS.set(len(self._healthy()))
        flight.record("router_worker", event="retire", worker=address)
        logger.info("router: retiring %s (fleet drain)", address)
        deadline = time.monotonic() + float(drain_timeout_s)
        while target.inflight > 0 and time.monotonic() < deadline:
            self._stop.wait(0.02)
        return target.inflight == 0

    def ingest_fleet_metrics(self, expositions: Dict[str, str]) -> None:
        """Feed of the fleet metrics scraper (dist/elastic.py): parse
        each answering worker's queue depth out of its exposition text;
        a healthy worker MISSING from the sweep goes stale — it scores
        as pressure until it answers again."""
        now = self.clock()
        for i, worker in enumerate(self.workers):
            if worker.retired:  # deliberately gone — silence is expected
                continue
            text = expositions.get(str(i))
            if text is None:
                if worker.healthy and not worker.stale:
                    worker.stale = True
                    obsm.ROUTER_WORKER_EVENTS.labels(event="stale").inc()
                continue
            m = None
            for m in _DEPTH_RE.finditer(text):
                pass  # last match (merged expositions repeat families)
            if m is not None:
                worker.depth = int(float(m.group(1)))
            worker.stale = False
            worker.last_scrape_t = now

    # -- transport -----------------------------------------------------------
    def _send(self, worker: WorkerState, method: str, path: str,
              body: Optional[bytes] = None, headers: Optional[dict] = None,
              timeout: Optional[float] = None, conn_box: Optional[list] = None,
              ):
        """One HTTP exchange; returns ``(code, headers, body)`` or None
        on a connection-level failure. ``conn_box`` (a list) receives
        the live connection so a hedging loser can be torn down from
        the winner's thread."""
        conn = http.client.HTTPConnection(
            worker.host, worker.port,
            timeout=timeout if timeout is not None else self.request_timeout_s,
        )
        if conn_box is not None:
            conn_box.append(conn)
        try:
            conn.request(method, path, body=body, headers=headers or {})
            resp = conn.getresponse()
            data = resp.read()
            return resp.status, dict(resp.getheaders()), data
        except Exception:  # noqa: BLE001 — any transport failure is one
            # verdict: this worker is unreachable right now
            return None
        finally:
            conn.close()

    # -- the proxy core ------------------------------------------------------
    def proxy_predict(self, body: bytes, request_id: str,
                      headers: Optional[dict] = None,
                      ) -> Tuple[int, dict, bytes]:
        """Route one ``/predict`` through the fleet (see module
        docstring for the retry/hedge contract). Returns the single
        client-visible ``(code, headers, body)``."""
        t0 = self.clock()
        fwd_headers = dict(headers or {})
        fwd_headers["X-Request-Id"] = request_id
        arm = ""
        if self.ab_active:
            arm = fwd_headers.get("X-AB-Arm") or ab_arm_for(
                request_id, self.ab_split)
            fwd_headers["X-AB-Arm"] = arm

        tried: set = set()
        sheds: Dict[str, Tuple[str, Optional[int]]] = {}
        last_error: Optional[Tuple[int, dict, bytes]] = None
        attempts = 0
        backoff = self.backoff_base_s
        while attempts <= self.retry_budget:
            worker = self._pick(exclude=tried)
            if worker is None:
                if tried and self._healthy():
                    # every healthy worker shed once: later attempts may
                    # retry them (their Retry-After may have elapsed)
                    tried = set()
                    continue
                break  # nobody healthy at all
            tried.add(worker)
            attempts += 1
            try:
                result = self._send_maybe_hedged(
                    worker, body, fwd_headers, tried)
            finally:
                self._release(worker)
            if result is None:
                self._eject(worker)
                if attempts <= self.retry_budget:
                    obsm.ROUTER_RETRIES.labels(reason="connection").inc()
                    self.retries += 1
                continue  # immediate sibling — no backoff for a corpse
            code, rhdrs, rbody = result
            if code == 503:
                reason, retry_after = self._shed_info(rhdrs, rbody)
                worker.last_shed_reason = reason
                worker.last_retry_after = retry_after
                sheds[worker.address] = (reason, retry_after)
                if attempts <= self.retry_budget:
                    obsm.ROUTER_RETRIES.labels(reason="shed").inc()
                    self.retries += 1
                    self._stop.wait(min(backoff, self.backoff_cap_s))
                    backoff = min(backoff * 2.0, self.backoff_cap_s)
                continue
            if code >= 500 and attempts <= self.retry_budget:
                # non-shed worker failure (e.g. an in-flight future died
                # with a relaunching core): /predict is pure inference,
                # so resubmitting to a sibling is safe — keep the answer
                # around in case every avenue fails the same way
                last_error = (code, rhdrs, rbody)
                obsm.ROUTER_RETRIES.labels(reason="error").inc()
                self.retries += 1
                self._stop.wait(min(backoff, self.backoff_cap_s))
                backoff = min(backoff * 2.0, self.backoff_cap_s)
                continue
            # an answer (200/4xx): the client's answer
            self._finish(code, arm, self.clock() - t0)
            out = {"X-Router-Attempts": str(attempts),
                   "X-Router-Worker": worker.address}
            for key in ("X-Request-Id", "X-Serve-Latency-Ms",
                        "Content-Type"):
                if key in rhdrs:
                    out[key] = rhdrs[key]
            return code, out, rbody

        # honest degradation. A real (non-shed) worker error with no
        # shedding anywhere is returned as-is — inventing a 503 would
        # misreport a failure as overload.
        if last_error is not None and not sheds:
            code, rhdrs, rbody = last_error
            self._finish(code, arm, self.clock() - t0)
            out = {"X-Router-Attempts": str(attempts)}
            for key in ("X-Request-Id", "Content-Type"):
                if key in rhdrs:
                    out[key] = rhdrs[key]
            return code, out, rbody
        # every avenue exhausted → ONE 503 whose body names each
        # worker's last reason and leads with the worst
        worst = None
        soonest: Optional[int] = None
        for reason, retry_after in sheds.values():
            worst = _worse_reason(worst, reason)
            if retry_after is not None:
                soonest = (retry_after if soonest is None
                           else min(soonest, retry_after))
        if worst is None:
            worst = "unreachable"
        self._finish(503, arm, self.clock() - t0)
        payload = json.dumps({
            "status": "rejected", "reason": worst,
            "request_id": request_id, "attempts": attempts,
            "workers": {addr: reason for addr, (reason, _) in sheds.items()},
        }).encode()
        out = {"Content-Type": "application/json",
               "X-Request-Id": request_id,
               "X-Router-Attempts": str(attempts)}
        if soonest is not None:
            out["Retry-After"] = str(int(soonest))
        return 503, out, payload

    def _send_maybe_hedged(self, primary: WorkerState, body: bytes,
                           headers: dict, tried: set):
        """The primary exchange, with an optional single hedge to a
        sibling past the p99-derived deadline. Exactly one result is
        returned and recorded; the loser's connection is closed."""
        if not self.hedge:
            return self._send(primary, "POST", "/predict", body, headers)
        results: "queue_mod.Queue" = queue_mod.Queue()
        boxes: Dict[str, list] = {"primary": [], "hedge": []}

        def call(worker: WorkerState, tag: str) -> None:
            results.put((tag, self._send(
                worker, "POST", "/predict", body, headers,
                conn_box=boxes[tag])))

        threading.Thread(target=call, args=(primary, "primary"),
                         name="dpt-router-req", daemon=True).start()
        try:
            tag, result = results.get(timeout=self._hedge_delay_s())
            return result
        except queue_mod.Empty:
            pass
        sibling = self._pick(exclude=tried | {primary})
        if sibling is None:  # nobody to hedge to — wait the primary out
            tag, result = results.get()
            return result
        self.hedges_fired += 1
        try:
            threading.Thread(target=call, args=(sibling, "hedge"),
                             name="dpt-router-hedge", daemon=True).start()
            tag, result = results.get()  # first answer wins
        finally:
            self._release(sibling)
        loser = "hedge" if tag == "primary" else "primary"
        for conn in boxes[loser]:
            try:  # tear the loser down: its response is never read,
                # never recorded — cancelled, not double-counted
                conn.close()
            except Exception:  # noqa: BLE001
                pass
        obsm.ROUTER_HEDGES.labels(winner=tag).inc()
        if tag == "hedge":
            self.hedge_wins += 1
        flight.record("router_hedge", winner=tag,
                      primary=primary.address, sibling=sibling.address)
        return result

    def _hedge_delay_s(self) -> float:
        with self._lock:
            lat = list(self._latencies_s)
        p99_ms = percentile(lat, 99) * 1e3 if lat else 0.0
        return max(self.hedge_factor * p99_ms, self.hedge_floor_ms) / 1e3

    @staticmethod
    def _shed_info(rhdrs: dict, rbody: bytes
                   ) -> Tuple[str, Optional[int]]:
        reason = "overloaded"
        try:
            reason = json.loads(rbody).get("reason", reason)
        except Exception:  # noqa: BLE001
            pass
        retry_after = None
        ra = rhdrs.get("Retry-After")
        if ra is not None:
            try:
                retry_after = int(float(ra))
            except ValueError:
                pass
        return reason, retry_after

    def _finish(self, code: int, arm: str, latency_s: float) -> None:
        with self._lock:
            if code == 200:
                self.requests_ok += 1
                self._latencies_s.append(latency_s)
            else:
                self.requests_failed += 1
            if arm:
                led = self._ab_ledger.setdefault(arm, {
                    "requests_ok": 0, "requests_failed": 0,
                    "latencies_s": collections.deque(maxlen=4096),
                })
                if code == 200:
                    led["requests_ok"] += 1
                    led["latencies_s"].append(latency_s)
                else:
                    led["requests_failed"] += 1
        obsm.ROUTER_REQUESTS.labels(code=str(code)).inc()

    # -- sustained A/B fan-out ----------------------------------------------
    def admin_ab(self, spec: dict) -> Tuple[int, dict]:
        """``POST /admin/ab`` front: fan the action out to every
        healthy worker and merge. ``spec`` carries ``action``
        (start/verdict/stop) plus start's ``checkpoint``/``split``/
        ``label`` or stop's ``winner``."""
        action = spec.get("action")
        if action not in ("start", "verdict", "stop"):
            return 400, {"error": "action must be start|verdict|stop"}
        per_worker: Dict[str, dict] = {}
        codes: List[int] = []
        for worker in self._healthy():
            result = self._send(worker, "POST", "/admin/ab",
                                json.dumps(spec).encode(),
                                {"Content-Type": "application/json"},
                                timeout=30.0)
            if result is None:
                per_worker[worker.address] = {"error": "unreachable"}
                codes.append(503)
                continue
            code, _, rbody = result
            codes.append(code)
            try:
                per_worker[worker.address] = json.loads(rbody)
            except Exception:  # noqa: BLE001
                per_worker[worker.address] = {"error": rbody[:200].decode(
                    "utf-8", "replace")}
        ok = bool(codes) and all(c < 400 for c in codes)
        if action == "start" and ok:
            self.ab_active = True
            self.ab_split = float(spec.get("split", 0.5))
            self.ab_label = str(spec.get("label", ""))
            with self._lock:
                self._ab_ledger = {}
        elif action == "stop":
            self.ab_active = False
        body = {
            "action": action, "ok": ok,
            "router": self.ab_status(),
            "workers": per_worker,
        }
        if action == "verdict":
            # one fleet verdict: per-arm ledgers summed across workers,
            # Dice averaged over workers that actually served probe
            # rows (serve/rollout.py:merge_fleet_verdict)
            body["fleet"] = merge_fleet_verdict(per_worker)
        return (200 if ok else 502), body

    def ab_status(self) -> dict:
        with self._lock:
            ledger = {
                arm: (dict(led), list(led["latencies_s"]))
                for arm, led in self._ab_ledger.items()
            }
        arms = {}
        for arm, (led, lat) in sorted(ledger.items()):
            arms[arm] = {
                "requests_ok": led["requests_ok"],
                "requests_failed": led["requests_failed"],
                "p50_ms": round(percentile(lat, 50) * 1e3, 3) if lat else None,
                "p99_ms": round(percentile(lat, 99) * 1e3, 3) if lat else None,
            }
        return {"active": self.ab_active, "split": self.ab_split,
                "label": self.ab_label, "arms": arms}

    # -- health probe thread -------------------------------------------------
    def probe_once(self) -> None:
        """One sweep: re-probe ejected workers' ``/healthz``; with no
        external metrics feed, scrape healthy workers' ``/stats`` for
        depth (and mark the silent ones stale)."""
        now = self.clock()
        for worker in self.workers:
            if worker.retired:
                continue
            if not worker.healthy:
                result = self._send(worker, "GET", "/healthz",
                                    timeout=2.0)
                if result is not None and result[0] == 200:
                    self._readmit(worker)
                continue
            result = self._send(worker, "GET", "/stats", timeout=2.0)
            if result is None or result[0] != 200:
                if (worker.last_scrape_t is None
                        or now - worker.last_scrape_t > self.stale_after_s):
                    if not worker.stale:
                        worker.stale = True
                        obsm.ROUTER_WORKER_EVENTS.labels(
                            event="stale").inc()
                continue
            try:
                stats = json.loads(result[2])
                worker.depth = int(stats.get("queue_depth_images", 0))
            except Exception:  # noqa: BLE001
                pass
            worker.stale = False
            worker.last_scrape_t = now

    # -- active/standby HA ---------------------------------------------------
    def export_state(self) -> dict:
        """The ``/admin/state`` snapshot: everything a sibling router
        cannot re-derive from its own probes + the fleet metrics sweep
        — the A/B split and per-arm ledger, the hedge deadline's
        latency window, and the retired set. Worker rows ride along as
        a hint (the importer's own probes remain authoritative)."""
        with self._lock:
            ledger = {
                arm: {
                    "requests_ok": led["requests_ok"],
                    "requests_failed": led["requests_failed"],
                    "latencies_s": [round(v, 6) for v in
                                    list(led["latencies_s"])[-512:]],
                }
                for arm, led in self._ab_ledger.items()
            }
            latencies = [round(v, 6) for v in
                         list(self._latencies_s)[-512:]]
        return {
            "kind": "dpt_router_state",
            "role": self.role,
            "epoch": self.ha_epoch,
            "primary": self.ha_primary,
            "policy": self.policy,
            "workers": [w.payload() for w in self.workers],
            "ab": {"active": self.ab_active, "split": self.ab_split,
                   "label": self.ab_label, "ledger": ledger},
            "latencies_s": latencies,
        }

    def import_state(self, state: dict) -> None:
        """Apply a peer's snapshot (standby side of the exchange):
        restore the A/B config + ledger and the latency window, adopt
        the retired set, and admit workers the peer knows that we were
        not constructed with (a fleet spawn we missed)."""
        if state.get("kind") != "dpt_router_state":
            raise ValueError("not a dpt_router_state snapshot")
        by_address = {w.address: w for w in self.workers}
        for row in state.get("workers", []):
            addr = row.get("address", "")
            worker = by_address.get(addr)
            if worker is None and ":" in addr:
                host, _, port = addr.rpartition(":")
                worker = self.ensure_worker(
                    host, int(port), healthy=bool(row.get("healthy")))
            if worker is not None:
                worker.retired = bool(row.get("retired", False))
        ab = state.get("ab", {})
        with self._lock:
            self.ab_active = bool(ab.get("active", False))
            self.ab_split = float(ab.get("split", 0.5))
            self.ab_label = str(ab.get("label", ""))
            self._ab_ledger = {
                arm: {
                    "requests_ok": int(led.get("requests_ok", 0)),
                    "requests_failed": int(led.get("requests_failed", 0)),
                    "latencies_s": collections.deque(
                        led.get("latencies_s", []), maxlen=4096),
                }
                for arm, led in ab.get("ledger", {}).items()
            }
            self._latencies_s = collections.deque(
                state.get("latencies_s", []), maxlen=4096)
        self.ha_syncs += 1
        obsm.ROUTER_HA_EVENTS.labels(event="sync").inc()

    def _peer_state(self):
        """GET the peer router's ``/admin/state``; None if the peer is
        unreachable or not answering sensibly."""
        if self.peer is None:
            return None
        host, port = self.peer
        conn = http.client.HTTPConnection(host, port, timeout=2.0)
        try:
            conn.request("GET", "/admin/state")
            resp = conn.getresponse()
            data = resp.read()
            if resp.status != 200:
                return None
            return json.loads(data)
        except Exception:  # noqa: BLE001 — unreachable peer is the
            # signal, not an error
            return None
        finally:
            conn.close()

    def _take_over(self, decision: control.HaDecision) -> None:
        self.role = "active"
        self.ha_epoch = decision.epoch
        self.takeovers += 1
        obsm.ROUTER_HA_EVENTS.labels(event="takeover").inc()
        flight.record("router_ha", event="takeover", reason=decision.reason,
                      epoch=self.ha_epoch)
        logger.warning("router: TOOK OVER as active (epoch %d): %s",
                       self.ha_epoch, decision.reason)

    def _demote(self, decision: control.HaDecision) -> None:
        self.role = "standby"
        self.ha_epoch = decision.epoch
        obsm.ROUTER_HA_EVENTS.labels(event="demote").inc()
        flight.record("router_ha", event="demote", reason=decision.reason,
                      epoch=self.ha_epoch)
        logger.warning("router: demoted to standby (epoch %d): %s",
                       self.ha_epoch, decision.reason)

    def ha_once(self) -> None:
        """One HA exchange with the peer router (runs every probe
        interval, so 'takeover within one probe interval' is by
        construction). The DECISION is ``serve/control.decide_ha`` —
        the same pure arbitration rule the protocol explorer
        (analysis/protocol.py) exhaustively model-checks: standby +
        reachable active → pull its snapshot; standby + dead active →
        take over on THIS missed probe; both active (a relaunched
        ex-active rejoining) → the higher epoch keeps the role, primary
        wins ties; both standby → the primary promotes itself."""
        if self.peer is None:
            return
        state = self._peer_state()
        peer_reachable = state is not None
        peer_role = state.get("role", "") if peer_reachable else None
        peer_epoch = int(state.get("epoch", 0)) if peer_reachable else 0
        decision = control.decide_ha(
            role=self.role,
            epoch=self.ha_epoch,
            primary=self.ha_primary,
            peer_epoch_seen=self._peer_epoch_seen,
            peer_reachable=peer_reachable,
            peer_role=peer_role,
            peer_epoch=peer_epoch,
        )
        if peer_reachable:
            self._peer_epoch_seen = max(self._peer_epoch_seen, peer_epoch)
        if decision.action == control.HA_TAKE_OVER:
            self._take_over(decision)
        elif decision.action == control.HA_DEMOTE:
            self._demote(decision)
        elif decision.action == control.HA_SYNC:
            try:
                self.import_state(state)
            except Exception:  # noqa: BLE001 — a malformed snapshot
                # must not kill the probe loop; next interval retries
                logger.exception("router: peer snapshot import failed")
            self.ha_epoch = decision.epoch

    def _probe_loop(self) -> None:
        while not self._stop.wait(self.probe_interval_s):
            try:
                self.probe_once()
            except Exception:  # noqa: BLE001 — the probe must outlive
                # one bad sweep
                logger.exception("router: probe sweep failed")
            try:
                self.ha_once()
            except Exception:  # noqa: BLE001
                logger.exception("router: HA exchange failed")

    def start(self) -> "Router":
        obsm.ROUTER_HEALTHY_WORKERS.set(len(self._healthy()))
        self._thread = threading.Thread(
            target=self._probe_loop, name="dpt-router-probe", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)

    def stats(self) -> dict:
        with self._lock:
            lat = list(self._latencies_s)
        return {
            "policy": self.policy,
            "workers": [w.payload() for w in self.workers],
            "healthy_workers": len(self._healthy()),
            "requests_ok": self.requests_ok,
            "requests_failed": self.requests_failed,
            "retries": self.retries,
            "hedges_fired": self.hedges_fired,
            "hedge_wins": self.hedge_wins,
            "p50_ms": round(percentile(lat, 50) * 1e3, 3) if lat else None,
            "p99_ms": round(percentile(lat, 99) * 1e3, 3) if lat else None,
            "ab": self.ab_status(),
            "ha": {
                "role": self.role,
                "epoch": self.ha_epoch,
                "primary": self.ha_primary,
                "peer": (f"{self.peer[0]}:{self.peer[1]}"
                         if self.peer else None),
                "takeovers": self.takeovers,
                "syncs": self.ha_syncs,
            },
        }


def make_router_http(router: Router, host: str = "127.0.0.1",
                     port: int = 0):
    """Wrap a :class:`Router` in a ThreadingHTTPServer (port 0 =
    ephemeral) — the ONE address clients talk to. Routes: ``POST
    /predict`` (proxied with retry/hedge), ``POST /admin/ab`` (fleet
    fan-out), ``GET /healthz`` (200 while >= 1 worker is routable),
    ``GET /stats``, ``GET /metrics``."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from distributedpytorch_tpu.obs.http import metrics_response
    from distributedpytorch_tpu.obs.reqtrace import (
        new_request_id,
        request_id_from_headers,
    )

    class Handler(BaseHTTPRequestHandler):
        def _json(self, code: int, obj: dict,
                  headers: Optional[dict] = None) -> None:
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for key, value in (headers or {}).items():
                self.send_header(key, value)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 — http.server's contract
            if self.path == "/healthz":
                healthy = len(router._healthy())
                self._json(200 if healthy else 503, {
                    "ready": healthy > 0,
                    "healthy_workers": healthy,
                    "workers": [w.payload() for w in router.workers],
                })
            elif self.path == "/livez":
                self._json(200, {"status": "alive"})
            elif self.path == "/admin/state":
                self._json(200, router.export_state())
            elif self.path == "/stats":
                self._json(200, router.stats())
            elif self.path == "/metrics":
                body, ctype = metrics_response()
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self._json(404, {"error": f"no route {self.path}"})

        def do_POST(self):  # noqa: N802
            length = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(length)
            if self.path == "/admin/ab":
                try:
                    spec = json.loads(body or b"{}")
                except ValueError:
                    self._json(400, {"error": "body must be JSON"})
                    return
                code, payload = router.admin_ab(spec)
                self._json(code, payload)
                return
            if self.path == "/admin/state":
                try:
                    router.import_state(json.loads(body or b"{}"))
                except (ValueError, TypeError) as exc:
                    self._json(400, {"error": str(exc)})
                    return
                self._json(200, {"imported": True,
                                 "role": router.role,
                                 "epoch": router.ha_epoch})
                return
            if self.path != "/predict":
                self._json(404, {"error": f"no route {self.path}"})
                return
            rid = (request_id_from_headers(self.headers)
                   or new_request_id())
            fwd = {}
            for key in ("Content-Type", "X-AB-Arm", "traceparent"):
                if key in self.headers:
                    fwd[key] = self.headers[key]
            code, rhdrs, rbody = router.proxy_predict(
                body, request_id=rid, headers=fwd)
            self.send_response(code)
            rhdrs.setdefault("X-Request-Id", rid)
            rhdrs["Content-Length"] = str(len(rbody))
            for key, value in rhdrs.items():
                self.send_header(key, value)
            self.end_headers()
            self.wfile.write(rbody)

        def log_message(self, fmt, *fmt_args):  # route through logging
            logger.debug("router-http: " + fmt, *fmt_args)

    return ThreadingHTTPServer((host, port), Handler)


def _parse_hostport(text: str) -> Tuple[str, int]:
    host, _, port = text.rpartition(":")
    return (host or "127.0.0.1"), int(port)


def main(argv: Optional[Sequence[str]] = None) -> int:
    """Standalone router process — what the HA chaos drill SIGKILLs.
    The supervisor normally runs routers in-process; this entry point
    exists so one half of an active/standby pair can be a real OS
    process whose death is a real death."""
    import argparse
    import signal

    parser = argparse.ArgumentParser(
        prog="python -m distributedpytorch_tpu.serve.router",
        description="Fleet front-door router (one of an HA pair).")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, required=True)
    parser.add_argument(
        "--workers", required=True,
        help="comma-separated host:port list of serve workers")
    parser.add_argument("--role", choices=("active", "standby"),
                        default="active")
    parser.add_argument(
        "--peer", default=None,
        help="host:port of the sibling router's front address")
    parser.add_argument("--policy", choices=("p2c", "least"),
                        default="p2c")
    parser.add_argument("--probe-interval", type=float, default=1.0)
    parser.add_argument("--retry-budget", type=int, default=3)
    parser.add_argument("--backoff-base", type=float, default=0.05)
    parser.add_argument("--hedge", action="store_true")
    args = parser.parse_args(argv)

    workers = [_parse_hostport(w)
               for w in args.workers.split(",") if w.strip()]
    router = Router(
        workers, policy=args.policy,
        retry_budget=args.retry_budget,
        backoff_base_s=args.backoff_base,
        hedge=args.hedge,
        probe_interval_s=args.probe_interval,
        role=args.role,
        peer=_parse_hostport(args.peer) if args.peer else None,
    ).start()
    httpd = make_router_http(router, host=args.host, port=args.port)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    server_thread = threading.Thread(
        target=httpd.serve_forever, name="dpt-router-http", daemon=True)
    server_thread.start()
    logger.info("router: %s on %s:%d (peer=%s, %d workers)",
                args.role, args.host, args.port, args.peer, len(workers))
    try:
        while not stop.wait(0.2):
            pass
    except KeyboardInterrupt:
        pass
    finally:
        httpd.shutdown()
        router.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
