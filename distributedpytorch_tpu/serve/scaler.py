"""Planner-actuated replica autoscaling: ACT on ``dpt_serve_replica_hint``.

PR 13 left autoscaling split in two honest halves: serve/autoscale.py
*recommends* (queue-depth/shed hysteresis → the
``dpt_serve_replica_hint`` gauge) and a human resizes. This module is
the missing actuator — it grows and shrinks the LIVE replica group
through ``Server.resize_replicas`` (AOT-store-backed executables, no
worker restart, no drain) whenever the hint diverges from reality.

Two disciplines keep the control loop boring:

* **The plan-serve grid is the control law.** Every decision is cited
  against the ``dpt_serve_plan`` artifact (analysis/serve_planner.py):
  the scaler matches the observed arrival rate to the nearest simulated
  poisson scenario and logs the grid **point key** its new replica
  count corresponds to — so a 2→4 scale-up reads
  ``plan_point=poisson:8rps/b1,4,8/slo50/r4/eager/capauto`` in the
  flight ring and ``/stats``, and an operator can open the plan and see
  the predicted p99/shed that decision was buying. No plan → decisions
  still happen (the hint alone), cited as ``plan_point=None``.
* **No flapping.** The scaler refuses to act more often than the
  hint's own hysteresis (``cooldown_windows`` — default the max of the
  hint's up/down window counts) and holds entirely while replica
  groups are pinned by a sustained A/B or a mid-flight rollout
  (mixed weight versions): resizing would tear an arm boundary.

Actuations land in ``dpt_serve_scale_events_total`` (by direction),
``dpt_serve_replicas``, and the flight ring; after every resize the
hint's ``depth_high`` pressure line is re-anchored to the new capacity
so the NEXT recommendation judges the fleet that exists, not the one
that did.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Callable, List, Optional

from distributedpytorch_tpu.obs import defs as obsm
from distributedpytorch_tpu.obs import flight

# the control law itself lives in serve/control.py, shared with the
# supervisor's FleetScaler (dist/elastic.py) so the two actuation
# granularities cannot drift; re-exported here for back-compat
from distributedpytorch_tpu.serve.control import (  # noqa: F401
    DIR_DOWN,
    DIR_HOLD,
    DIR_UP,
    ScaleDecision,
    decide_scale,
    plan_point_for,
    scale_hold_reason,
)

logger = logging.getLogger(__name__)


class ReplicaScaler:
    """The hint's actuator (see module docstring).

    ``plan`` is a loaded ``dpt_serve_plan`` payload dict, a path to
    one, or None. ``step()`` is the whole control loop iteration —
    read the hint, decide, act — and is what both the background
    thread and the deterministic tests drive.
    """

    def __init__(
        self,
        server,
        hint,
        plan=None,
        min_replicas: int = 1,
        max_replicas: Optional[int] = None,
        cooldown_windows: Optional[int] = None,
        interval_s: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.server = server
        self.hint = hint
        if isinstance(plan, str):
            from distributedpytorch_tpu.analysis.serve_planner import (
                load_serve_plan,
            )
            plan = load_serve_plan(plan)
        self.plan = plan
        self.min_replicas = max(1, int(min_replicas))
        self.max_replicas = (
            int(max_replicas) if max_replicas is not None else None
        )
        self.cooldown_windows = int(
            cooldown_windows if cooldown_windows is not None
            else max(int(hint.up_windows), int(hint.down_windows))
        )
        self.interval_s = (
            float(interval_s) if interval_s is not None
            else float(hint.interval_s)
        )
        self.clock = clock
        # start past cooldown: the FIRST divergence may act immediately
        self.windows_since_action = self.cooldown_windows
        self.decisions: List[dict] = []  # bounded ledger (status())
        self.scale_ups = 0
        self.scale_downs = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # arrival-rate observation state (thread mode)
        self._last_requests = None
        self._last_t = None

    # -- the control law -----------------------------------------------------
    def decide(self, recommendation: Optional[int],
               observed_rate_rps: Optional[float] = None) -> ScaleDecision:
        """Pure verdict: no actuation, no counters — tests drive this
        directly with a fake hint value and an explicit rate."""
        current = self.server.engine.num_replicas
        abtest = getattr(self.server, "abtest", None)
        # the pin rule is the pure law the protocol explorer
        # model-checks (control.scale_hold_reason): a scaler that acts
        # while a canary owns the groups retires the experiment's pinned
        # replicas out from under it
        hold_reason = scale_hold_reason(
            ab_pinned=(abtest is not None and abtest.active) or (
                getattr(self.server, "ab_arms", None) is not None),
            versions_mixed=self.server.engine.versions_mixed,
        )
        cap = self.max_replicas
        if cap is None:
            import jax
            cap = len(jax.devices())
        return decide_scale(
            current, recommendation,
            min_units=self.min_replicas, max_units=cap,
            windows_since_action=self.windows_since_action,
            cooldown_windows=self.cooldown_windows,
            hold_reason=hold_reason,
            rate_rps=observed_rate_rps, plan=self.plan)

    def _plan_point(self, target: int, rate_rps: Optional[float]):
        return plan_point_for(self.plan, target, rate_rps)

    # -- actuation -----------------------------------------------------------
    def apply(self, decision: ScaleDecision) -> ScaleDecision:
        """Execute a non-hold decision through the server's live
        resizer; stamps the ledger/flight/metric trail either way."""
        achieved = decision.current
        if decision.direction != DIR_HOLD:
            achieved = self.server.resize_replicas(decision.target)
            if achieved != decision.current:
                self.windows_since_action = 0
                if decision.direction == DIR_UP:
                    self.scale_ups += 1
                else:
                    self.scale_downs += 1
                # re-anchor the hint's pressure line to the NEW capacity
                # (it was frozen at init against the old replica count)
                self.hint.depth_high = (
                    self.server.engine.planner.max_size * achieved
                )
                obsm.SERVE_SCALE_EVENTS.labels(
                    direction=decision.direction).inc()
                logger.info(
                    "scaler: %s %d -> %d (%s) plan_point=%s",
                    decision.direction, decision.current, achieved,
                    decision.reason, decision.plan_point,
                )
            entry = {**decision.payload(), "achieved": achieved}
            self.decisions.append(entry)
            del self.decisions[:-50]
            flight.record("serve_scale", **{
                k: v for k, v in entry.items() if v is not None})
        return dataclasses.replace(decision, target=achieved)

    def step(self, observed_rate_rps: Optional[float] = None
             ) -> ScaleDecision:
        """One control-loop window: age the cooldown, read the hint's
        latest recommendation, decide, act."""
        self.windows_since_action += 1
        decision = self.decide(self.hint.recommendation, observed_rate_rps)
        return self.apply(decision)

    # -- background thread (worker mode) -------------------------------------
    def _observed_rate(self) -> Optional[float]:
        snap = self.server.metrics.snapshot()
        now = self.clock()
        total = snap["requests_ok"] + snap["requests_failed"] + snap.get(
            "rejected_total", 0)
        rate = None
        if self._last_requests is not None and now > self._last_t:
            rate = (total - self._last_requests) / (now - self._last_t)
        self._last_requests, self._last_t = total, now
        return rate

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.step(observed_rate_rps=self._observed_rate())
            except Exception:  # noqa: BLE001 — the control loop must
                # outlive one bad window; the failure is in the log
                logger.exception("scaler: step failed")

    def start(self) -> "ReplicaScaler":
        self._thread = threading.Thread(
            target=self._run, name="dpt-serve-scaler", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)

    def status(self) -> dict:
        return {
            "replicas": self.server.engine.num_replicas,
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "cooldown_windows": self.cooldown_windows,
            "windows_since_action": self.windows_since_action,
            "scale_ups": self.scale_ups,
            "scale_downs": self.scale_downs,
            "plan": bool(self.plan),
            "decisions": self.decisions[-10:],
        }
