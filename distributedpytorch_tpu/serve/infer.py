"""Shared inference pieces: preprocess → forward → postprocess.

ONE implementation of the inference data path, used by BOTH surfaces:

* the batch-offline CLI (``predict.py`` / ``dpt-predict``) — streams a
  directory of images through it batch-by-batch;
* the serving tier (``serve/engine.py`` / ``python -m
  distributedpytorch_tpu serve``) — AOT-compiles the same forward per
  padded bucket shape and runs it under the continuous-batching queue.

Because both paths run these exact functions, the offline-vs-serve
parity test (tests/test_serve.py) can pin masks *bit-identical* across
the two surfaces — any drift in preprocessing, the forward, or the
thresholding is a test failure, not a silent production skew.

Kept import-light at module scope (numpy/PIL only); jax loads inside
the functions that trace, mirroring predict.py's historical layout so
``--help`` and queue-only tests never pay a backend init.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Callable, Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger(__name__)


def preprocess_image(pil_img, size_wh: Sequence[int]) -> np.ndarray:
    """One decoded PIL image → the model's input row: forced RGB, BICUBIC
    resize to ``(W, H)``, /255, NHWC float32 — exactly the training-side
    ``BasicDataset.preprocess`` (any divergence here would silently skew
    every served prediction against the trained distribution)."""
    from distributedpytorch_tpu.data.dataset import BasicDataset

    # palette GIFs, RGBA PNGs, grayscale: the model wants exactly 3 channels
    pil_img = pil_img.convert("RGB")
    return BasicDataset.preprocess(pil_img, size_wh, is_mask=False)


def load_image(path: str, size_wh: Sequence[int]) -> np.ndarray:
    """Decode + preprocess one image file (PIL / .npy / .pt dispatch via
    ``BasicDataset.load``)."""
    from distributedpytorch_tpu.data.dataset import BasicDataset

    return preprocess_image(BasicDataset.load(path), size_wh)


def make_forward(
    model, quantized: bool = False, mask_threshold: Optional[float] = None
) -> Callable:
    """The eval forward as a plain jittable ``fwd(variables, x) -> probs``:
    ``variables`` is ``{"params": ...}`` (plus ``"batch_stats"`` for
    stateful families — milesial BatchNorm — applied in eval mode),
    ``x`` is ``(B, H, W, 3) float32``, the result ``(B, H, W) float32``
    sigmoid probabilities (the trailing channel squeezed inside the
    traced program). Taking the variables as an ARGUMENT (not a closure)
    is what lets the serving engine place them per replica device and
    AOT-compile against device-pinned ShapeDtypeStructs.

    ``quantized=True`` consumes int8 weights-only variables (``params``
    holds ``{"q": int8, "scale": f32}`` kernel subtrees — ops/quant.py):
    dequantization happens INSIDE the traced forward, so the executable's
    resident weight arguments stay one byte per element and the float
    kernels exist only as temps.

    ``mask_threshold`` (the ``--kernels pallas`` serve-mask engagement,
    ops/kernels.py) traces the fused sigmoid/threshold mask kernel onto
    the tail: the forward then returns the served ``{0, 255} uint8``
    mask itself — 1 byte/pixel over the D2H drain instead of 4, and no
    host threshold pass — bit-identical to ``postprocess_mask`` of the
    probabilities at the same threshold (the model's sigmoid already ran
    under the LOSS_DTYPE contract, so the kernel runs its exact-compare
    threshold leg)."""
    stateful = bool(getattr(model, "is_stateful", False))

    def fwd(variables, x):
        if quantized:
            from distributedpytorch_tpu.ops.quant import dequantize_tree

            variables = dict(variables)
            variables["params"] = dequantize_tree(variables["params"])
        if stateful:
            probs = model.apply(variables, x, train=False)
        else:
            probs = model.apply(variables, x)
        probs = probs[..., 0]
        if mask_threshold is not None:
            from distributedpytorch_tpu.ops.kernels import (
                sigmoid_threshold_mask,
            )

            return sigmoid_threshold_mask(probs, mask_threshold)
        return probs

    return fwd


def bundle_variables(model, params, model_state=None) -> dict:
    """The flax variables dict ``make_forward`` consumes — batch_stats
    included exactly when the model family is stateful."""
    if getattr(model, "is_stateful", False):
        return {"params": params, "batch_stats": model_state}
    return {"params": params}


def postprocess_mask(probs: np.ndarray, threshold: float = 0.5) -> np.ndarray:
    """Probabilities → the served artifact: ``{0, 255} uint8`` masks
    (same shape in, channelless out). Works on a single ``(H, W)`` row or
    a ``(B, H, W)`` batch. A ``uint8`` input passes through untouched —
    it IS the mask already, thresholded on-device by the serve-mask
    kernel (``make_forward(mask_threshold=...)``), so the completion
    drain stays one code path under either kernel policy."""
    arr = np.asarray(probs)
    if arr.dtype == np.uint8:
        return arr
    return (arr >= threshold).astype(np.uint8) * 255


@dataclasses.dataclass
class InferenceBundle:
    """Everything one checkpoint needs to serve: the model object, its
    weights (+ BatchNorm stats for stateful families), and the resolved
    TrainConfig whose geometry/arch fields sized the model.
    ``quantized=True`` means ``params`` is an int8 weights-only tree
    (ops/quant.py) and the forward dequantizes in-trace."""

    model: object
    params: object
    model_state: object
    config: object
    input_hw: Tuple[int, int]  # (H, W) — note: CLI flags order (W, H)
    quantized: bool = False

    def forward(self) -> Callable:
        return make_forward(self.model, quantized=self.quantized)

    @property
    def variables(self) -> dict:
        return bundle_variables(self.model, self.params, self.model_state)


def load_inference_bundle(
    checkpoint: str,
    checkpoint_dir: str = "./checkpoints",
    image_size: Sequence[int] = (960, 640),
    model_arch: str = "unet",
    model_widths: Optional[Sequence[int]] = None,
    s2d_levels: int = -1,
    quantize: Optional[str] = None,
) -> InferenceBundle:
    """Resolve a checkpoint name/path and build the model + weights for
    inference. ``model_arch``/``model_widths`` must match the trained
    checkpoint's architecture. Image sizes the space-to-depth mode cannot
    express (H or W not divisible by ``2**levels``) fall back to the
    (equivalent) pixel path — checkpoints are identical across execution
    modes, so this changes speed, never results.

    ``quantize="int8"`` serves weights-only int8 (ops/quant.py): a file
    written by tools/quantize.py loads directly (its manifest records the
    source checkpoint hash), a regular checkpoint is quantized on load
    (convenient for A/Bs; persist with the tool for production). A
    quantized file is also auto-detected when ``quantize`` is unset —
    loudly, since the serving numerics change."""
    from distributedpytorch_tpu.checkpoint import resolve_checkpoint
    from distributedpytorch_tpu.config import TrainConfig
    from distributedpytorch_tpu.models import create_model
    from distributedpytorch_tpu.ops import quant

    if quantize not in (None, "int8"):
        raise ValueError(
            f"quantize must be None or 'int8', got {quantize!r}"
        )
    path = resolve_checkpoint(checkpoint, checkpoint_dir)
    w, h = int(image_size[0]), int(image_size[1])
    cfg = TrainConfig(
        model_arch=model_arch,
        model_widths=tuple(model_widths) if model_widths else None,
        s2d_levels=s2d_levels,
    )
    div = 2 ** cfg.model_levels
    if s2d_levels != 0 and (h % div or w % div):
        logger.info(
            "image size %dx%d not divisible by %d: space-to-depth execution "
            "unavailable, using the (equivalent) pixel path", w, h, div,
        )
        cfg = dataclasses.replace(cfg, s2d_levels=0)
    model, _ = create_model(cfg)

    # ONE file read decides the kind AND feeds whichever loader applies —
    # a multi-GB checkpoint must not be deserialized twice per startup
    # (the same read_payload seam the trainer's restore uses)
    payload = None
    if not path.endswith(".pth"):
        from distributedpytorch_tpu.checkpoint import read_payload

        payload = read_payload(path)
    if isinstance(payload, dict) and payload.get("kind") == quant.QUANT_KIND:
        if quantize is None:
            logger.warning(
                "%s is an int8 weights file — serving quantized "
                "(pass --quantize int8 to make this explicit)", path,
            )
        qtree, raw_state, manifest = quant.load_quantized(
            path, payload=payload
        )
        _check_quantized_identity(manifest, model_arch, model_widths, path)
        model_state = _restore_model_state(model, raw_state, (h, w), path)
        return InferenceBundle(
            model=model, params=qtree, model_state=model_state, config=cfg,
            input_hw=(h, w), quantized=True,
        )
    params, model_state = load_params_for_inference(
        path, model, input_hw=(h, w), payload=payload
    )
    if quantize == "int8":
        logger.info(
            "quantizing %s to int8 weights on load (per-out-channel "
            "symmetric); persist with tools/quantize.py to skip this at "
            "every startup", path,
        )
        params = quant.quantize_tree(params)
        return InferenceBundle(
            model=model, params=params, model_state=model_state, config=cfg,
            input_hw=(h, w), quantized=True,
        )
    return InferenceBundle(
        model=model, params=params, model_state=model_state, config=cfg,
        input_hw=(h, w),
    )


def _check_quantized_identity(manifest, model_arch, model_widths, path):
    """A quantized file's manifest records the model identity its ints
    were produced for (tools/quantize.py); a mismatched --model /
    --model-widths would otherwise surface as an opaque flax/XLA shape
    error deep in the engine's AOT compile — the qtree is handed to the
    model raw, never bound against a template like the float path."""
    saved_arch = manifest.get("model_arch")
    if saved_arch is not None and saved_arch != model_arch:
        raise ValueError(
            f"{path} was quantized from a {saved_arch!r} checkpoint but "
            f"--model is {model_arch!r} — pass the architecture the "
            f"manifest records"
        )
    saved_widths = manifest.get("model_widths")
    got_widths = list(model_widths) if model_widths else None
    if saved_widths is not None and list(saved_widths or []) != (
        got_widths or []
    ):
        raise ValueError(
            f"{path} was quantized for model_widths={saved_widths} but "
            f"--model-widths is {got_widths} — pass the widths the "
            f"manifest records"
        )


def _restore_model_state(model, raw_state, input_hw, path):
    """BatchNorm running stats from a quantized file's raw state dict,
    restored against the model's own template (stateless models: None)."""
    if raw_state is None:
        return None
    import flax.serialization
    import jax
    import jax.numpy as jnp

    variables = model.init(
        jax.random.key(0), jnp.zeros((1, input_hw[0], input_hw[1], 3))
    )
    template = variables.get("batch_stats")
    if template is None:
        logger.warning(
            "%s carries model_state but the model family is stateless — "
            "ignored", path,
        )
        return None
    return flax.serialization.from_state_dict(template, raw_state)


def load_params_for_inference(
    checkpoint_path: str, model, input_hw: Tuple[int, int], payload=None
):
    """(params, model_state) from a native .ckpt or a reference-format .pth
    (the format dispatch lives in checkpoint.load_weights, shared with the
    trainer). ``model_state`` is the BatchNorm running stats for stateful
    models, None otherwise. ``payload`` is an already-read checkpoint
    payload (checkpoint.read_payload) — the bundle loader probes the file
    kind first and hands the bytes down instead of re-reading.

    Params are routed through the precision policy's restore seam
    (ops/precision.ensure_restored_dtypes — the ckpt-dtype-drift
    contract): a checkpoint trained under ``--dtype bf16_params`` stores
    bf16 weights, and serving promotes them to the model template's f32
    loudly, so inference numerics are identical whatever policy trained
    the checkpoint."""
    import jax
    import jax.numpy as jnp

    from distributedpytorch_tpu.ops.precision import (
        POLICIES,
        ensure_restored_dtypes,
    )

    variables = model.init(
        jax.random.key(0), jnp.zeros((1, input_hw[0], input_hw[1], 3))
    )
    template = variables["params"]
    state_template = variables.get("batch_stats")
    inference_policy = POLICIES["f32"]  # f32 param storage for serving
    if checkpoint_path.endswith(".pth"):
        if state_template is not None:
            # stateful family: milesial/Pytorch-UNet-layout .pth (the
            # public upstream checkpoints load directly)
            from distributedpytorch_tpu.checkpoint import import_milesial_pth

            params, stats = import_milesial_pth(
                checkpoint_path, template, state_template
            )
            return (
                ensure_restored_dtypes(
                    params, inference_policy, f"inference {checkpoint_path}"
                ),
                stats,
            )
        from distributedpytorch_tpu.checkpoint import load_weights

        params = load_weights(checkpoint_path, template)
        return (
            ensure_restored_dtypes(
                params, inference_policy, f"inference {checkpoint_path}"
            ),
            state_template,
        )
    from distributedpytorch_tpu.checkpoint import load_checkpoint

    restored = load_checkpoint(
        checkpoint_path, template, model_state_target=state_template,
        payload=payload,
    )
    model_state = restored["model_state"]
    if state_template is not None and model_state is None:
        logger.warning(
            "checkpoint %s has no batch_stats; using init statistics",
            checkpoint_path,
        )
        model_state = state_template
    params = ensure_restored_dtypes(
        restored["params"], inference_policy, f"inference {checkpoint_path}"
    )
    return params, model_state
