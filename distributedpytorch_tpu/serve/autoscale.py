"""Replica autoscale *hint*: the recommendation half of autoscaling.

The Prometheus gauges the serve tier already exports (PR 7: queue
depth, shed counts, throughput) contain the capacity answer; this
module reads them on a fixed cadence and publishes what to do about
it: the ``dpt_serve_replica_hint`` gauge plus one log line whenever
the recommendation changes. ACTING on the hint — growing or retiring
live replica groups against the plan-serve grid — is
``serve/scaler.py``'s job; this layer stays a pure signal so the
policy is unit-testable without devices and dashboards keep working
when the scaler is off.

Hysteresis, not thresholds: one shed burst must not flap the
recommendation. Scale-up needs ``up_windows`` consecutive windows with
shedding (or depth pinned at the high-water mark); scale-down needs
``down_windows`` consecutive *completely quiet* windows (no sheds, no
queue depth) — the asymmetry is deliberate, under-provisioning costs
users and over-provisioning costs only money.

``observe_window`` is the whole policy, a pure function of one
window's deltas — the unit tests drive it directly with fabricated
windows and never wait out a cadence (tests/test_serve_fleet.py).

This hint is the RUNTIME SHADOW of the ``plan-serve`` capacity planner
(analysis/serve_planner.py, docs/SERVING.md "Capacity planning"): the
planner answers "how many replicas for this traffic at this SLO" ahead
of time from recorded traces + profiled service times; the hint watches
the same pressure signals (shed deltas, queue depth vs the per-replica
high-water mark) live, with hysteresis instead of simulation. On an
obvious overload the two must agree on direction — pinned by
tests/test_serve_planner.py's cross-check, which runs one deterministic
scenario through BOTH and asserts the hint's scale-up matches the
plan's recommendation.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

from distributedpytorch_tpu.obs import defs as obsm

logger = logging.getLogger(__name__)


class AutoscaleHint:
    """Periodic recommendation thread over one server's gauges."""

    def __init__(
        self,
        server,
        interval_s: float = 30.0,
        up_windows: int = 2,
        down_windows: int = 6,
        depth_high: Optional[int] = None,
    ):
        self.server = server
        self.interval_s = max(0.01, float(interval_s))
        self.up_windows = max(1, int(up_windows))
        self.down_windows = max(1, int(down_windows))
        # depth at (or past) one full bucket per replica means every
        # replica has a complete dispatch waiting behind its current one
        # — sustained, that is the queue telling us it wants more devices
        self.depth_high = (
            int(depth_high) if depth_high is not None
            else server.engine.planner.max_size * server.engine.num_replicas
        )
        self.recommendation = server.engine.num_replicas
        self._up_streak = 0
        self._down_streak = 0
        self._last_shed_total = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        obsm.SERVE_REPLICA_HINT.set(self.recommendation)

    # -- the policy (pure per-window; unit-testable without threads) ---------
    def observe_window(self, shed_delta: int, max_depth: int,
                       stale: bool = False) -> int:
        """Fold one window's observations into the recommendation.

        ``stale`` closes the hint's blind spot: ``shed_delta`` and
        ``max_depth`` only describe workers that ANSWERED the last
        scrape, so a wedged worker used to read as absence of pressure
        — exactly when its siblings are absorbing its load. A stale
        window counts as pressure (a worker we cannot see is a worker
        we must assume is drowning) and can never count as quiet."""
        replicas = self.server.engine.num_replicas
        pressured = (
            bool(stale) or shed_delta > 0 or max_depth >= self.depth_high
        )
        quiet = not stale and shed_delta == 0 and max_depth == 0
        self._up_streak = self._up_streak + 1 if pressured else 0
        self._down_streak = self._down_streak + 1 if quiet else 0
        if self._up_streak >= self.up_windows:
            rec = replicas + 1
        elif self._down_streak >= self.down_windows and replicas > 1:
            rec = replicas - 1
        else:
            rec = replicas
        if rec != self.recommendation:
            logger.info(
                "serve autoscale hint: recommend %d replica(s) "
                "(serving with %d) — %s over the last window(s) "
                "(shed=%d, max_depth=%d, stale=%s, cap=%d); the hint is "
                "a signal — serve/scaler.py is the actuator",
                rec, replicas,
                "sustained pressure" if rec > replicas else "sustained idle",
                shed_delta, max_depth, bool(stale), self.depth_high,
            )
        self.recommendation = rec
        obsm.SERVE_REPLICA_HINT.set(rec)
        return rec

    # -- cadence -------------------------------------------------------------
    def start(self) -> "AutoscaleHint":
        self._last_shed_total = self._shed_total()
        self._thread = threading.Thread(
            target=self._run, name="dpt-serve-autoscale", daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def _shed_total(self) -> int:
        snap = self.server.metrics.snapshot()
        return int(snap["rejected"].get("overloaded", 0))

    def _run(self) -> None:
        # sample depth a few times within each window so a burst between
        # cadence ticks still registers as pressure
        sub = max(0.005, self.interval_s / 8.0)
        while not self._stop.is_set():
            max_depth = 0
            deadline = time.monotonic() + self.interval_s
            while time.monotonic() < deadline and not self._stop.wait(sub):
                max_depth = max(max_depth, self.server.queue.depth_images)
            if self._stop.is_set():
                return
            shed_total = self._shed_total()
            self.observe_window(shed_total - self._last_shed_total,
                                max_depth)
            self._last_shed_total = shed_total
