"""Per-request serving metrics, recorded OFF the dispatch loop.

The dispatch loop's job is to keep the devices' queues non-empty; a
metrics read that syncs a device value (or even contends a hot lock)
there shows up directly as serving latency. So accounting follows the
PR-1 async-metrics pattern: completion workers — which already block on
the device result to build the response — stamp timestamps and append a
small record under a lock; nothing in the dispatch path reads, syncs,
or aggregates. Aggregation (percentiles, rates) happens only when
someone asks (``snapshot()``: the /stats endpoint, the load generator's
report, a test).

Recording now rides the shared telemetry registry
(distributedpytorch_tpu/obs): every record call updates the process-
wide ``dpt_serve_*`` families (what ``GET /metrics`` exposes) in the
same breath as the per-instance state. The two views deliberately
differ in lifetime — ``/stats`` is *this server's* story (counters
reset with the Server object; the JSON schema is pinned byte-compatible
by tests/test_serve.py), ``/metrics`` is the *process's* story
(Prometheus counters only ever go up, across server rebuilds) — which
is exactly the cumulative contract scrapers rate() over.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Deque, Dict, List, Optional

from distributedpytorch_tpu.obs import defs as obsm


def percentile(values: List[float], q: float) -> float:
    """Nearest-rank percentile (q in [0, 100]) — NaN on empty, no numpy
    dependency so jax-free callers (the bench report path) stay
    jax-free. The rank math is the shared obs definition
    (``registry.nearest_rank``) so /stats and the profile artifact
    cannot drift."""
    from distributedpytorch_tpu.obs.registry import nearest_rank

    if not values:
        return float("nan")
    return nearest_rank(sorted(values), q)


class ServeMetrics:
    """Thread-safe accumulator of per-request serving records.

    Counters (request/image/rejection/dispatch totals) are exact for the
    server's lifetime; the per-request latency samples feeding the
    percentiles keep only the most recent ``window`` requests — a
    long-running server must not grow memory per request served, and a
    ``snapshot()`` sort under the recording lock must stay O(window), not
    O(requests-ever), or /stats polling would eventually stall the
    completion workers it shares the lock with.
    """

    def __init__(self, clock: Callable[[], float] = time.monotonic,
                 window: int = 8192):
        self.clock = clock
        self._lock = threading.Lock()
        self._latencies_s: Deque[float] = collections.deque(maxlen=window)
        # request ids aligned 1:1 with the latency window (appended
        # together under the lock): the p99 exemplar source — trace ids
        # a dashboard can jump from the latency percentile straight to
        self._latency_ids: Deque[str] = collections.deque(maxlen=window)
        self._queue_s: Deque[float] = collections.deque(maxlen=window)
        self._images_ok = 0
        self._requests_ok = 0
        self._requests_failed = 0
        self._requests_cached = 0
        self._rejections: Dict[str, int] = {}
        self._bucket_dispatches: Dict[int, int] = {}
        self._pad_rows = 0
        self._real_rows = 0
        self._started_t = clock()
        # sustained-A/B per-arm ledgers, keyed by arm ("a"/"b"); only
        # armed requests land here, so the dict stays empty — and
        # snapshot() stays un-grown — whenever no A/B is running
        self._arms: Dict[str, dict] = {}

    def _arm_state(self, arm: str) -> dict:
        state = self._arms.get(arm)
        if state is None:
            state = {
                "requests_ok": 0, "requests_failed": 0, "images_ok": 0,
                "rejected": 0,
                "latencies_s": collections.deque(
                    maxlen=self._latencies_s.maxlen
                ),
            }
            self._arms[arm] = state
        return state

    # -- recording (completion workers + submit path) ------------------------
    def record_request(
        self, n_images: int, enqueue_t: float, dispatch_t: float,
        done_t: float, request_id: str = "", arm: str = "",
    ) -> None:
        with self._lock:
            self._latencies_s.append(done_t - enqueue_t)
            self._latency_ids.append(request_id)
            self._queue_s.append(dispatch_t - enqueue_t)
            self._images_ok += n_images
            self._requests_ok += 1
            if arm:
                state = self._arm_state(arm)
                state["requests_ok"] += 1
                state["images_ok"] += n_images
                state["latencies_s"].append(done_t - enqueue_t)
        obsm.SERVE_REQUESTS.labels(status="ok").inc()
        obsm.SERVE_IMAGES.inc(n_images)
        obsm.SERVE_LATENCY.observe(done_t - enqueue_t)
        obsm.SERVE_QUEUE_SECONDS.observe(dispatch_t - enqueue_t)
        if arm:
            obsm.SERVE_AB_REQUESTS.labels(arm=arm, status="ok").inc()

    def record_failure(self, arm: str = "") -> None:
        with self._lock:
            self._requests_failed += 1
            if arm:
                self._arm_state(arm)["requests_failed"] += 1
        obsm.SERVE_REQUESTS.labels(status="failed").inc()
        if arm:
            obsm.SERVE_AB_REQUESTS.labels(arm=arm, status="failed").inc()

    def record_cached(self, n_images: int) -> None:
        """A prediction-cache hit answered without touching the queue —
        counted apart from ``requests_ok`` so hit traffic can't inflate
        the accelerator-throughput story (``imgs_per_s``)."""
        with self._lock:
            self._requests_cached += 1
        obsm.SERVE_REQUESTS.labels(status="cached").inc()

    def record_rejection(self, reason: str, arm: str = "") -> None:
        with self._lock:
            self._rejections[reason] = self._rejections.get(reason, 0) + 1
            if arm:
                self._arm_state(arm)["rejected"] += 1
        obsm.SERVE_REJECTIONS.labels(reason=reason).inc()
        if arm:
            obsm.SERVE_AB_REQUESTS.labels(arm=arm, status="rejected").inc()

    def ab_snapshot(self) -> Dict[str, dict]:
        """Per-arm aggregates for the A/B verdict (``/admin/ab``):
        latency percentiles over each arm's own window plus exact
        ok/failed/shed counters. Empty dict when nothing is armed."""
        with self._lock:
            arms = {
                arm: (dict(state), list(state["latencies_s"]))
                for arm, state in self._arms.items()
            }
        out: Dict[str, dict] = {}
        for arm, (state, lat) in sorted(arms.items()):
            out[arm] = {
                "requests_ok": state["requests_ok"],
                "requests_failed": state["requests_failed"],
                "images_ok": state["images_ok"],
                "rejected": state["rejected"],
                "p50_ms": round(percentile(lat, 50) * 1e3, 3) if lat else None,
                "p99_ms": round(percentile(lat, 99) * 1e3, 3) if lat else None,
            }
        return out

    def record_dispatch(self, bucket: int, real_rows: int) -> None:
        with self._lock:
            self._bucket_dispatches[bucket] = (
                self._bucket_dispatches.get(bucket, 0) + 1
            )
            self._real_rows += real_rows
            self._pad_rows += bucket - real_rows
        obsm.SERVE_DISPATCHES.labels(bucket=str(bucket)).inc()
        obsm.SERVE_REAL_ROWS.inc(real_rows)
        if bucket > real_rows:
            obsm.SERVE_PAD_ROWS.inc(bucket - real_rows)

    def p99_exemplars(self, limit: int = 5) -> List[str]:
        """Request ids of the latency window's p99 tail (most recent
        first, capped): the exemplar hook — a dashboard reading
        ``p99_ms`` can jump straight to the span ledgers of the
        requests that produced it (slow-request log / flight ring)."""
        with self._lock:
            pairs = list(zip(self._latencies_s, self._latency_ids))
        if not pairs:
            return []
        p99 = percentile([lat for lat, _ in pairs], 99)
        out = [rid for lat, rid in reversed(pairs) if lat >= p99 and rid]
        return out[:limit]

    # -- aggregation (pull-based; never on the dispatch path) ----------------
    def snapshot(self, elapsed_s: Optional[float] = None) -> dict:
        with self._lock:
            lat = list(self._latencies_s)
            qs = list(self._queue_s)
            elapsed = (
                float(elapsed_s) if elapsed_s is not None
                else max(1e-9, self.clock() - self._started_t)
            )
            dispatched = self._real_rows + self._pad_rows
            return {
                "requests_ok": self._requests_ok,
                "requests_failed": self._requests_failed,
                "requests_cached": self._requests_cached,
                "rejected": dict(self._rejections),
                "rejected_total": sum(self._rejections.values()),
                "images_ok": self._images_ok,
                "elapsed_s": round(elapsed, 4),
                "imgs_per_s": round(self._images_ok / elapsed, 3),
                "p50_ms": round(percentile(lat, 50) * 1e3, 3) if lat else None,
                "p99_ms": round(percentile(lat, 99) * 1e3, 3) if lat else None,
                "queue_p50_ms": (
                    round(percentile(qs, 50) * 1e3, 3) if qs else None
                ),
                "bucket_dispatches": {
                    str(k): v
                    for k, v in sorted(self._bucket_dispatches.items())
                },
                "pad_ratio": (
                    round(self._pad_rows / dispatched, 4) if dispatched else 0.0
                ),
            }
