"""Pallas TPU kernel for the 3×3 conv weight gradient (the 9-tap
tall contraction).

VERDICT r04 weak-3: the conv hot path's one genuinely Pallas-shaped
opportunity is the weight gradient — a tall contraction

    dW[ky,kx,ci,co] = Σ_{b,y,x} Xpad[b, y+ky, x+kx, ci] · dY[b, y, x, co]

with K = B·H·W ≈ 614k for the hot s2d shape (128→128 @ 320×480, batch 4).
The einsum formulation (ops/conv_backward.py) issues 9 independent
matmuls, each streaming a full shifted view of X and all of dY from HBM:
~9× the minimum input traffic for what is, at these C's, a
bandwidth-bound reduction. This kernel makes one pass: each grid step
loads one image row of Xpad (three row-offset views) and of dY (three
column-shift paddings) into VMEM ONCE and accumulates all nine taps from
it — ~3×+3× total traffic instead of 9×+9×.

Why three shifted OPERANDS instead of in-kernel slicing: the kx shift is
along the sublane dimension, and sublane slices at offsets 1 and 2 are
unaligned (f32 tiles are 8×128) — Mosaic may reject or silently relayout
them. Shifting dY *outside* the kernel turns every in-kernel operand into
a full (W+2, C) tile at offset 0, with the identity

    Σ_x Xpad[y+ky, x+kx]·dY[x]  =  Σ_u Xpad[y+ky, u]·dYpad_kx[u],
    dYpad_kx = dY padded with kx zeros left, 2−kx right.

The row (ky) offsets cost nothing: three BlockSpecs on the same Xpad
array whose index_map starts one block (= one row) apart.

Accumulation: the (3,3,Cin,Cout) f32 output block maps to the same block
at every grid step, so it stays VMEM-resident across the sequential grid
("arbitrary" dimension semantics) — the standard Pallas accumulator
pattern; taps accumulate in f32 regardless of input dtype (same contract
as XLA's bf16 conv backward and the einsum path).

Status: exactness-proven vs `jax.grad` of the plain conv in interpret
mode (tests/test_wgrad_pallas.py); real-TPU lowering and the A/B against
the einsum path are part of the chip-gated measurement program
(`tools/bench_wgrad.py --backend pallas`). Selected at trace time via
``DPT_WGRAD_BACKEND=pallas`` (ops/conv_backward.py); einsum remains the
default until the on-chip number exists.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# The tap accumulator spells the WGRAD_DTYPE contract (ops/precision.py):
# weight-gradient accumulation is f32 under every --dtype policy — the
# dptlint ``dtype-policy`` rule reaches kernel bodies, and the named
# constant is its sanctioned spelling (this module is no longer exempt).
from distributedpytorch_tpu.ops.precision import WGRAD_DTYPE

try:  # TPU-specific memory space; absent on some CPU-only installs
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except ImportError:  # pragma: no cover
    pltpu = None
    _VMEM = None


def _auto_interpret() -> bool:
    """Real Mosaic lowering on TPU; the Pallas interpreter elsewhere."""
    return jax.devices()[0].platform != "tpu"


def _wgrad_kernel(x0, x1, x2, d0, d1, d2, out_ref):
    """One grid step = one (batch, row): nine (Cin, W+2) × (W+2, Cout)
    tap contractions from VMEM-resident tiles into the f32 accumulator."""

    @pl.when((pl.program_id(0) == 0) & (pl.program_id(1) == 0))
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    xrows = (x0, x1, x2)
    dpads = (d0, d1, d2)
    for ky in range(3):
        xrow = xrows[ky][0, 0]  # (W+2, Cin)
        for kx in range(3):
            dpad = dpads[kx][0, 0]  # (W+2, Cout)
            out_ref[ky, kx] += jax.lax.dot_general(
                xrow,
                dpad,
                (((0,), (0,)), ((), ())),
                preferred_element_type=WGRAD_DTYPE,
            )


def wgrad_9tap_pallas(
    x: jax.Array, dy: jax.Array, interpret: Optional[bool] = None
) -> jax.Array:
    """Weight gradient of a SAME stride-1 3×3 NHWC conv: returns
    dW (3, 3, Cin, Cout) in float32 (callers cast to the kernel dtype)."""
    if interpret is None:
        interpret = _auto_interpret()
    b, h, w, cin = x.shape
    cout = dy.shape[-1]
    xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))  # (B, H+2, W+2, Cin)
    # dYpad_kx[u] = dY[u − kx]: kx zeros left, 2−kx right → width W+2
    dps = [
        jnp.pad(dy, ((0, 0), (0, 0), (kx, 2 - kx), (0, 0)))
        for kx in range(3)
    ]

    in_space = _VMEM if (not interpret and _VMEM is not None) else None

    def spec(block, index_map):
        if in_space is None:
            return pl.BlockSpec(block, index_map)
        return pl.BlockSpec(block, index_map, memory_space=in_space)

    x_specs = [
        spec((1, 1, w + 2, cin), lambda bi, yi, _d=d: (bi, yi + _d, 0, 0))
        for d in range(3)
    ]
    d_specs = [
        spec((1, 1, w + 2, cout), lambda bi, yi: (bi, yi, 0, 0))
        for _ in range(3)
    ]
    out_spec = spec((3, 3, cin, cout), lambda bi, yi: (0, 0, 0, 0))

    kwargs = {}
    if not interpret and pltpu is not None:
        # sequential grid: the output block accumulates across steps
        kwargs["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")
        )
    return pl.pallas_call(
        _wgrad_kernel,
        grid=(b, h),
        in_specs=x_specs + d_specs,
        out_specs=out_spec,
        out_shape=jax.ShapeDtypeStruct((3, 3, cin, cout), WGRAD_DTYPE),
        interpret=interpret,
        **kwargs,
    )(xp, xp, xp, *dps)
