"""Weights-only int8 post-training quantization for the serving tier.

The serving follow-on of the mixed-precision policy (ops/precision.py,
ROADMAP "int8 weights-only first"): kernels are stored as int8 with
per-OUTPUT-CHANNEL symmetric scales and dequantized *inside* the traced
forward, so the device-resident weights really are one byte per element
— param bytes quartered vs f32 (halved vs bf16), which on a serving host
is replicated per replica group — while every matmul/conv still computes
in the model's compute dtype. Weights-only deliberately: activations at
this model's scale are a small fraction of serve-time memory, and
skipping activation quantization keeps the scheme calibration-free (no
representative-batch pass, no clipping heuristics).

Scheme (the standard symmetric per-channel recipe):

    scale[c] = max(|W[..., c]|) / 127          (scale 1 for all-zero c)
    Q[..., c] = round(W[..., c] / scale[c])    ∈ [-127, 127], int8
    W'[..., c] = Q[..., c] · scale[c]          (inside the traced forward)

Quantized leaves are kernels only (``ndim >= 2``; flax puts out-features
on the LAST axis for Conv AND ConvTranspose). Biases, BatchNorm
scale/bias, and all running statistics stay f32 — they are vectors whose
bytes are noise and whose precision is not.

File format (``tools/quantize.py`` writes, :func:`load_quantized`
reads): one msgpack payload through checkpoint.py's integrity-footer
writer, carrying ``kind`` = :data:`QUANT_KIND`, a ``manifest`` that
records the SOURCE checkpoint path + sha256 (provenance: which float
weights produced these ints), the quantization scheme name, and the
model-identity fields, plus the quantized params and the unquantized
``model_state``. ``serve --quantize int8`` consumes either this file or
a regular checkpoint (quantized on load); Dice parity vs the float
checkpoint is pinned by tests/test_quantize.py.
"""

from __future__ import annotations

import hashlib
import logging
import os
from typing import Any, Dict, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)

QUANT_KIND = "int8-weights-v1"
SCHEME = "symmetric-per-out-channel"
_QLEAF_KEYS = frozenset({"q", "scale"})


def _is_quantized_leaf(node) -> bool:
    return isinstance(node, dict) and set(node.keys()) == _QLEAF_KEYS


def quantize_leaf(w: np.ndarray) -> Dict[str, np.ndarray]:
    """One float kernel → ``{"q": int8, "scale": f32}`` with the scale
    broadcastable over the last (out-channel) axis."""
    w = np.asarray(w, np.float32)
    reduce_axes = tuple(range(w.ndim - 1))
    amax = np.max(np.abs(w), axis=reduce_axes, keepdims=True)
    scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.rint(w / scale), -127, 127).astype(np.int8)
    return {"q": q, "scale": scale}


def quantize_tree(params) -> Any:
    """Quantize every kernel-shaped float leaf of a params tree; vectors
    and scalars (biases, BN affine) pass through as f32."""
    import jax

    def quantize(leaf):
        arr = np.asarray(jax.device_get(leaf))
        if arr.ndim >= 2 and np.issubdtype(arr.dtype, np.floating):
            return quantize_leaf(arr)
        if np.issubdtype(arr.dtype, np.floating):
            return arr.astype(np.float32)
        return arr

    def walk(node):
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        return quantize(node)

    import flax.serialization

    return walk(flax.serialization.to_state_dict(params))


def dequantize_tree(tree, dtype=None):
    """The traced-side inverse: ``{"q","scale"}`` subtrees → float
    kernels (``q · scale``, computed in f32 then cast to ``dtype`` when
    given). Pure jnp over a static tree structure, so it lowers into the
    AOT-compiled serve executables — the int8 arrays are the executable's
    *arguments*, the dequantized floats only ever exist as temps."""
    import jax.numpy as jnp

    def walk(node):
        if _is_quantized_leaf(node):
            w = node["q"].astype(jnp.float32) * node["scale"]
            return w.astype(dtype) if dtype is not None else w
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        return node

    return walk(tree)


def is_quantized_tree(tree) -> bool:
    """True iff any subtree is a ``{"q","scale"}`` quantized leaf."""
    if _is_quantized_leaf(tree):
        return True
    if isinstance(tree, dict):
        return any(is_quantized_tree(v) for v in tree.values())
    return False


def quantization_error(params, qtree) -> float:
    """max |W − W'| over the quantized kernels, as a fraction of each
    channel's scale (≤ 0.5 by construction — the rounding bound the
    roundtrip test pins)."""
    import flax.serialization

    flat: list = []

    def walk(node, qnode):
        if _is_quantized_leaf(qnode):
            w = np.asarray(node, np.float32)
            wq = qnode["q"].astype(np.float32) * qnode["scale"]
            flat.append(np.max(np.abs(w - wq) / qnode["scale"]))
        elif isinstance(qnode, dict):
            for k in qnode:
                walk(node[k], qnode[k])

    walk(flax.serialization.to_state_dict(params), qtree)
    return float(max(flat)) if flat else 0.0


# ---------------------------------------------------------------------------
# File format
# ---------------------------------------------------------------------------


def file_sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def save_quantized(
    path: str,
    qtree,
    manifest: Dict[str, Any],
    model_state=None,
) -> str:
    """Write a quantized-weights file (atomic, integrity-footed — the
    same writer the native checkpoints use). ``manifest`` should carry
    ``source``/``source_sha256`` (tools/quantize.py fills them)."""
    import flax.serialization

    from distributedpytorch_tpu.checkpoint import _to_host, _write_payload

    payload = {
        "kind": QUANT_KIND,
        "manifest": {"scheme": SCHEME, **manifest},
        "params": qtree,
        "model_state": flax.serialization.to_state_dict(_to_host(model_state))
        if model_state is not None
        else None,
    }
    return _write_payload(path, payload, keep=1)


def peek_quantized(path: str) -> Optional[Dict[str, Any]]:
    """The manifest iff ``path`` is a quantized-weights file, else None
    (including files that are not valid msgpack — the caller is probing,
    not asserting)."""
    if not os.path.isfile(path):
        return None
    try:
        from distributedpytorch_tpu.checkpoint import _read_verified

        payload = _read_verified(path)
    except Exception:  # noqa: BLE001 — a probe, not a load
        return None
    if not isinstance(payload, dict) or payload.get("kind") != QUANT_KIND:
        return None
    return dict(payload.get("manifest") or {})


def load_quantized(
    path: str, payload: Optional[Dict[str, Any]] = None
) -> Tuple[Any, Any, Dict[str, Any]]:
    """Read a quantized-weights file → ``(qtree, model_state, manifest)``.
    Integrity-verified by the shared reader; raises ValueError on a file
    of the wrong kind (a regular checkpoint handed to the int8 loader).
    ``payload`` short-circuits the file read — a caller that already ran
    ``checkpoint.read_payload`` (the serve loader probes the kind first)
    must not deserialize the file twice."""
    if payload is None:
        from distributedpytorch_tpu.checkpoint import _read_verified

        payload = _read_verified(path)
    if payload.get("kind") != QUANT_KIND:
        raise ValueError(
            f"{path} is not an int8 weights file (kind="
            f"{payload.get('kind')!r}); quantize it first with "
            f"tools/quantize.py or drop --quantize"
        )
    manifest = dict(payload.get("manifest") or {})
    logger.info(
        "loaded int8 weights %s (source %s, sha256 %.12s…)",
        path, manifest.get("source"), manifest.get("source_sha256", ""),
    )
    return payload["params"], payload.get("model_state"), manifest
