"""The mixed-precision policy layer: one object owns every cast boundary.

The recipe is Micikevicius et al.'s mixed-precision training (ICLR 2018)
specialized to TPU bf16: low-precision *compute* where the MXU pays
(convolutions, activations), full-precision *state* where rounding
compounds (master weights, loss, reductions). Before this module the
pieces existed as conventions scattered across the codebase — bf16 conv
compute via the model ``dtype``, f32 params by init default, f32 loss by
``astype`` calls in ops/losses.py, f32 wgrad accumulation hand-written
into the 1F1B schedule. A convention cannot be selected, checkpointed,
or linted; a policy object can.

``--dtype`` (``TrainConfig.dtype``) selects one of three policies:

=============  ========  ==========  ==============  =====================
policy         compute   params      master weights  what it is
=============  ========  ==========  ==============  =====================
``f32``        float32   float32     —               the pure-f32 reference
                                                     every equivalence band
                                                     is measured against
``bf16``       bfloat16  float32     —               today's shipping
                                                     default made explicit:
                                                     MXU conv compute in
                                                     bf16, f32 params/loss
``bf16_params`` bfloat16  bfloat16   f32 in opt      halved on-device param
                                                     bytes (and FSDP
                                                     all-gather traffic);
                                                     Adam runs on an f32
                                                     master copy living in
                                                     optimizer state
=============  ========  ==========  ==============  =====================

Invariant under EVERY policy — the three stated f32 contracts, named as
constants so traced code spells the *policy seam*, not a bare dtype
literal (the ``dtype-policy`` dptlint rule flags bare ``jnp.float32`` in
traced functions; these names are the sanctioned spelling):

* ``LOSS_DTYPE``   — loss and Dice/BCE statistics accumulate in f32
  (ops/losses.py casts at entry; a bf16 log-loss near saturation is
  garbage — see losses._clamped_log);
* ``WGRAD_DTYPE``  — weight-gradient accumulation is f32: the 1F1B
  schedule's per-microbatch accumulator (parallel/pipeline.py), the
  grad-accumulation scan (train/steps.make_accum_train_step), and the
  master-weight wrapper's cast at the optimizer boundary;
* ``REDUCE_DTYPE`` — the schedule-closing grad psum and the loss-stats
  psum operate on f32 trees (a contract extended from the PR-4
  pipeline, now stated once here).

Master weights (``bf16_params``): :func:`with_master_weights` wraps the
optax chain so ``opt_state`` carries an f32 master copy; each update
casts incoming grads to ``WGRAD_DTYPE``, runs Adam against the master,
and emits the delta that lands the bf16 on-device params exactly on the
rounded master. The plateau scheduler's lr passthrough keeps working:
:class:`MasterWeightsState` forwards ``.hyperparams`` to the wrapped
inject_hyperparams state.

Checkpoints record the saving policy in the manifest (``topology
["precision"]``); :func:`convert_checkpoint_state` converts between
policies at restore EXACTLY (bf16_params → f32 promotes the f32 master
to the params; f32 → bf16_params seeds the master from the saved f32
params), and :func:`ensure_restored_dtypes` is the loud re-cast seam
every restore path must route through (the ``ckpt-dtype-drift`` dptlint
rule flags restores that bypass it — a silently drifted dtype retraces
the jitted step against donated buffers of the wrong layout).
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

logger = logging.getLogger(__name__)

# -- the stated f32 contracts (sanctioned spellings for traced code) --------
LOSS_DTYPE = jnp.float32    # loss + Dice/BCE stats accumulation
WGRAD_DTYPE = jnp.float32   # weight-grad accumulation (pipeline, accum, master)
REDUCE_DTYPE = jnp.float32  # cross-device grad/stats psums
# BatchNorm statistics + normalization math (models/milesial.py: variance
# in bf16 is numerically unsafe, so BN computes f32 and casts back under
# every policy). Named here so the fused conv-epilogue kernel
# (ops/kernels.py) spells the same contract the XLA BN path implements.
NORM_DTYPE = jnp.float32


def _is_float_leaf(x) -> bool:
    dt = getattr(x, "dtype", None)
    return dt is not None and jnp.issubdtype(dt, jnp.floating)


def cast_float_leaves(tree, dtype):
    """Cast every floating leaf of ``tree`` to ``dtype``; integer leaves
    (step counters, int8 quantized weights) pass through. THE one
    cast-a-tree definition — every policy boundary in this module (and
    the pipeline's gpipe widening) goes through it, so a change to what
    counts as castable cannot drift between boundaries."""
    return jax.tree.map(
        lambda x: x.astype(dtype) if _is_float_leaf(x) else x, tree
    )


@dataclasses.dataclass(frozen=True)
class PrecisionPolicy:
    """One precision policy: which dtype computes, which dtype stores
    params on device, and whether an f32 master copy lives in optimizer
    state. Frozen — strategies, steps, and checkpoints all read the same
    object, so a cast boundary cannot drift between layers."""

    name: str
    compute: str         # conv/activation compute dtype (the model dtype)
    params: str          # on-device param storage dtype
    master_weights: bool  # f32 master copy in optimizer state

    @property
    def compute_dtype(self):
        return jnp.dtype(self.compute)

    @property
    def param_dtype(self):
        return jnp.dtype(self.params)

    # -- cast boundaries ----------------------------------------------------
    def cast_params(self, params):
        """Param cast-in at state construction/restore: float leaves to the
        policy's on-device storage dtype (integer leaves — step counters —
        pass through)."""
        return cast_float_leaves(params, self.param_dtype)

    def cast_grads(self, grads):
        """The optimizer-boundary wgrad contract: under a master-weight
        policy, gradients leave the backward in the param (bf16) dtype and
        must be stated f32 BEFORE any scaling or accumulation touches
        them. No-op when params are already f32."""
        if not self.master_weights:
            return grads
        return cast_float_leaves(grads, WGRAD_DTYPE)

    def wrap_optimizer(self, tx: optax.GradientTransformation):
        """Master-weight policies interpose :func:`with_master_weights`;
        the others return ``tx`` unchanged."""
        if not self.master_weights:
            return tx
        return with_master_weights(tx)


POLICIES = {
    "f32": PrecisionPolicy("f32", "float32", "float32", False),
    "bf16": PrecisionPolicy("bf16", "bfloat16", "float32", False),
    "bf16_params": PrecisionPolicy("bf16_params", "bfloat16", "bfloat16", True),
}


def get_policy(config_or_name=None) -> PrecisionPolicy:
    """Resolve the session's policy.

    Accepts a policy name, ``None`` (→ the ``bf16`` default), or a
    TrainConfig — in which case the legacy ``compute_dtype`` override is
    honored: the test/bench idiom ``TrainConfig(compute_dtype="float32")``
    keeps meaning "f32 conv compute, f32 params" exactly as it did before
    the policy layer existed (param storage and master-weight behavior
    still follow ``dtype``)."""
    if config_or_name is None:
        return POLICIES["bf16"]
    if isinstance(config_or_name, str):
        return _by_name(config_or_name)
    name = getattr(config_or_name, "dtype", None) or "bf16"
    policy = _by_name(name)
    override = getattr(config_or_name, "compute_dtype", None)
    if override is not None and jnp.dtype(override) != policy.compute_dtype:
        policy = dataclasses.replace(
            policy, compute=jnp.dtype(override).name
        )
    return policy


def _by_name(name: str) -> PrecisionPolicy:
    try:
        return POLICIES[name]
    except KeyError:
        raise ValueError(
            f"unknown precision policy {name!r}; expected one of "
            f"{sorted(POLICIES)}"
        ) from None


# ---------------------------------------------------------------------------
# f32 master weights in optimizer state (the bf16_params policy)
# ---------------------------------------------------------------------------


class MasterWeightsState(NamedTuple):
    """Optimizer state of :func:`with_master_weights`: the f32 master
    params plus the wrapped transformation's own state (over the master).
    A NamedTuple so it is a jax pytree and flax-msgpack-serializable —
    master weights ride in every checkpoint's ``opt_state`` untouched.
    ``hyperparams`` forwards to the wrapped inject_hyperparams state so
    the plateau scheduler's lr rewrite (ops/optim.set_learning_rate)
    works identically under every policy."""

    master: Any
    inner: Any

    @property
    def hyperparams(self):
        return self.inner.hyperparams


def with_master_weights(
    tx: optax.GradientTransformation,
) -> optax.GradientTransformation:
    """Run ``tx`` against an f32 master copy of the params.

    ``init`` promotes the (bf16) params to the f32 master and initializes
    ``tx`` over it — Adam's m/v therefore live in f32, mirroring master
    shapes. ``update`` casts incoming grads to ``WGRAD_DTYPE`` (the
    stated contract), steps the master, and returns the f32 delta whose
    ``optax.apply_updates`` application lands the on-device params
    exactly on the master rounded to their storage dtype (the add
    promotes to f32, so no second rounding accumulates)."""

    def init(params):
        master = cast_float_leaves(params, WGRAD_DTYPE)
        return MasterWeightsState(master=master, inner=tx.init(master))

    def update(updates, state, params=None):
        if params is None:
            raise ValueError(
                "with_master_weights requires params (the on-device "
                "low-precision copy) at every update"
            )
        grads32 = cast_float_leaves(updates, WGRAD_DTYPE)
        inner_updates, inner_state = tx.update(
            grads32, state.inner, state.master
        )
        master = optax.apply_updates(state.master, inner_updates)

        def delta(m, p):
            if not _is_float_leaf(p):
                return jnp.zeros_like(p)
            # target = master rounded to the storage dtype; emit it as an
            # f32 delta so apply_updates' promoted add reconstructs the
            # target without compounding a second rounding
            target = m.astype(p.dtype).astype(WGRAD_DTYPE)
            return target - p.astype(WGRAD_DTYPE)

        return (
            jax.tree.map(delta, master, params),
            MasterWeightsState(master=master, inner=inner_state),
        )

    return optax.GradientTransformation(init, update)


def unwrap_opt_state(opt_state):
    """The inject_hyperparams-bearing inner state regardless of policy —
    ops/optim's lr read/write goes through here."""
    if isinstance(opt_state, MasterWeightsState):
        return opt_state.inner
    return opt_state


# ---------------------------------------------------------------------------
# Restore-side seams (the ckpt-dtype-drift contract)
# ---------------------------------------------------------------------------


def ensure_restored_dtypes(tree, policy: PrecisionPolicy, where: str):
    """Loudly re-cast a restored float tree to the session policy's param
    dtype. The sanctioned restore seam: every ``load_checkpoint`` /
    ``load_weights`` consumer routes its params through here (or through
    :func:`convert_checkpoint_state`), so a checkpoint whose dtype drifted
    from the session policy re-casts with a log line instead of silently
    retracing the donated-buffer step executable against a layout the
    trainer never asked for."""
    dt = policy.param_dtype
    drifted = [
        getattr(x, "dtype", None)
        for x in jax.tree.leaves(tree)
        if _is_float_leaf(x) and x.dtype != dt
    ]
    if not drifted:
        return tree
    logger.warning(
        "%s: restored %d float leaves with dtype(s) %s under policy %r — "
        "re-cast to %s via the precision policy (a checkpoint saved under "
        "a different --dtype)",
        where, len(drifted), sorted({str(d) for d in drifted}), policy.name,
        dt.name,
    )
    return cast_float_leaves(tree, dt)


def convert_checkpoint_state(
    saved: PrecisionPolicy,
    current: PrecisionPolicy,
    params,
    opt_state,
    where: str = "restore",
):
    """Convert a restored (params, opt_state) pair between policies.

    The conversions are EXACT where exactness is possible:

    * master → no-master: the f32 master IS the full-precision truth;
      it becomes the params (cast to the current storage dtype — a no-op
      for f32) and the wrapped inner state becomes the opt_state.
    * no-master → master: the saved f32 params seed the master
      bit-identically; the saved Adam state (already over f32 params of
      the same shapes) becomes the inner state.
    * storage-dtype-only changes re-cast params; Adam state is f32 under
      every policy and passes through.

    Returns ``(params, opt_state)`` under the CURRENT policy. ``opt_state``
    may be None (weights-only restores) and passes through as None.
    """
    if saved.master_weights == current.master_weights:
        out_params = ensure_restored_dtypes(params, current, where)
        return out_params, opt_state
    if opt_state is None:
        return ensure_restored_dtypes(params, current, where), None
    if saved.master_weights and not current.master_weights:
        logger.warning(
            "%s: checkpoint saved under %r, restoring under %r — promoting "
            "the f32 master weights to the params (exact) and unwrapping "
            "the optimizer state",
            where, saved.name, current.name,
        )
        master = opt_state.master
        return current.cast_params(master), opt_state.inner
    logger.warning(
        "%s: checkpoint saved under %r, restoring under %r — seeding the "
        "f32 master from the saved params (exact) and wrapping the "
        "optimizer state",
        where, saved.name, current.name,
    )
    return (
        current.cast_params(params),
        MasterWeightsState(
            master=cast_float_leaves(params, WGRAD_DTYPE), inner=opt_state
        ),
    )


def param_bytes(tree) -> int:
    """Total bytes of a tree's array leaves — the policy table's memory
    claims (bf16 halves, int8 quarters) measured directly."""
    return sum(
        int(x.size) * jnp.dtype(x.dtype).itemsize
        for x in jax.tree.leaves(tree)
        if hasattr(x, "dtype")
    )
