"""The kernel-engagement policy layer: one object owns every Pallas
fast-path decision, the way ``ops/precision.py`` owns every cast
boundary.

Before this module the kernel tier was scattered conventions: an
eval-only stats kernel (ops/pallas_kernels.py) behind ``use_pallas``, a
differentiable fused loss (ops/fused_loss.py) enabled per-strategy, a
wgrad kernel behind a trace-time env var (ops/conv_backward.py), and
nothing the planner could see. A convention cannot be selected, probed,
or searched; a policy object can.

``--kernels`` (``TrainConfig.kernels``) selects one of two policies:

=========  =================================================================
policy     what engages
=========  =================================================================
``xla``    nothing — every output is BIT-IDENTICAL to the historical
           paths (the correctness reference every kernel is pinned
           against)
``pallas`` every engagement site below, each individually revocable by
           the per-chip Mosaic probe priors (``apply_priors``)
=========  =================================================================

Engagement sites (the full table lives in docs/PERFORMANCE.md
"Kernels"):

* ``train_loss_fused`` — the training loss statistics through the fused
  one-pass kernel + analytic VJP (ops/fused_loss.py; plain steps, the
  grad-accum scan, and both pipeline schedules);
* ``eval_stats_fused`` — eval loss+Dice from the one-pass stats kernel
  (ops/pallas_kernels.py; unsharded eval batches only, as before);
* ``conv_epilogue``    — the NEW fused DoubleConv epilogue below
  (:func:`fused_bn_act`): BN-normalize + ReLU in one VMEM pass after
  the XLA conv, with a hand-written elementwise VJP so it rides the
  training path (models/milesial.py ``DoubleConv``). XLA keeps the conv
  itself — its conv lowering owns the MXU (pallas_kernels.py design
  note); what Pallas buys is the elementwise tail that XLA schedules as
  separate normalize/activation fusions over HBM;
* ``serve_mask``       — the NEW fused sigmoid/threshold mask kernel
  (:func:`sigmoid_threshold_mask`): probabilities → ``{0,255} uint8``
  masks INSIDE the serve tier's AOT bucket executables
  (serve/infer.make_forward), so the D2H transfer carries 1 byte/pixel
  instead of 4 and the host threshold pass disappears;
* ``wgrad_pallas``     — the existing single-pass 9-tap weight-gradient
  kernel (ops/wgrad_pallas.py): surfaces the decision here; the
  trace-time selection stays ``DPT_WGRAD_BACKEND`` (the bench lever)
  because the taps path itself is still an A/B, not a default.

**Mosaic probe priors.** Every kernel has a compile-only probe
(``PROBES`` — the ``wgrad_pallas_probe`` pattern generalized): lower +
compile at a representative shape, record accepted-or-rejected with the
Mosaic reason, ZERO execution. ``tools/probe_kernels.py`` runs the
registry on a chip window and writes a per-chip priors file;
``apply_priors`` turns rejected kernels off in the resolved policy
(bit-identical fallback), and ``analysis/planner.py --kernel-priors``
consumes the same file as a search axis — ``plan`` rejects
Mosaic-rejected kernel points with zero device time and ranks kernel-on
vs kernel-off configs.

The legacy ``TrainConfig.use_pallas`` flag resolves here as a LOUD
backward-compat alias (like ``compute_dtype`` → ``--dtype``): it maps to
exactly its historical engagement set (fused training loss + eval
stats), never the new kernels.
"""

from __future__ import annotations

import dataclasses
import functools
import json
import logging
import os
import time
from typing import Callable, Dict, Mapping, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from distributedpytorch_tpu.ops.precision import (
    LOSS_DTYPE,
    NORM_DTYPE,
    WGRAD_DTYPE,
)

try:  # TPU-specific memory spaces; absent on some CPU-only installs
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except ImportError:  # pragma: no cover
    pltpu = None
    _VMEM = None

logger = logging.getLogger(__name__)

LANES = 128  # TPU vector lane width (pallas_kernels.py contract)
#: Rows per grid step of the elementwise kernels: a (512, C) f32 tile is
#: 256 KB at C=128 and 2 MB at the deepest milesial width (C=1024) —
#: comfortably VMEM-resident with in+out+params live.
BLOCK_ROWS = 512


def _auto_interpret() -> bool:
    """Real Mosaic lowering on TPU; the Pallas interpreter elsewhere
    (CPU test meshes, GPU). One place decides — callers pass
    interpret=None."""
    return jax.devices()[0].platform != "tpu"


# ---------------------------------------------------------------------------
# The policy object
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class KernelPolicy:
    """One kernel-engagement policy: which Pallas fast paths trace into
    the step/serve executables. Frozen — strategies, the model factory,
    the serve engine, and the planner all read the same object, so an
    engagement decision cannot drift between layers."""

    name: str
    train_loss_fused: bool   # ops/fused_loss.py on the training path
    eval_stats_fused: bool   # ops/pallas_kernels.py on the eval path
    conv_epilogue: bool      # fused_bn_act in milesial DoubleConv
    serve_mask: bool         # sigmoid_threshold_mask in the AOT serve fwd
    wgrad_pallas: bool       # ops/wgrad_pallas.py allowed on the taps path

    def any_engaged(self) -> bool:
        return any(
            (self.train_loss_fused, self.eval_stats_fused,
             self.conv_epilogue, self.serve_mask, self.wgrad_pallas)
        )


KERNEL_POLICIES: Dict[str, KernelPolicy] = {
    "xla": KernelPolicy("xla", False, False, False, False, False),
    "pallas": KernelPolicy("pallas", True, True, True, True, True),
}

#: Probe-registry kernel name → the policy field(s) it gates: a priors
#: file marking a kernel Mosaic-rejected turns exactly these engagement
#: sites off (``apply_priors``).
KERNEL_GATES: Dict[str, Tuple[str, ...]] = {
    "fused_loss": ("train_loss_fused",),
    "eval_stats": ("eval_stats_fused",),
    "conv_epilogue": ("conv_epilogue",),
    "serve_mask": ("serve_mask",),
    "wgrad_9tap": ("wgrad_pallas",),
}


def get_kernel_policy(
    config_or_name=None, priors: Optional[Mapping] = None
) -> KernelPolicy:
    """Resolve the session's kernel policy.

    Accepts a policy name, ``None`` (→ ``xla``), an already-resolved
    :class:`KernelPolicy` (passes through), or a TrainConfig/ServeConfig
    — in which case the legacy ``use_pallas`` flag is honored as a loud
    backward-compat alias mapping to its HISTORICAL engagement set
    (fused training loss + eval stats, nothing new). An explicit
    ``kernels="pallas"`` supersedes the alias.

    ``priors`` (or the config's ``kernel_priors`` path / the
    ``DPT_KERNEL_PRIORS`` env var) applies the per-chip Mosaic probe
    verdicts: rejected kernels disengage, loudly."""
    if isinstance(config_or_name, KernelPolicy):
        policy = config_or_name
    elif config_or_name is None:
        policy = KERNEL_POLICIES["xla"]
    elif isinstance(config_or_name, str):
        policy = _by_name(config_or_name)
        if priors is None:
            # name-based resolution (the serve engine, bench cells)
            # still honors the session's probe verdicts
            policy = apply_priors(policy, _env_priors() or {})
    else:
        name = getattr(config_or_name, "kernels", None) or "xla"
        policy = _by_name(name)
        if policy.name == "xla" and getattr(config_or_name, "use_pallas", False):
            logger.warning(
                "use_pallas is a legacy alias — resolving to the fused "
                "loss/eval-stats kernels it always meant; prefer "
                "--kernels pallas (ops/kernels.py), which also engages "
                "the conv-epilogue and serve-mask kernels"
            )
            policy = dataclasses.replace(
                policy, name="pallas_loss", train_loss_fused=True,
                eval_stats_fused=True,
            )
        if priors is None:
            priors = _config_priors(config_or_name)
    if priors is not None:
        policy = apply_priors(policy, priors)
    return policy


def _by_name(name: str) -> KernelPolicy:
    try:
        return KERNEL_POLICIES[name]
    except KeyError:
        raise ValueError(
            f"unknown kernel policy {name!r}; expected one of "
            f"{sorted(KERNEL_POLICIES)}"
        ) from None


def _env_priors() -> Optional[dict]:
    path = os.environ.get("DPT_KERNEL_PRIORS")
    return load_priors(path) if path else None


def _config_priors(config) -> Optional[dict]:
    path = getattr(config, "kernel_priors", None)
    if not path:
        return _env_priors()
    return load_priors(path)


#: (policy name, kernel, field) verdicts already warned about — see the
#: once-per-verdict note inside :func:`apply_priors`.
_WARNED_REJECTIONS: set = set()


def apply_priors(policy: KernelPolicy, priors: Mapping) -> KernelPolicy:
    """Disengage every kernel the priors file marks Mosaic-rejected.
    A kernel absent from the file stays as the policy says (unprobed ≠
    rejected). Returns the (possibly narrowed) policy."""
    kernels = priors.get("kernels") if isinstance(priors, Mapping) else None
    if not isinstance(kernels, Mapping):
        return policy
    changes: Dict[str, bool] = {}
    for kernel, row in kernels.items():
        if not isinstance(row, Mapping) or row.get("accepted", True):
            continue
        for field in KERNEL_GATES.get(kernel, ()):
            if getattr(policy, field, False):
                changes[field] = False
                # the policy re-resolves per layer (strategy, model
                # factory, serve engine) — warn ONCE per verdict so one
                # rejection doesn't read as several in the log
                mark = (policy.name, kernel, field)
                if mark not in _WARNED_REJECTIONS:
                    _WARNED_REJECTIONS.add(mark)
                    logger.warning(
                        "kernel policy %r: Mosaic rejected %s on this "
                        "chip (%s) — %s disengaged, XLA path "
                        "(bit-identical reference) kept",
                        policy.name, kernel,
                        row.get("reason", "no reason recorded"), field,
                    )
    if not changes:
        return policy
    return dataclasses.replace(policy, **changes)


def conv_epilogue_engaged(config) -> bool:
    """Whether the model factory should build milesial's DoubleConv with
    the fused epilogue: the policy must ask for it AND the strategy's
    forward must be device-local — single device, or the shard_map
    pipeline schedules (stage fns see plain local arrays). GSPMD-sharded
    strategies (DP/DDP/FSDP/TP/SP) keep the XLA BN+ReLU: pallas_call has
    no partition rule for their sharded activations (the same gate
    ``_pallas_eval`` applies to the stats kernel)."""
    policy = get_kernel_policy(config)
    if not policy.conv_epilogue:
        return False
    method = getattr(config, "train_method", "singleGPU")
    if method not in ("singleGPU", "MP", "DDP_MP"):
        logger.info(
            "--kernels: strategy %s runs the model forward under GSPMD "
            "sharding — the conv-epilogue kernel stays off there "
            "(pallas_call has no partition rule); single-device and "
            "shard_map pipeline runs engage it", method,
        )
        return False
    return True


def train_step_kernels(config) -> Tuple[str, ...]:
    """Probe-registry names of the kernels a TRAIN step under ``config``
    would engage with a ``pallas`` policy — what the planner's priors
    gate must clear for a kernel-on point (analysis/planner.py)."""
    names = ["fused_loss"]
    if getattr(config, "model_arch", "unet") == "milesial":
        names.append("conv_epilogue")
    if getattr(config, "wgrad_taps", False):
        names.append("wgrad_9tap")
    return tuple(names)


# ---------------------------------------------------------------------------
# Kernel 1 (NEW): fused DoubleConv epilogue — BN-normalize + ReLU
# ---------------------------------------------------------------------------
#
# After the XLA conv, milesial's DoubleConv runs BatchNorm-normalize then
# ReLU: two elementwise passes XLA schedules as separate fusions over the
# (B, H, W, C) activation in HBM. Folding the affine —
#
#     y = relu((x − mean)·rsqrt(var + eps)·scale + bias)
#       = relu(x·a + b),   a = rsqrt(var+eps)·scale,  b = bias − mean·a
#
# — makes the whole epilogue one multiply-add + max per element: each
# tile is read from VMEM once and written once. The BATCH STATISTICS
# (mean/var reductions, running-average updates) stay XLA — they are
# tiny reductions the compiler already fuses, and keeping them outside
# means autodiff composes: the kernel's VJP emits cotangents w.r.t.
# (x, mean, var, scale, bias) and XLA chains d(mean)/d(var) back to x
# through its own stats graph.
#
# Backward: dz = g·[z > 0] is elementwise; every parameter cotangent is
# a per-channel reduction of dz — so ONE kernel pass computes dx and
# accumulates s1 = Σ dz, s2 = Σ dz·(x − mean) per channel (the standard
# sequential-grid accumulator, f32 per the WGRAD contract), and the
# closed forms
#
#     dbias = s1          dscale = inv·s2        dmean = −a·s1
#     dvar  = −½·scale·inv³·s2                   dx    = dz·a
#
# finish in a few (C,)-sized XLA ops.


def _bn_act_kernel(x_ref, p_ref, o_ref):
    """One grid step: y = relu(x·a + b) of a (BLOCK_ROWS, C) tile;
    p_ref rows are [a, b] (the folded affine), f32 per NORM_DTYPE."""
    x = x_ref[:].astype(NORM_DTYPE)
    a = p_ref[0, :]
    b = p_ref[1, :]
    o_ref[:] = jnp.maximum(x * a + b, 0.0)


def _bn_act_bwd_kernel(x_ref, g_ref, p_ref, dx_ref, s_ref):
    """One grid step of the epilogue backward: dx tile + the two
    per-channel WGRAD_DTYPE accumulators (s_ref rows: Σdz, Σdz·(x−mean))
    carried VMEM-resident across the sequential grid. p_ref rows are
    [a, b, mean]."""

    @pl.when(pl.program_id(0) == 0)
    def _init():
        s_ref[...] = jnp.zeros_like(s_ref)

    x = x_ref[:].astype(NORM_DTYPE)
    g = g_ref[:].astype(WGRAD_DTYPE)
    a = p_ref[0, :]
    b = p_ref[1, :]
    mean = p_ref[2, :]
    z = x * a + b
    dz = jnp.where(z > 0.0, g, 0.0)
    dx_ref[:] = dz * a
    s_ref[0, :] += jnp.sum(dz, axis=0)
    s_ref[1, :] += jnp.sum(dz * (x - mean), axis=0)


def _rows_of(x: jax.Array) -> Tuple[jax.Array, int]:
    """(B, ..., C) → zero-padded (R, C) with R a BLOCK_ROWS multiple;
    returns (rows, true row count). Zero pad rows are inert in the
    backward (g is padded with zeros too → dz = 0 contributes nothing to
    the channel sums); forward pad rows are sliced off."""
    c = x.shape[-1]
    flat = x.reshape(-1, c)
    n = flat.shape[0]
    pad = (-n) % BLOCK_ROWS
    if pad:
        flat = jnp.pad(flat, ((0, pad), (0, 0)))
    return flat, n


def _spec(block, index_map, interpret):
    if interpret or _VMEM is None:
        return pl.BlockSpec(block, index_map)
    return pl.BlockSpec(block, index_map, memory_space=_VMEM)


def _sequential_grid_params(interpret):
    if interpret or pltpu is None:
        return {}
    # sequential grid: the accumulator output block is carried across
    # steps (the wgrad_pallas.py pattern)
    return {"compiler_params": pltpu.CompilerParams(
        dimension_semantics=("arbitrary",)
    )}


def fused_bn_act(
    x: jax.Array,
    mean: jax.Array,
    var: jax.Array,
    scale: jax.Array,
    bias: jax.Array,
    epsilon: float = 1e-5,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """``relu((x − mean)·rsqrt(var + eps)·scale + bias)`` in ONE fused
    VMEM pass, differentiable on the training path via the hand-written
    elementwise VJP above. ``x`` is (..., C); the channel operands are
    (C,). Returns NORM_DTYPE (f32), like the XLA BN it replaces —
    callers cast back to the compute dtype.

    Numerics: the folded affine associates ``x·(inv·scale)`` where the
    XLA path computes ``((x − mean)·inv)·scale`` — equal to float
    rounding (~1e-6 relative), not bitwise; the parity band is pinned in
    tests/test_kernels.py. Inputs must be unsharded/device-local
    (pallas_call has no GSPMD partition rule — see
    ``conv_epilogue_engaged``)."""
    if interpret is None:
        interpret = _auto_interpret()
    return _fused_bn_act_p(
        x, mean, var, scale, bias, float(epsilon), bool(interpret)
    )


def _bn_act_fwd_impl(x, mean, var, scale, bias, epsilon, interpret):
    mean = mean.astype(NORM_DTYPE)
    inv = jax.lax.rsqrt(var.astype(NORM_DTYPE) + epsilon)
    a = inv * scale.astype(NORM_DTYPE)
    b = bias.astype(NORM_DTYPE) - mean * a
    rows, n = _rows_of(x)
    c = rows.shape[-1]
    num_blocks = rows.shape[0] // BLOCK_ROWS
    packed = jnp.stack([a, b])  # (2, C)
    y = pl.pallas_call(
        _bn_act_kernel,
        grid=(num_blocks,),
        in_specs=[
            _spec((BLOCK_ROWS, c), lambda i: (i, 0), interpret),
            _spec((2, c), lambda i: (0, 0), interpret),
        ],
        out_specs=_spec((BLOCK_ROWS, c), lambda i: (i, 0), interpret),
        out_shape=jax.ShapeDtypeStruct(rows.shape, NORM_DTYPE),
        interpret=interpret,
    )(rows, packed)
    return y[:n].reshape(x.shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def _fused_bn_act_p(x, mean, var, scale, bias, epsilon, interpret):
    return _bn_act_fwd_impl(x, mean, var, scale, bias, epsilon, interpret)


def _bn_act_fwd(x, mean, var, scale, bias, epsilon, interpret):
    y = _bn_act_fwd_impl(x, mean, var, scale, bias, epsilon, interpret)
    return y, (x, mean, var, scale, bias)


def _bn_act_bwd(epsilon, interpret, res, g):
    x, mean, var, scale, bias = res
    mean32 = mean.astype(NORM_DTYPE)
    inv = jax.lax.rsqrt(var.astype(NORM_DTYPE) + epsilon)
    a = inv * scale.astype(NORM_DTYPE)
    b = bias.astype(NORM_DTYPE) - mean32 * a
    rows, n = _rows_of(x)
    g_rows, _ = _rows_of(g)
    c = rows.shape[-1]
    num_blocks = rows.shape[0] // BLOCK_ROWS
    packed = jnp.stack([a, b, mean32])  # (3, C)
    dx_rows, sums = pl.pallas_call(
        _bn_act_bwd_kernel,
        grid=(num_blocks,),
        in_specs=[
            _spec((BLOCK_ROWS, c), lambda i: (i, 0), interpret),
            _spec((BLOCK_ROWS, c), lambda i: (i, 0), interpret),
            _spec((3, c), lambda i: (0, 0), interpret),
        ],
        out_specs=[
            _spec((BLOCK_ROWS, c), lambda i: (i, 0), interpret),
            _spec((2, c), lambda i: (0, 0), interpret),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(rows.shape, WGRAD_DTYPE),
            jax.ShapeDtypeStruct((2, c), WGRAD_DTYPE),
        ],
        interpret=interpret,
        **_sequential_grid_params(interpret),
    )(rows, g_rows, packed)
    s1, s2 = sums[0], sums[1]
    dx = dx_rows[:n].reshape(x.shape).astype(x.dtype)
    dbias = s1.astype(bias.dtype)
    dscale = (inv * s2).astype(scale.dtype)
    dmean = (-a * s1).astype(mean.dtype)
    dvar = (-0.5 * scale.astype(NORM_DTYPE) * inv**3 * s2).astype(var.dtype)
    return dx, dmean, dvar, dscale, dbias


_fused_bn_act_p.defvjp(_bn_act_fwd, _bn_act_bwd)


# ---------------------------------------------------------------------------
# Kernel 2 (NEW): fused sigmoid/threshold serve mask
# ---------------------------------------------------------------------------


def sigmoid_threshold_mask(
    x: jax.Array,
    threshold: float,
    from_logits: bool = False,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Probabilities (or logits) → the served ``{0, 255} uint8`` mask in
    ONE fused pass, same shape out. The serve tier traces this into its
    AOT bucket executables (serve/infer.make_forward): the executable's
    output — and the D2H transfer behind every completion drain — shrinks
    from 4 f32 bytes/pixel to 1, and the host-side numpy threshold pass
    disappears from the completion workers.

    ``from_logits=True`` fuses the sigmoid in too (for heads that emit
    raw logits); the shipping binary-segmentation heads apply their
    sigmoid inside the model under the LOSS_DTYPE contract, so the serve
    engagement feeds probabilities and the comparison is EXACT — masks
    are bit-identical to ``postprocess_mask`` on the same probabilities
    (tests/test_kernels.py pins this across bucket shapes).

    ``threshold`` is trace-time static (the serve tier compiles one
    executable per bucket at a fixed operating point)."""
    if interpret is None:
        interpret = _auto_interpret()
    thr = float(threshold)

    def kernel(x_ref, o_ref):
        v = x_ref[:].astype(LOSS_DTYPE)
        if from_logits:
            v = jax.nn.sigmoid(v)
        o_ref[:] = jnp.where(v >= thr, jnp.uint8(255), jnp.uint8(0))

    flat = x.reshape(-1)
    n = flat.shape[0]
    per_block = BLOCK_ROWS * LANES
    num_blocks = max(1, -(-n // per_block))
    pad = num_blocks * per_block - n
    rows = jnp.pad(flat, (0, pad)).reshape(num_blocks * BLOCK_ROWS, LANES)
    mask = pl.pallas_call(
        kernel,
        grid=(num_blocks,),
        in_specs=[_spec((BLOCK_ROWS, LANES), lambda i: (i, 0), interpret)],
        out_specs=_spec((BLOCK_ROWS, LANES), lambda i: (i, 0), interpret),
        out_shape=jax.ShapeDtypeStruct(rows.shape, jnp.uint8),
        interpret=interpret,
    )(rows)
    return mask.reshape(-1)[:n].reshape(x.shape)


# ---------------------------------------------------------------------------
# Mosaic probe registry + per-chip priors file
# ---------------------------------------------------------------------------

PRIORS_KIND = "dpt_kernel_priors"
#: Priors-file schema version: consumers (planner ``--kernel-priors``,
#: ``apply_priors`` via DPT_KERNEL_PRIORS) ignore any other value with a
#: note — a stale priors file must never silently flip engagement.
PRIORS_VERSION = 1


def _probe_eval_stats():
    from distributedpytorch_tpu.ops.pallas_kernels import eval_stats_pallas

    x = jnp.zeros((2, 32, 64, 1), LOSS_DTYPE)
    jax.jit(eval_stats_pallas).lower(x, x).compile()


def _probe_fused_loss():
    from distributedpytorch_tpu.ops.fused_loss import fused_bce_dice_loss

    x = jnp.zeros((2, 32, 64, 1), LOSS_DTYPE)
    jax.jit(jax.value_and_grad(fused_bce_dice_loss)).lower(x, x).compile()


def _probe_conv_epilogue():
    c = 128  # the hot milesial widths are full lane tiles
    x = jnp.zeros((2, 16, 24, c), NORM_DTYPE)
    vec = jnp.zeros((c,), NORM_DTYPE)

    def loss(x, mean, var, scale, bias):
        return jnp.sum(fused_bn_act(x, mean, var, scale, bias))

    jax.jit(jax.grad(loss, argnums=(0, 1, 2, 3, 4))).lower(
        x, vec, vec + 1.0, vec + 1.0, vec
    ).compile()


def _probe_serve_mask():
    x = jnp.zeros((2, 32, 64), LOSS_DTYPE)
    jax.jit(
        lambda v: sigmoid_threshold_mask(v, 0.5)
    ).lower(x).compile()


def _probe_wgrad_9tap():
    from distributedpytorch_tpu.ops.wgrad_pallas import wgrad_9tap_pallas

    x = jnp.zeros((1, 8, 30, 128), jnp.bfloat16)
    dy = jnp.zeros((1, 8, 30, 128), jnp.bfloat16)
    jax.jit(wgrad_9tap_pallas).lower(x, dy).compile()


#: The probe registry: kernel name → a compile-only callable (AOT
#: ``lower().compile()``, ZERO execution — the wgrad_pallas_probe
#: pattern per kernel). On TPU the auto-interpret gate resolves to real
#: Mosaic lowering, so an exception IS the chip's accept/reject verdict;
#: elsewhere the interpreter path compiles, proving the machinery.
PROBES: Dict[str, Callable[[], None]] = {
    "eval_stats": _probe_eval_stats,
    "fused_loss": _probe_fused_loss,
    "conv_epilogue": _probe_conv_epilogue,
    "serve_mask": _probe_serve_mask,
    "wgrad_9tap": _probe_wgrad_9tap,
}


def run_probes(
    names: Optional[Sequence[str]] = None,
    emit: Optional[Callable[[dict], None]] = None,
) -> dict:
    """Run the (selected) probe registry; returns the priors payload
    (what ``save_priors`` writes). Never raises on a probe failure —
    a Mosaic rejection is a RESULT (recorded with its reason), not an
    error."""
    selected = list(names) if names else sorted(PROBES)
    unknown = [n for n in selected if n not in PROBES]
    if unknown:
        raise ValueError(
            f"unknown probe kernel(s) {unknown}; registry has "
            f"{sorted(PROBES)}"
        )
    dev = jax.devices()[0]
    kernels: Dict[str, dict] = {}
    for name in selected:
        t0 = time.monotonic()
        row: Dict[str, object] = {"kernel": name}
        try:
            PROBES[name]()
            row.update(accepted=True)
        except Exception as exc:  # noqa: BLE001 — the verdict, not a bug
            reason = f"{type(exc).__name__}: {exc}"
            row.update(accepted=False, reason=reason[:500])
        row["compile_s"] = round(time.monotonic() - t0, 3)
        kernels[name] = {k: v for k, v in row.items() if k != "kernel"}
        if emit is not None:
            emit(row)
    return {
        "kind": PRIORS_KIND,
        "version": PRIORS_VERSION,
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", str(dev)),
        "kernels": kernels,
    }


def save_priors(payload: dict, path: str) -> None:
    """Atomic write, mirroring the planner's plan-file IO."""
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=2)
    os.replace(tmp, path)


#: (path → (mtime, payload)) memo: the policy re-resolves per layer in
#: one process (strategy, model factory, serve engine), and each should
#: not re-read + re-parse the same on-disk file. Keyed on mtime so a
#: rewritten file (a fresh probe run) invalidates naturally.
_PRIORS_CACHE: Dict[str, Tuple[float, Optional[dict]]] = {}


def load_priors(path: str) -> Optional[dict]:
    """The priors payload, or None — with a logged note — for a missing,
    unreadable, corrupt, or version-skewed file. Consumers degrade to
    unprobed behavior on None; a half-written or stale priors file must
    never flip kernel engagement or reorder a plan silently."""
    try:
        mtime = os.path.getmtime(path)
    except OSError:
        return None
    cached = _PRIORS_CACHE.get(path)
    if cached is not None and cached[0] == mtime:
        return cached[1]
    payload = _read_priors(path)
    _PRIORS_CACHE[path] = (mtime, payload)
    return payload


def _read_priors(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            payload = json.load(f)
    except FileNotFoundError:
        return None
    except (OSError, ValueError) as exc:
        logger.warning(
            "kernel priors %s unreadable (%s) — ignored; kernels stay "
            "unprobed", path, exc,
        )
        return None
    if (
        not isinstance(payload, dict)
        or payload.get("kind") != PRIORS_KIND
        or payload.get("version") != PRIORS_VERSION
        or not isinstance(payload.get("kernels"), dict)
    ):
        logger.warning(
            "kernel priors %s stale or malformed (want kind=%r version="
            "%d) — ignored; kernels stay unprobed",
            path, PRIORS_KIND, PRIORS_VERSION,
        )
        return None
    return payload
