"""Adam with torch-parity semantics and a runtime-adjustable learning rate.

The reference optimizes with ``optim.Adam(params, lr, weight_decay=1e-8)``
(reference utils/train_utils.py:45). torch's Adam ``weight_decay`` is L2
regularization folded into the gradient BEFORE the moment updates — not
AdamW's decoupled decay — so the optax chain is::

    add_decayed_weights(wd)  →  scale_by_adam(b1=.9, b2=.999, eps=1e-8)  →  -lr

(`optax.adamw` would decay after the Adam scaling — different trajectory.)

The lr rides in optimizer state via `optax.inject_hyperparams`, so the
plateau scheduler (ops/schedule.py) can change it between epochs WITHOUT
retriggering XLA compilation: the jitted train step reads the lr from state,
and `set_learning_rate` rewrites that one scalar on the host.
"""

from __future__ import annotations

import jax.numpy as jnp
import optax


def adam_l2(learning_rate: float, weight_decay: float = 1e-8) -> optax.GradientTransformation:
    """torch.optim.Adam(lr, weight_decay) parity (defaults b1=0.9, b2=0.999,
    eps=1e-8 match torch's)."""

    @optax.inject_hyperparams
    def _make(lr):
        return optax.chain(
            optax.add_decayed_weights(weight_decay),
            optax.scale_by_adam(b1=0.9, b2=0.999, eps=1e-8),
            optax.scale(-lr),
        )

    return _make(lr=learning_rate)


def _hyperparams(opt_state):
    """The inject_hyperparams dict regardless of precision policy: the
    bf16_params master-weight wrapper (ops/precision.MasterWeightsState)
    nests the real state one level down."""
    from distributedpytorch_tpu.ops.precision import unwrap_opt_state

    return unwrap_opt_state(opt_state).hyperparams


def set_learning_rate(opt_state, lr: float):
    """Rewrite the injected lr scalar in-place on the host (no recompile)."""
    hyperparams = _hyperparams(opt_state)
    hyperparams["lr"] = jnp.asarray(lr, dtype=jnp.asarray(hyperparams["lr"]).dtype)
    return opt_state


def get_learning_rate(opt_state) -> float:
    return float(_hyperparams(opt_state)["lr"])
