"""Space-to-depth (s2d) execution domain for shallow UNet levels.

Why this exists (measured on the attached TPU v5e, batch 4, bf16):
the full-resolution low-channel convolutions that dominate the reference
UNet's shallow levels map terribly onto the 128-lane MXU —

    conv  32→32 @640×960:  5.3 TFLOP/s fwd,  4.2 TFLOP/s bwd   (~2.5% peak)
    conv 128→128 @320×480: 37.9 TFLOP/s fwd, 36.0 TFLOP/s bwd

Rewriting a 3×3 stride-1 SAME conv over (H, W, C) as a 3×3 SAME conv over
the 2×2 space-to-depth image (H/2, W/2, 4C) does 4× the MAC count (the
structured kernel is 3/4 zeros) yet runs ~2× faster wall-clock on those
shapes, forward and backward. The transform is EXACT: the dense kernel is
assembled from the original (3,3,Cin,Cout) parameters inside the traced
computation, so parameter pytrees, checkpoints, and autodiff (gradients
flow through the assembly and land on the original weights) are unchanged.

Layout convention ("g-major"): the s2d image S of a pixel image X is

    S[b, i, j, g*C + c] = X[b, 2i + di, 2j + dj, c],   g = 2*di + dj

with di/dj ∈ {0,1} the intra-block row/col offsets. A concatenation of two
s2d tensors is NOT the s2d of the pixel concatenation — kernel builders
take ``in_segments`` describing the per-tensor channel counts so the skip
concat in the UNet decoder needs no data movement at all.

Every builder here mirrors one reference op:
  * 3×3 SAME conv           (reference model/unet_parts.py:10-12)
  * 2×2 stride-2 maxpool    (reference model/unet_parts.py:26)
  * 2×2 stride-2 ConvTranspose (reference model/unet_parts.py:51-54)
  * 1×1 segmentation head   (reference model/unet_model.py:10)
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp


def space_to_depth(x: jax.Array) -> jax.Array:
    """(B, H, W, C) → (B, H/2, W/2, 4C), g-major. H and W must be even."""
    b, h, w, c = x.shape
    assert h % 2 == 0 and w % 2 == 0, f"s2d needs even H, W; got {(h, w)}"
    x = x.reshape(b, h // 2, 2, w // 2, 2, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)  # (B, H/2, W/2, di, dj, C)
    return x.reshape(b, h // 2, w // 2, 4 * c)


def depth_to_space(x: jax.Array) -> jax.Array:
    """Inverse of :func:`space_to_depth`."""
    b, h, w, c4 = x.shape
    assert c4 % 4 == 0
    c = c4 // 4
    x = x.reshape(b, h, w, 2, 2, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)  # (B, H, di, W, dj, C)
    return x.reshape(b, 2 * h, 2 * w, c)


def _conv3x3_kernel_one_segment(w: jax.Array) -> jax.Array:
    """(3,3,Cin,Cout) → (3,3,4Cin,4Cout), single g-major input segment.

    Derivation: output pixel row 2I+do+ky−1 sits in block row I+Bi−1 at
    intra-block offset di where 2·Bi+di = do+ky+1 — so for a fixed output
    group, padding the kernel's ky axis to 6 slots at offset do+1 and
    reshaping 6 → (Bi=3, di=2) places every tap, no scatters. Built from
    pads/reshapes/one stack so the traced graph stays tiny (a 36-scatter
    construction made XLA compiles of the differentiated model ~5× slower).
    """
    cin, cout = w.shape[2], w.shape[3]
    per_group = []
    for do_i in range(2):
        wi = jnp.pad(w, ((do_i + 1, 2 - do_i), (0, 0), (0, 0), (0, 0)))
        for do_j in range(2):
            wij = jnp.pad(wi, ((0, 0), (do_j + 1, 2 - do_j), (0, 0), (0, 0)))
            # (6, 6, Cin, Cout) → (Bi, di, Bj, dj, Cin, Cout)
            wij = wij.reshape(3, 2, 3, 2, cin, cout)
            per_group.append(wij.transpose(0, 2, 1, 3, 4, 5))
    # (g_out, Bi, Bj, di, dj, Cin, Cout) → (Bi, Bj, (di,dj,Cin), (g_out,Cout))
    dense = jnp.stack(per_group, axis=0).transpose(1, 2, 3, 4, 5, 0, 6)
    return dense.reshape(3, 3, 4 * cin, 4 * cout)


def conv3x3_kernel(
    w: jax.Array, in_segments: Optional[Sequence[int]] = None
) -> jax.Array:
    """(3,3,Cin,Cout) → (3,3,4Cin,4Cout) structured dense kernel such that a
    SAME conv of it over the s2d image equals the SAME conv of ``w`` over
    the pixel image (then s2d). 1/4 density — each output group uses 2×2 of
    the 3×3 block taps. ``in_segments`` describes an input that is a channel
    concatenation of independently g-major s2d tensors (the decoder's skip
    concat): each segment's kernel slice transforms independently."""
    kh, kw, cin, cout = w.shape
    assert (kh, kw) == (3, 3), f"conv3x3_kernel got kernel {w.shape}"
    segs = tuple(in_segments) if in_segments is not None else (cin,)
    assert sum(segs) == cin, (segs, cin)
    parts = []
    off = 0
    for seg in segs:
        parts.append(_conv3x3_kernel_one_segment(w[:, :, off : off + seg, :]))
        off += seg
    return jnp.concatenate(parts, axis=2) if len(parts) > 1 else parts[0]


def upconv_kernel(u: jax.Array) -> jax.Array:
    """(2,2,Cin,Cout) ConvTranspose(k=2,s=2) weights → (1,1,Cin,4Cout): the
    stride-2 transpose conv writes each output pixel from exactly one tap,
    so in s2d space it is a 1×1 conv on the PIXEL-space input at half
    resolution. flax/lax orientation (verified): Y[2I+di, 2J+dj] =
    X[I,J] @ U[1−di, 1−dj]."""
    kh, kw, cin, cout = u.shape
    assert (kh, kw) == (2, 2), f"upconv_kernel got kernel {u.shape}"
    flipped = u[::-1, ::-1]  # [di, dj] = U[1−di, 1−dj]
    dense = flipped.transpose(2, 0, 1, 3).reshape(cin, 4 * cout)
    return dense[None, None]


def head1x1_kernel(
    w: jax.Array, in_segments: Optional[Sequence[int]] = None
) -> jax.Array:
    """(1,1,Cin,Cout) → (1,1,4Cin,4Cout) block-diagonal-by-group kernel: a
    1×1 conv acts within each pixel, i.e. within each s2d group —
    kron(I₄, w) in the g-major layout."""
    kh, kw, cin, cout = w.shape
    assert (kh, kw) == (1, 1), f"head1x1_kernel got kernel {w.shape}"
    segs = tuple(in_segments) if in_segments is not None else (cin,)
    assert sum(segs) == cin, (segs, cin)
    eye = jnp.eye(4, dtype=w.dtype)
    parts = []
    off = 0
    for seg in segs:
        parts.append(jnp.kron(eye, w[0, 0, off : off + seg, :]))
        off += seg
    dense = jnp.concatenate(parts, axis=0) if len(parts) > 1 else parts[0]
    return dense[None, None]


def tile_bias(b: jax.Array) -> jax.Array:
    """Per-channel bias → per-s2d-channel bias (g-major ⇒ plain tile)."""
    return jnp.tile(b, 4)


def group_max(x: jax.Array) -> jax.Array:
    """2×2 stride-2 maxpool of the underlying pixel image, evaluated on its
    s2d form: the pool window IS the s2d group. (B,h,w,4C) → (B,h,w,C) at
    what is now the next level's pixel resolution."""
    b, h, w, c4 = x.shape
    assert c4 % 4 == 0
    return jnp.max(x.reshape(b, h, w, 4, c4 // 4), axis=3)


def conv_same(x: jax.Array, kernel: jax.Array) -> jax.Array:
    """NHWC SAME conv used by the s2d path (stride 1)."""
    return jax.lax.conv_general_dilated(
        x,
        kernel,
        window_strides=(1, 1),
        padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
