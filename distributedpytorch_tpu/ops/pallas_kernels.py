"""Pallas TPU kernels for the framework's hot elementwise+reduction ops.

The compute path of this framework is XLA-compiled convolutions (XLA's conv
lowering owns the MXU; hand-writing convs would fight the compiler, see
SURVEY.md §7 hard-part 4). What Pallas is the right tool for here is the
fused tail op: the BCE + soft-dice sufficient statistics over the full
(B, H, W, 1) probability map — four reductions plus elementwise logs that
XLA schedules as separate fusions. `bce_dice_stats_pallas` computes all
four in ONE pass over the data: each (block, 128-lane) tile is read from
VMEM once, the clamped-log BCE term and the dice partial sums are computed
in registers, and four scalar accumulators in SMEM carry the running sums
across the sequential grid (the standard Pallas reduction pattern:
initialize at program 0, accumulate each step).

Numerics follow ops/losses.py exactly in formula (same clamp at -100, same
`== 1` binarization — reference utils/utils.py:14-25) but NOT bit-for-bit:
multi-block accumulation sums in a different order than XLA's reduction
tree, so results agree to ~1e-5 relative (the equivalence tests' tolerance),
not exactly. The tests run the kernel in interpret mode on CPU and real
mode on TPU.

Used on the no-grad paths (evaluation; anywhere stats are consumed without
autodiff). The training loss keeps the XLA path: differentiating a Pallas
kernel needs a hand-written VJP, and grad-parity risk there buys nothing
while the step is conv-dominated.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# The stats accumulators spell the LOSS_DTYPE contract (ops/precision.py):
# loss/Dice statistics accumulate f32 under every --dtype policy — the
# dptlint ``dtype-policy`` rule reaches kernel bodies, and these named
# constants are its sanctioned spelling (this module is no longer exempt).
from distributedpytorch_tpu.ops.precision import LOSS_DTYPE

try:  # TPU-specific memory spaces; absent on some CPU-only installs
    from jax.experimental.pallas import tpu as pltpu

    _SMEM = pltpu.SMEM
    _VMEM = pltpu.VMEM
except ImportError:  # pragma: no cover
    pltpu = None
    _SMEM = _VMEM = None

_LOG_CLAMP = -100.0  # torch BCELoss log clamp (ops/losses.py)

LANES = 128  # TPU vector lane width
BLOCK_ROWS = 512  # (512, 128) f32 block = 256 KB per input — fits VMEM


def _stats_kernel(p_ref, t_ref, out_ref):
    """One grid step: partial BCE + soft-dice + hard-dice sums of a
    (BLOCK_ROWS, LANES) tile, accumulated into 6 SMEM scalars laid out as
    out_ref[0, 0:6] (slot 1 is patched with the element count outside)."""
    p = p_ref[:].astype(LOSS_DTYPE)
    t = t_ref[:].astype(LOSS_DTYPE)
    tb = (t == 1.0).astype(LOSS_DTYPE)  # reference utils.py:16 binarize
    pb = (p >= 0.5).astype(LOSS_DTYPE)  # hard-dice threshold (losses.py)
    log_p = jnp.maximum(jnp.log(p), _LOG_CLAMP)
    log_1p = jnp.maximum(jnp.log(1.0 - p), _LOG_CLAMP)
    per_elem = -(tb * log_p + (1.0 - tb) * log_1p)

    @pl.when(pl.program_id(0) == 0)
    def _init():
        for j in range(6):
            out_ref[0, j] = 0.0

    out_ref[0, 0] += jnp.sum(per_elem)  # bce numerator
    out_ref[0, 2] += jnp.sum(p * tb)  # soft-dice intersection
    out_ref[0, 3] += jnp.sum(p) + jnp.sum(tb)  # soft-dice union
    out_ref[0, 4] += jnp.sum(pb * tb)  # hard-dice intersection
    out_ref[0, 5] += jnp.sum(pb) + jnp.sum(tb)  # hard-dice union


def _auto_interpret() -> bool:
    """Real Mosaic lowering on TPU; the Pallas interpreter elsewhere (CPU
    test meshes, GPU). One place decides — callers pass interpret=None."""
    return jax.devices()[0].platform != "tpu"


def _stats_call(p2, t2, n, num_blocks, interpret):
    # no jit here: n/num_blocks/grid must stay static, and callers (the
    # jitted eval step; tests) already run this under their own trace
    if not interpret and _SMEM is not None:
        in_space, out_space = _VMEM, _SMEM
    else:  # interpreter has no TPU memory spaces
        in_space = out_space = None

    def spec(block, index_map, space):
        if space is None:
            return pl.BlockSpec(block, index_map)
        return pl.BlockSpec(block, index_map, memory_space=space)

    stats = pl.pallas_call(
        _stats_kernel,
        grid=(num_blocks,),
        in_specs=[
            spec((BLOCK_ROWS, LANES), lambda i: (i, 0), in_space),
            spec((BLOCK_ROWS, LANES), lambda i: (i, 0), in_space),
        ],
        out_specs=spec((1, 6), lambda i: (0, 0), out_space),
        out_shape=jax.ShapeDtypeStruct((1, 6), LOSS_DTYPE),
        interpret=interpret,
    )(p2, t2)
    return jnp.stack(
        [
            stats[0, 0],
            jnp.asarray(n, LOSS_DTYPE),
            stats[0, 2],
            stats[0, 3],
            stats[0, 4],
            stats[0, 5],
        ]
    )


def eval_stats_pallas(
    outputs: jax.Array, targets: jax.Array, interpret=None
) -> jax.Array:
    """Fused one-pass `[bce_sum, count, soft_inter, soft_union, hard_inter,
    hard_union]`: the first four are ops/losses.py `bce_dice_stats`, the
    last two are the hard-Dice metric's sums — everything the eval step
    needs from ONE VMEM read per element.

    Padding invariant: tiles are padded with (p=0, t=0), which contributes
    exactly zero to every accumulator — per_elem = -log(1-0) = 0, p·tb = 0,
    p + tb = 0 — so no masking is needed in the kernel; the true element
    count is patched in outside.

    `interpret=None` auto-selects: Mosaic on TPU, interpreter elsewhere.
    The inputs must be unsharded (single device or replicated): pallas_call
    has no GSPMD partitioning rule, so callers on sharded meshes must not
    route sharded arrays here (see make_eval_step's gating).
    """
    if interpret is None:
        interpret = _auto_interpret()
    p = outputs.astype(LOSS_DTYPE).reshape(-1)
    t = targets.astype(LOSS_DTYPE).reshape(-1)
    n = p.size
    per_block = BLOCK_ROWS * LANES
    num_blocks = max(1, -(-n // per_block))
    pad = num_blocks * per_block - n
    p = jnp.pad(p, (0, pad)).reshape(num_blocks * BLOCK_ROWS, LANES)
    t = jnp.pad(t, (0, pad)).reshape(num_blocks * BLOCK_ROWS, LANES)
    return _stats_call(p, t, n, num_blocks, interpret)


def bce_dice_stats_pallas(
    outputs: jax.Array, targets: jax.Array, interpret=None
) -> jax.Array:
    """ops/losses.py `bce_dice_stats` contract (4 stats) via the kernel."""
    return eval_stats_pallas(outputs, targets, interpret=interpret)[:4]


def bce_dice_loss_pallas(
    outputs: jax.Array, targets: jax.Array, interpret=None
) -> jax.Array:
    """Scalar BCE − log-dice via the fused kernel (no-grad paths only)."""
    from distributedpytorch_tpu.ops.losses import loss_from_stats

    return loss_from_stats(bce_dice_stats_pallas(outputs, targets, interpret=interpret))


def eval_metrics_pallas(
    outputs: jax.Array, targets: jax.Array, interpret=None, dice_eps: float = 1e-7
) -> dict:
    """{'loss', 'dice'} for the eval step from one fused pass — BCE −
    log-dice (losses.py `bce_dice_loss`) and hard Dice (losses.py
    `dice_coefficient`, threshold 0.5, same eps)."""
    from distributedpytorch_tpu.ops.losses import loss_from_stats

    stats = eval_stats_pallas(outputs, targets, interpret=interpret)
    dice = (2.0 * stats[4] + dice_eps) / (stats[5] + dice_eps)
    return {"loss": loss_from_stats(stats[:4]), "dice": dice}
