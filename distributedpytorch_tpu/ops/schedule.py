"""ReduceLROnPlateau — host-side LR state machine.

optax has no plateau scheduler driven by a runtime metric, so this is a small
reimplementation of torch.optim.lr_scheduler.ReduceLROnPlateau with the
defaults the reference relies on (reference utils/train_utils.py:46:
``ReduceLROnPlateau(optimizer, 'min', patience=2)`` → factor=0.1,
threshold=1e-4, threshold_mode='rel', cooldown=0, min_lr=0).

It runs on the host between epochs (stepped on val loss, reference
train_utils.py:86); the resulting lr enters the jitted train step as a scalar
argument, so an lr change never retriggers compilation.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class ReduceLROnPlateau:
    lr: float
    mode: str = "min"
    factor: float = 0.1
    patience: int = 2
    threshold: float = 1e-4
    threshold_mode: str = "rel"
    cooldown: int = 0
    min_lr: float = 0.0

    best: float = None  # type: ignore[assignment]
    num_bad_epochs: int = 0
    cooldown_counter: int = 0

    def __post_init__(self):
        if self.mode not in ("min", "max"):
            raise ValueError(f"mode must be 'min' or 'max', got {self.mode!r}")
        if self.best is None:
            self.best = float("inf") if self.mode == "min" else float("-inf")

    def _is_better(self, current: float) -> bool:
        if self.threshold_mode == "rel":
            if self.mode == "min":
                return current < self.best * (1.0 - self.threshold)
            return current > self.best * (1.0 + self.threshold)
        if self.mode == "min":
            return current < self.best - self.threshold
        return current > self.best + self.threshold

    def step(self, metric: float) -> float:
        """Record an epoch's metric; returns the (possibly reduced) lr."""
        current = float(metric)
        if self._is_better(current):
            self.best = current
            self.num_bad_epochs = 0
        else:
            self.num_bad_epochs += 1

        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.num_bad_epochs = 0

        if self.num_bad_epochs > self.patience:
            self.lr = max(self.lr * self.factor, self.min_lr)
            self.cooldown_counter = self.cooldown
            self.num_bad_epochs = 0
        return self.lr

    def state_dict(self) -> dict:
        return dataclasses.asdict(self)

    def load_state_dict(self, state: dict) -> None:
        """Restore from `state_dict()` output (or a legacy subset of it).

        Unknown keys are rejected loudly — silently setattr'ing them
        (the old behavior) let a typo'd or stale checkpoint field ride
        along as a dead attribute. Missing keys keep their constructor
        values (legacy checkpoints predate some fields). A legacy dict
        that carries ``best=None`` (saved before the first `step()` ever
        ran under an old version that serialized the pre-__post_init__
        placeholder) re-derives the mode-correct sentinel instead of
        poisoning every later `_is_better` comparison with a
        None-vs-float TypeError."""
        known = {f.name for f in dataclasses.fields(self)}
        unknown = set(state) - known
        if unknown:
            raise ValueError(
                f"ReduceLROnPlateau.load_state_dict: unknown keys "
                f"{sorted(unknown)} (expected a subset of {sorted(known)})"
            )
        # validate on a candidate copy first (replace() re-runs
        # __post_init__: mode check + best-sentinel derivation), so a bad
        # value leaves this scheduler untouched — no half-applied state
        # for a caller that catches the error
        candidate = dataclasses.replace(self, **state)
        self.__dict__.update(candidate.__dict__)
