"""Alternative conv backward: weight-grad as 9 tap matmuls.

Round-3 profiling (docs/PERFORMANCE.md) left the step backward-dominated:
the s2d-domain 3×3 convs run their BACKWARD at ~2.1× the forward's time,
i.e. XLA's conv-backward-filter emitter schedules no better than the
forward even though the weight gradient is just a tall contraction

    dW[ky,kx,ci,co] = Σ_{b,y,x} Xpad[b, y+ky, x+kx, ci] · dY[b, y, x, co]

— for the hot 128→128 @ 320×480 batch-4 shape: M = Cin = 128,
N = Cout = 128, K = B·H·W ≈ 614k per tap. This module re-expresses that
weight gradient as 9 explicit `einsum`s (one per kernel tap, each a plain
MXU matmul over a shifted view of the padded input) behind a
`jax.custom_vjp`, leaving the forward and the input-gradient on XLA's
conv emitter (the input-grad IS a conv — of dY with the rot180,
in/out-swapped kernel — and XLA runs convs at forward speed).

Numerics: the taps accumulate in float32 (`preferred_element_type`) and
cast back to the kernel dtype, the same contract as XLA's bf16 conv
backward; exactness vs `jax.grad` of the plain conv is pinned in
tests/test_s2d.py. Off by default (`TrainConfig.wgrad_taps`) until the
TPU measurement lands — this is a hypothesis with a test harness, not a
claimed win.

Backend: the tap contraction itself has two implementations — the 9
einsums below, and a single-pass Pallas kernel (ops/wgrad_pallas.py)
that loads each row once and accumulates all nine taps from VMEM.
``DPT_WGRAD_BACKEND=pallas`` selects the kernel AT TRACE TIME (set it
before the first jit of the model; already-compiled executables keep
whatever they traced). The Pallas path engages only for channel counts
that fill the 128-wide MXU/lane tiles; skinny convs (the RGB stem) stay
on einsum.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from distributedpytorch_tpu.ops.s2d import conv_same as _conv_same

# Minimum channel count for the Pallas wgrad path: below a full lane tile
# the kernel's (W+2, C) operands waste most of the vector unit and the
# einsum path's XLA fusions win.
_PALLAS_MIN_CHANNELS = 128


def _wgrad_backend() -> str:
    return os.environ.get("DPT_WGRAD_BACKEND", "einsum")


@jax.custom_vjp
def _conv3x3_same_taps_vjp(x: jax.Array, kernel: jax.Array) -> jax.Array:
    """NHWC SAME stride-1 3×3 conv; forward = XLA conv, backward =
    XLA conv for dx + 9 tap matmuls for dW."""
    return _conv_same(x, kernel)


def _taps_min_hw() -> int:
    """Trace-time spatial gate for the taps rewrite.

    ``DPT_WGRAD_TAPS_MIN_HW=N`` scopes the 9-tap weight gradient to
    convs whose H·W plane is at least N pixels (default 0 = every
    conv). Two reasons to scope: (a) the tall-contraction win
    concentrates where K = B·H·W is largest — the shallow levels —
    while small-plane convs gain nothing over XLA's emitter; (b) the
    full-taps graph (9 einsums × every conv) is the largest XLA program
    this framework emits, and the round-5 window-1 attempt never
    finished compiling it over the tunneled runtime in 1200 s — scoping
    to the top level(s) shrinks the graph severalfold."""
    raw = os.environ.get("DPT_WGRAD_TAPS_MIN_HW", "0")
    try:
        return int(raw)
    except ValueError:
        # fail LOUD: a typo'd threshold silently falling back to 0 would
        # select the full-taps-everywhere graph — the exact compile hang
        # the scoped config exists to avoid — under the scoped label
        raise ValueError(
            f"DPT_WGRAD_TAPS_MIN_HW={raw!r}: expected an integer pixel "
            "count") from None


def conv3x3_same_taps(x: jax.Array, kernel: jax.Array) -> jax.Array:
    """The public taps conv: every model call site funnels here, so the
    DPT_WGRAD_TAPS_MIN_HW gate applies uniformly. Below the gate the
    conv is the plain XLA one — identical forward AND backward."""
    if x.shape[1] * x.shape[2] >= _taps_min_hw():
        return _conv3x3_same_taps_vjp(x, kernel)
    return _conv_same(x, kernel)


def _fwd(x, kernel):
    return _conv_same(x, kernel), (x, kernel)


def _wgrad_einsum(x, dy):
    """dW (3,3,Cin,Cout) f32 as 9 shifted-view einsums."""
    b, h, w, _ = x.shape
    xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    taps = []
    for ky in range(3):
        for kx in range(3):
            win = jax.lax.slice(
                xp, (0, ky, kx, 0), (b, ky + h, kx + w, x.shape[3])
            )
            taps.append(
                jnp.einsum(
                    "bhwi,bhwo->io",
                    win,
                    dy,
                    preferred_element_type=jnp.float32,
                )
            )
    return jnp.stack(taps).reshape(3, 3, x.shape[3], dy.shape[3])


def _bwd(res, dy):
    x, kernel = res
    # dx: SAME conv of dY with the rotated, in/out-swapped kernel —
    # kt[ky,kx,co,ci] = k[2−ky, 2−kx, ci, co] (exact for stride-1 SAME).
    kt = kernel[::-1, ::-1].transpose(0, 1, 3, 2)
    dx = _conv_same(dy, kt)

    cin, cout = x.shape[3], kernel.shape[3]
    if (
        _wgrad_backend() == "pallas"
        and min(cin, cout) >= _PALLAS_MIN_CHANNELS
    ):
        from distributedpytorch_tpu.ops.wgrad_pallas import wgrad_9tap_pallas

        dk = wgrad_9tap_pallas(x, dy)
    else:
        dk = _wgrad_einsum(x, dy)
    return dx.astype(x.dtype), dk.astype(kernel.dtype)


_conv3x3_same_taps_vjp.defvjp(_fwd, _bwd)
