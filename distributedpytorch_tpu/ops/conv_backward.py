"""Alternative conv backward: weight-grad as 9 tap matmuls.

Round-3 profiling (docs/PERFORMANCE.md) left the step backward-dominated:
the s2d-domain 3×3 convs run their BACKWARD at ~2.1× the forward's time,
i.e. XLA's conv-backward-filter emitter schedules no better than the
forward even though the weight gradient is just a tall contraction

    dW[ky,kx,ci,co] = Σ_{b,y,x} Xpad[b, y+ky, x+kx, ci] · dY[b, y, x, co]

— for the hot 128→128 @ 320×480 batch-4 shape: M = Cin = 128,
N = Cout = 128, K = B·H·W ≈ 614k per tap. This module re-expresses that
weight gradient as 9 explicit `einsum`s (one per kernel tap, each a plain
MXU matmul over a shifted view of the padded input) behind a
`jax.custom_vjp`, leaving the forward and the input-gradient on XLA's
conv emitter (the input-grad IS a conv — of dY with the rot180,
in/out-swapped kernel — and XLA runs convs at forward speed).

Numerics: the taps accumulate in float32 (`preferred_element_type`) and
cast back to the kernel dtype, the same contract as XLA's bf16 conv
backward; exactness vs `jax.grad` of the plain conv is pinned in
tests/test_s2d.py. Off by default (`TrainConfig.wgrad_taps`) until the
TPU measurement lands — this is a hypothesis with a test harness, not a
claimed win.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from distributedpytorch_tpu.ops.s2d import conv_same as _conv_same


@jax.custom_vjp
def conv3x3_same_taps(x: jax.Array, kernel: jax.Array) -> jax.Array:
    """NHWC SAME stride-1 3×3 conv; forward = XLA conv, backward =
    XLA conv for dx + 9 tap matmuls for dW."""
    return _conv_same(x, kernel)


def _fwd(x, kernel):
    return _conv_same(x, kernel), (x, kernel)


def _bwd(res, dy):
    x, kernel = res
    # dx: SAME conv of dY with the rotated, in/out-swapped kernel —
    # kt[ky,kx,co,ci] = k[2−ky, 2−kx, ci, co] (exact for stride-1 SAME).
    kt = kernel[::-1, ::-1].transpose(0, 1, 3, 2)
    dx = _conv_same(dy, kt)

    b, h, w, _ = x.shape
    xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    taps = []
    for ky in range(3):
        for kx in range(3):
            win = jax.lax.slice(
                xp, (0, ky, kx, 0), (b, ky + h, kx + w, x.shape[3])
            )
            taps.append(
                jnp.einsum(
                    "bhwi,bhwo->io",
                    win,
                    dy,
                    preferred_element_type=jnp.float32,
                )
            )
    dk = jnp.stack(taps).reshape(3, 3, x.shape[3], kernel.shape[3])
    return dx.astype(x.dtype), dk.astype(kernel.dtype)


conv3x3_same_taps.defvjp(_fwd, _bwd)
