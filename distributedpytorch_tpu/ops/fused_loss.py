"""Differentiable fused loss: Pallas one-pass stats forward, analytic VJP.

Round 3 shipped the fused BCE+dice stats kernel (ops/pallas_kernels.py)
eval-only: differentiating a ``pallas_call`` needs a hand-written VJP, and
the training path stayed XLA (VERDICT r03 weak-3: "Pallas is barely
load-bearing"). This module supplies that VJP at the right altitude — the
SUFFICIENT-STATISTICS level (ops/losses.py `bce_dice_stats`):

    stats = [bce_sum, count, intersection, output_sum + target_sum]

The cotangent of each stat w.r.t. each output element is closed-form:

    ∂bce_sum/∂o_i       = −(t_i·[o_i ≥ m]/o_i − (1−t_i)·[1−o_i ≥ m]/(1−o_i))
    ∂count/∂o_i         = 0
    ∂intersection/∂o_i  = t_i
    ∂(Σo + Σt)/∂o_i     = 1

with m = losses._LOG_SAFE_MIN reproducing the grad-safe clamp (saturated
pixels contribute exactly zero gradient — the round-3 NaN fix's contract,
ops/losses.py `_clamped_log`). Everything downstream of the stats —
`loss_from_stats`, pipeline psums/accumulation, the scalar scheduler math —
is tiny and stays ordinary XLA, so autodiff composes: the pipeline schedule
(parallel/pipeline.py) and the shard_map wrapper below differentiate
through their psums as before while the O(B·H·W) passes run through the
Pallas kernel forward and one fused elementwise backward.

Numerics: the kernel accumulates in a different order than XLA's reduction
tree, so values agree to ~1e-5 relative, not bitwise (same caveat as the
eval kernel); the BACKWARD is elementwise and matches `jax.grad` of the
XLA loss to float tolerance (tests/test_pallas.py).
"""

from __future__ import annotations

import functools
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from distributedpytorch_tpu.utils.compat import shard_map

from distributedpytorch_tpu.ops.losses import (
    _LOG_SAFE_MIN,
    loss_from_stats,
)
# The analytic backward spells the LOSS_DTYPE contract (ops/precision.py)
# — the dptlint ``dtype-policy`` rule reaches custom-VJP bodies via
# ``defvjp``, and the named constant is its sanctioned spelling (this
# module is no longer exempt).
from distributedpytorch_tpu.ops.precision import LOSS_DTYPE
from distributedpytorch_tpu.ops.pallas_kernels import bce_dice_stats_pallas


@jax.custom_vjp
def bce_dice_stats_fused(outputs: jax.Array, targets: jax.Array) -> jax.Array:
    """`bce_dice_stats` contract (4 stats) via the Pallas kernel, with an
    analytic VJP so it sits on the TRAINING path."""
    return bce_dice_stats_pallas(outputs, targets)


def _stats_fwd(outputs, targets):
    return bce_dice_stats_pallas(outputs, targets), (outputs, targets)


def _stats_bwd(res, ct):
    outputs, targets = res
    o = outputs.astype(LOSS_DTYPE)
    tb = (targets == 1).astype(LOSS_DTYPE)
    m = _LOG_SAFE_MIN
    # zero (not inf·0=NaN) gradient on saturated pixels — the where-on-
    # both-sides pattern from losses._clamped_log, in derivative form
    inv_o = jnp.where(o >= m, 1.0 / jnp.maximum(o, m), 0.0)
    inv_1mo = jnp.where(1.0 - o >= m, 1.0 / jnp.maximum(1.0 - o, m), 0.0)
    dbce = -(tb * inv_o - (1.0 - tb) * inv_1mo)
    grad = ct[0] * dbce + ct[2] * tb + ct[3]
    return grad.astype(outputs.dtype), jnp.zeros_like(targets)


bce_dice_stats_fused.defvjp(_stats_fwd, _stats_bwd)


def fused_bce_dice_loss(outputs: jax.Array, targets: jax.Array) -> jax.Array:
    """Training-path BCE − log-dice through the fused kernel: unsharded
    (single-device / fully replicated) arrays only — mesh strategies use
    :func:`make_sharded_fused_loss`."""
    return loss_from_stats(bce_dice_stats_fused(outputs, targets))


def make_sharded_fused_loss(mesh: Mesh, spec: P, axes: Sequence[str]):
    """``loss(outputs, targets) -> scalar`` running the fused kernel
    per-shard under ``shard_map`` and psumming the 4 stats over ``axes``
    (the mesh axes `spec` shards the batch/image over).

    This is what lets mesh strategies stop gating Pallas off: pallas_call
    has no GSPMD partitioning rule, but inside shard_map every array is
    process-local and the kernel sees plain (local) shapes. The stats are
    additive over ANY slicing (losses.bce_dice_stats docstring), so the
    psum'd result — and therefore the loss AND its gradient through the
    custom VJP — equals the unsharded computation.
    """
    axes = tuple(axes)

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec, spec),
        out_specs=P(),
        check_vma=False,
    )
    def loss(outputs, targets):
        stats = bce_dice_stats_fused(outputs, targets)
        if axes:
            stats = jax.lax.psum(stats, axes)
        return loss_from_stats(stats)

    return loss


def spec_axes(spec: P) -> Tuple[str, ...]:
    """Mesh axis names a PartitionSpec shards over (entries may be axis
    names or tuples of them)."""
    axes = []
    for entry in spec:
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            axes.extend(entry)
        else:
            axes.append(entry)
    return tuple(axes)
