from distributedpytorch_tpu.ops.losses import BCEDiceLoss, bce_dice_loss, dice_coefficient  # noqa: F401
from distributedpytorch_tpu.ops.schedule import ReduceLROnPlateau  # noqa: F401
