"""Segmentation loss: BCE − log(soft-Dice), plus a real Dice metric.

Formula parity with the reference `Loss` (reference utils/utils.py:9-25):

    loss = BCE(outputs, targets_bin)
           - log( 2 * (outputs * targets_bin).sum()
                  / (outputs.sum() + targets_bin.sum() + eps) )

with ``eps = 1e-15`` and targets binarized by ``targets == 1``
(utils.py:16). The BCE term reproduces torch.nn.BCELoss semantics: mean
reduction and log terms clamped at -100 (torch clamps log(x) to >= -100 so a
hard 0/1 prediction yields a finite loss).

The reference never computes an actual Dice metric despite the segmentation
task (SURVEY.md §2 quirk 6); `dice_coefficient` adds one — it is the
"val Dice" used by the benchmark harness.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

EPS = 1e-15  # reference utils/utils.py:13
_LOG_CLAMP = -100.0  # torch BCELoss log clamp
# Below this, x is treated as saturated: the value clamps to -100 and the
# gradient is 0. The float32 minimum normal — the smallest x where 1/x is
# still finite (subnormals push 1/x to inf) — so the value-parity gap vs
# torch's effective clamp point (log(x) = -100 at x ≈ 3.7e-44) is as small
# as float32 allows: only [3.7e-44, 1.18e-38) clamps early, at log values
# in (-100, -87.3] (ADVICE r03).
_LOG_SAFE_MIN = 1.1754944e-38


def _clamped_log(x: jax.Array) -> jax.Array:
    """log(x) with torch.nn.BCELoss's >= -100 clamp — GRAD-SAFELY.

    ``maximum(log(x), -100)`` has the right value but a NaN gradient at
    x == 0: the max selects the constant (selector grad 0) while the log
    branch's cotangent is 1/0 = inf, and 0 · inf = NaN. One saturated
    sigmoid pixel (p exactly 0.0 or 1.0, which bf16 logits ≥ ~17 produce
    in float32) then NaNs the ENTIRE gradient through the sum — observed
    in round 3 as a real TPU training run diverging to NaN at epoch 10
    right after val-Dice hit 0.98. The where-on-both-sides pattern keeps
    every intermediate finite, so saturated pixels contribute the clamped
    value and exactly zero gradient (matching torch's backward clamp in
    effect)."""
    safe = jnp.maximum(x, _LOG_SAFE_MIN)
    return jnp.where(x >= _LOG_SAFE_MIN, jnp.log(safe), _LOG_CLAMP)


def binary_cross_entropy(outputs: jax.Array, targets: jax.Array) -> jax.Array:
    """torch.nn.BCELoss() parity: mean over all elements, clamped logs."""
    outputs = outputs.astype(jnp.float32)
    targets = targets.astype(jnp.float32)
    per_elem = -(
        targets * _clamped_log(outputs) + (1.0 - targets) * _clamped_log(1.0 - outputs)
    )
    return jnp.mean(per_elem)


def soft_dice(outputs: jax.Array, targets: jax.Array, eps: float = EPS) -> jax.Array:
    """2·|o∩t| / (|o|+|t|+eps) over the whole batch (reference utils.py:18-23)."""
    outputs = outputs.astype(jnp.float32)
    targets = targets.astype(jnp.float32)
    intersection = jnp.sum(outputs * targets)
    union = jnp.sum(outputs) + jnp.sum(targets)
    return 2.0 * intersection / (union + eps)


def bce_dice_loss(
    outputs: jax.Array, targets: jax.Array, dice_weight: float = 1.0
) -> jax.Array:
    """BCE − dice_weight · log(soft dice), target binarized by ``== 1``.

    `outputs` are probabilities (post-sigmoid) shaped like `targets`
    broadcast-compatibly; both are flattened by the reductions.
    """
    targets_bin = (targets == 1).astype(jnp.float32)  # reference utils.py:16
    bce = binary_cross_entropy(outputs, targets_bin)
    dice = soft_dice(outputs, targets_bin)
    return bce - dice_weight * _clamped_log(dice)


def bce_dice_stats(outputs: jax.Array, targets: jax.Array) -> jax.Array:
    """Sufficient statistics of the BCE−log-dice loss over a slice of the
    batch: ``[bce_sum, count, intersection, output_sum + target_sum]``.

    The log-dice term is a ratio of whole-batch sums, so a microbatched
    pipeline cannot average per-microbatch losses (mean of log-dice ≠
    log-dice of the mean) — it must accumulate these stats and call
    `loss_from_stats` once. Stats are additive: sum over microbatches /
    shards / stages (a psum) THEN combine, and the result is bit-comparable
    to the single-pass loss on the concatenated batch.
    """
    outputs = outputs.astype(jnp.float32)
    targets_bin = (targets == 1).astype(jnp.float32)
    per_elem = -(
        targets_bin * _clamped_log(outputs)
        + (1.0 - targets_bin) * _clamped_log(1.0 - outputs)
    )
    return jnp.stack(
        [
            jnp.sum(per_elem),
            jnp.asarray(outputs.size, jnp.float32),
            jnp.sum(outputs * targets_bin),
            jnp.sum(outputs) + jnp.sum(targets_bin),
        ]
    )


def loss_from_stats(stats: jax.Array, dice_weight: float = 1.0, eps: float = EPS) -> jax.Array:
    """Combine accumulated `bce_dice_stats` into the scalar loss."""
    bce_sum, count, intersection, union = stats[0], stats[1], stats[2], stats[3]
    bce = bce_sum / count
    dice = 2.0 * intersection / (union + eps)
    return bce - dice_weight * _clamped_log(dice)


class BCEDiceLoss:
    """Callable wrapper mirroring the reference `Loss(dice_weight=1)` object
    (reference utils/utils.py:9-12)."""

    def __init__(self, dice_weight: float = 1.0):
        self.dice_weight = dice_weight

    def __call__(self, outputs: jax.Array, targets: jax.Array) -> jax.Array:
        return bce_dice_loss(outputs, targets, self.dice_weight)


def dice_coefficient(
    outputs: jax.Array, targets: jax.Array, threshold: float = 0.5, eps: float = 1e-7
) -> jax.Array:
    """Hard Dice on thresholded predictions — the real segmentation metric the
    reference lacks (SURVEY.md §2 quirk 6). Used for val-Dice benchmarking."""
    preds = (outputs.astype(jnp.float32) >= threshold).astype(jnp.float32)
    targets_bin = (targets == 1).astype(jnp.float32)
    intersection = jnp.sum(preds * targets_bin)
    union = jnp.sum(preds) + jnp.sum(targets_bin)
    return (2.0 * intersection + eps) / (union + eps)
