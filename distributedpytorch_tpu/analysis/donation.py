"""Layer 1c: donation-safety for the serving tier, statically.

The donation bug class has bitten this codebase twice: the CPU-backend
SIGABRT (a donated train-state buffer re-read after the step consumed
it) and the AOT-store "Symbols not found" poisoning cousin (a shared
executable whose operands a sibling process must be able to re-read).
The AST lint's ``use-after-donation`` rule covers the train-step rebind
idiom; this pass covers the SERVING side, where the invariant is
stronger and simpler:

**No serve executable may donate, ever.** Serve replicas re-read their
weights operand on every request (``replica.variables`` is bound once
per swap, called thousands of times), rollouts re-read snapshots taken
BEFORE a swap (``snapshot_weights`` → ``restore_weights``), and
AOT-store entries are rehydrated by sibling processes that share
nothing with the compiling process but the bytes. A donated operand is
freed by its first call — every one of those paths then reads poisoned
memory.

Three tiers enforce it:

* **Intent** (:func:`check_serve_donation`, here) — every serve
  variant (float / int8 / pallas / int8+pallas) is lowered through
  ``serve/engine.serve_jit`` — the engine's ONE jit wrapper, so this
  is the exact code path every bucket executable takes — and the
  ``Lowered.donate_argnums`` record must be empty. This is
  backend-independent: it fires even on the CPU analysis rig, where
  XLA silently DROPS unusable donations at lowering (so a text scan
  alone would miss the intent and the bug would wait for TPU to
  materialize).
* **Materialization** (also :func:`check_serve_donation`) — the
  lowered module text must carry none of the aliasing markers
  (``utils/aotstore.DONATION_MARKERS``) XLA stamps when a donation IS
  usable. Lowering only; nothing compiles.
* **Admission** (``utils/aotstore.AOTStore.save``) — the runtime
  backstop: a compiled executable whose optimized HLO aliases an input
  to an output is refused store admission with a pointed log line, so
  even a donation introduced past the static gates cannot poison
  sibling processes through the store.

The AST companion rule (``analysis/lint.py`` ``serve-donation``) flags
any ``jit(..., donate_argnums=...)`` call that appears in a serve
module at the source level — catching wrappers that never reach the
engine's lowering path.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

from distributedpytorch_tpu.analysis import Finding, dedupe
from distributedpytorch_tpu.analysis.collectives import (
    SERVE_VARIANTS,
    _serve_rig,
)
from distributedpytorch_tpu.utils.aotstore import DONATION_MARKERS


def lower_serve_variant(variant: str, batch: int = 1):
    """One serve variant's forward, LOWERED through the exact wrapper
    the engine compiles with (``serve/engine.serve_jit``) — abstract
    inputs, no compile, no device execution."""
    from distributedpytorch_tpu.serve.engine import serve_jit

    fwd, variables, x = _serve_rig(variant, batch)
    return serve_jit(fwd).lower(variables, x)


def check_serve_donation(
    variants: Sequence[str] = SERVE_VARIANTS,
) -> Tuple[List[Finding], List[str]]:
    """Lower every serve variant through ``serve_jit`` and require it
    donation-free at both the intent and the materialization tier.
    Returns ``(findings, tags)``."""
    findings: List[Finding] = []
    tags: List[str] = []
    for variant in variants:
        where = f"serve {variant} forward (lowered)"
        tags.append(where)
        lowered = lower_serve_variant(variant)
        donated = tuple(getattr(lowered, "donate_argnums", ()) or ())
        if donated:
            findings.append(Finding(
                rule="serve-donation",
                where=where,
                message=(
                    f"serve executable lowers with donated argument(s) "
                    f"{donated} — replicas re-read their weights operand "
                    f"on every request and AOT-store siblings rehydrate "
                    f"them, so the donated buffer is freed after the "
                    f"first call and every later read is poisoned (the "
                    f"CPU donation SIGABRT class); serve_jit must never "
                    f"donate"
                ),
                layer="donation",
            ))
            continue
        text = lowered.as_text()
        marked = [m for m in DONATION_MARKERS if m in text]
        if marked:
            findings.append(Finding(
                rule="serve-donation",
                where=where,
                message=(
                    f"lowered serve module carries aliasing marker(s) "
                    f"{marked} — an input buffer is aliased into an "
                    f"output, so the executable consumes an operand the "
                    f"serving tier re-reads (swap snapshots, store "
                    f"rehydration); serve executables must lower "
                    f"alias-free"
                ),
                layer="donation",
            ))
    return dedupe(findings), tags


def analyze_donation(
    variants: Sequence[str] = SERVE_VARIANTS,
) -> Tuple[List[Finding], List[str]]:
    """The donation pass: every serve variant, lowering tier only (the
    admission guard runs at store-save time; the AST rule runs with the
    lint layer)."""
    return check_serve_donation(variants)
