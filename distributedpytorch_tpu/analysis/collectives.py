"""Layer 1: the jaxpr collective checker.

Abstractly traces each strategy's train/eval step on the CPU mesh —
``jax.make_jaxpr`` over ``ShapeDtypeStruct`` inputs, so NO device ever
executes anything — then walks the closed jaxpr (descending into
``shard_map``/``pjit``/``scan``/``cond``/``remat`` subjaxprs) to extract
the ordered collective program: every ``psum`` / ``all_gather`` /
``reduce_scatter`` / ``ppermute`` with its axis names and permutation.
Four checks over that program:

(a) **axis binding** — every collective's axis name is bound by the
    enclosing ``shard_map`` mesh; an unbound axis would fail at run time
    (or worse, under ``check_vma=False``, silently misresolve).

(b) **ppermute bijectivity + tick-program deadlock-freedom** — each
    permutation must be a partial bijection (no duplicated sources or
    destinations), and the composed tick program must be deadlock-free.
    Deadlock-freedom is checked by simulating the send/recv schedule per
    stage: the stage that PRODUCES a payload (the ``stage == s`` branch
    of the ``lax.cond`` feeding the ppermute) must appear among the
    permutation's sources, and every stage that CONSUMES the ppermuted
    value (the ``stage == j`` cond it feeds) must appear among the
    destinations. A flipped edge in the 1F1B phase-B program — perm
    ``((e, e+1),)`` where the cotangent producer is stage ``e+1`` —
    leaves stage ``e+1``'s send unposted and stage ``e`` waiting on a
    payload that never arrives: exactly the cyclic wait that hangs the
    CPU rendezvous for 300 s in CI, failed here statically instead.
    Producer/consumer attribution resolves cond predicates of the form
    ``eq(axis_index('stage'), <literal>)``; ppermutes whose endpoints
    don't resolve (e.g. autodiff-transposed gpipe programs) pass through
    unflagged — the check is sound, not complete.

(c) **SPMD rank uniformity** — (i) no collective may sit inside a
    ``cond`` branch whose predicate depends on ``axis_index`` (devices
    along the axis would execute divergent collective sequences); and
    (ii) the step is re-traced under simulated process identities
    (``jax.process_index`` patched to 0 and then 1) and the two
    extracted collective programs must be identical — a ``psum`` guarded
    by an ``if jax.process_index() == 0:`` Python conditional traces
    into rank 0's program only and is flagged here, instead of hanging a
    real 2-process run.

(d) **comms contract** — each strategy's extracted program must satisfy
    its declared contract below. ``EXPECTED_HLO_COLLECTIVES`` (the table
    ``tests/test_hlo_collectives.py`` used to hardcode, now owned here
    and imported by that test) describes the post-GSPMD optimized-HLO
    collectives; ``JAXPR_CONTRACTS`` describes the trace-level program of
    the explicit shard_map schedules, including the schedule-closing
    gradient psum whose 'data' axis IS the DDP all-reduce for DDP_MP —
    dropping it would silently fork the data replicas.

The GSPMD strategies (DP/SP/TP/FSDP) have EMPTY jaxpr-level programs
(XLA inserts their collectives at compile time); their contract lives in
the HLO tier, verified by ``hlo_collectives`` under ``--hlo`` (an AOT
CPU compile — still zero execution) and independently cross-checked by
tests/test_hlo_collectives.py's regex in tier-1.
"""

from __future__ import annotations

import dataclasses
import unittest.mock
from typing import Dict, FrozenSet, List, Optional, Sequence, Tuple

from distributedpytorch_tpu.analysis import (
    ANALYSIS_SCHEDULES,
    ANALYSIS_STRATEGIES,
    AnalysisEnvironmentError,
    Finding,
    dedupe,
)
# the mesh rule engine (jax-free module): contracts DERIVE from the
# sharding rules instead of a hand-kept table, and ``DxMxS[@rule]``
# mesh specs analyze exactly like the legacy strategy names
from distributedpytorch_tpu.parallel.mesh import (
    LEGACY_PATTERNS,
    channel_comms_required,
    derive_eval_jaxpr_contract,
    derive_hlo_contract,
    derive_jaxpr_contract,
    is_mesh_spec,
    parse_mesh_spec,
    spec_is_pipeline,
)

# -- the tiny analysis rig ---------------------------------------------------
# Same shapes as tests/test_strategies.py's equivalence rig: the analyzer
# exercises the parallelism machinery, where the model is a payload — the
# collective program of the 2-level narrow UNet is structurally identical
# to the reference-sized model's, and traces in ~2 s per combo.
H, W, B = 32, 48, 8
WIDTHS = (8, 16)

#: ANALYSIS_STRATEGIES / ANALYSIS_SCHEDULES live in the jax-free package
#: ``__init__`` (preflight call sites gate on them) and are re-exported
#: here as the checker's defaults.
PIPELINE_STRATEGIES = ("MP", "DDP_MP")

#: Collective primitives extracted from jaxprs.
COLLECTIVE_PRIMS = frozenset(
    {"psum", "ppermute", "all_gather", "reduce_scatter", "all_to_all",
     "pmin", "pmax"}
)

# -- the declared comms contract (check d) -----------------------------------
#: Optimized-HLO collectives each strategy's compiled train step must
#: contain (verified against XLA's output on the 8-device CPU mesh),
#: DERIVED from each strategy's mesh pattern by the sharding-rule engine
#: (parallel/mesh.derive_hlo_contract) — DP's gradient all-reduce, SP's
#: conv halo collective-permutes, FSDP's ZeRO all-gathers, MP/DDP_MP's
#: ppermute stage transfers. This is the single source
#: tests/test_hlo_collectives.py imports; the test keeps its own
#: independent regex over compiled.as_text().
EXPECTED_HLO_COLLECTIVES: Dict[str, FrozenSet[str]] = {
    method: derive_hlo_contract(LEGACY_PATTERNS[method])
    for method in ("DP", "SP", "FSDP", "MP", "DDP_MP")
}
#: TP's sharded-channel layers must communicate somehow; XLA picks the
#: mechanism per version — any of these proves channels are distributed.
#: (mesh.channel_comms_required marks the configs this tier applies to;
#: for channel HYBRIDS it applies IN ADDITION to the derived exact set.)
TP_HLO_ANY_OF = frozenset({"all-to-all", "all-gather", "collective-permute"})


@dataclasses.dataclass(frozen=True)
class JaxprComm:
    """One trace-level contract requirement: a collective of ``kind``
    whose axes cover ``axes`` must exist; ``grad_output=True`` restricts
    candidates to collectives whose results ARE step outputs (the
    schedule-closing gradient reduction), so a stats psum that happens to
    share the axes cannot mask a dropped grad psum."""

    kind: str
    axes: FrozenSet[str]
    grad_output: bool = False
    why: str = ""


def _derived_contract(pattern, schedule) -> Tuple[JaxprComm, ...]:
    """Wrap the rule engine's derived rows into JaxprComm requirements
    (the row tuples are JaxprComm's field order by construction)."""
    return tuple(
        JaxprComm(kind, axes, grad_output, why)
        for kind, axes, grad_output, why in derive_jaxpr_contract(
            pattern, schedule
        )
    )


def _build_contract_table() -> Dict[Tuple[str, Optional[str]], Tuple[JaxprComm, ...]]:
    table: Dict[Tuple[str, Optional[str]], Tuple[JaxprComm, ...]] = {}
    for method in ANALYSIS_STRATEGIES:
        pattern = LEGACY_PATTERNS[method]
        if pattern.is_pipeline:
            for schedule in ANALYSIS_SCHEDULES:
                table[(method, schedule)] = _derived_contract(
                    pattern, schedule
                )
        else:
            table[(method, None)] = _derived_contract(pattern, None)
    return table


#: Trace-level contract per (strategy, schedule), DERIVED from each
#: strategy's mesh pattern by the sharding-rule engine
#: (parallel/mesh.derive_jaxpr_contract) instead of a hand-kept table:
#: pipelined patterns require the inter-stage ppermutes, the whole-batch
#: stats psum over ('stage'[, 'data']), and (1f1b) the schedule-closing
#: output-feeding gradient psum whose 'data' axis IS the DDP all-reduce
#: for DDP_MP — dropping it would silently fork the data replicas.
#: GSPMD strategies derive EMPTY rows (XLA inserts their collectives at
#: compile time) — their contract lives in EXPECTED_HLO_COLLECTIVES.
#: Mesh-spec methods (``4x1x2``) don't need a row here: check_contract
#: derives theirs on the fly from the parsed spec.
JAXPR_CONTRACTS: Dict[Tuple[str, Optional[str]], Tuple[JaxprComm, ...]] = (
    _build_contract_table()
)


def _derived_eval_contract(pattern, schedule) -> Tuple[JaxprComm, ...]:
    """Eval-step rows from the rule engine, as JaxprComm requirements."""
    return tuple(
        JaxprComm(kind, axes, grad_output, why)
        for kind, axes, grad_output, why in derive_eval_jaxpr_contract(
            pattern, schedule
        )
    )


def _build_eval_contract_table(
) -> Dict[Tuple[str, Optional[str]], Tuple[JaxprComm, ...]]:
    table: Dict[Tuple[str, Optional[str]], Tuple[JaxprComm, ...]] = {}
    for method in ANALYSIS_STRATEGIES:
        pattern = LEGACY_PATTERNS[method]
        if pattern.is_pipeline:
            for schedule in ANALYSIS_SCHEDULES:
                table[(method, schedule)] = _derived_eval_contract(
                    pattern, schedule
                )
        else:
            table[(method, None)] = _derived_eval_contract(pattern, None)
    return table


#: Trace-level contract per (strategy, schedule) for the EVAL step,
#: derived by the same rule engine (parallel/mesh.
#: derive_eval_jaxpr_contract): the forward slice of the train program —
#: inter-stage ppermutes, the in-stage param-reconstruction all_gathers,
#: and the output-feeding eval-stats psum over 'stage' ONLY (stats are
#: returned per data shard; no 'data' axis even on hybrids). Before this
#: table, eval traces got structural checks but NO contract: a dropped
#: eval psum shipped stage-local metrics as if they were global and no
#: static gate noticed.
EVAL_JAXPR_CONTRACTS: Dict[Tuple[str, Optional[str]],
                           Tuple[JaxprComm, ...]] = (
    _build_eval_contract_table()
)


# -- extraction --------------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class Collective:
    """One extracted collective with everything the checks need."""

    kind: str
    axes: Tuple[object, ...]          # axis names (strs; ints under vmap)
    perm: Optional[Tuple[Tuple[int, int], ...]]
    context: Tuple[str, ...]          # enclosing-eqn path, e.g. (pjit, shard_map)
    bound_axes: FrozenSet[str]        # mesh axes in scope at this point
    producer_stage: Optional[int]     # stage whose cond branch made the input
    consumer_stages: Tuple[int, ...]  # stages whose conds consume the output
    direct_output: bool               # results are body outputs (grad psum)
    axis_guarded: bool                # inside an axis_index-dependent branch
    payload_bytes: int = 0            # summed operand aval bytes (per device)

    @property
    def signature(self) -> Tuple:
        """Order-sensitive identity for rank-invariance comparison."""
        return (self.kind, self.axes, self.perm, self.context)


def _subjaxprs(value) -> List:
    """Jaxpr objects reachable from one eqn param value (ClosedJaxpr,
    Jaxpr, or tuples of either — cond branches, scan bodies, ...)."""
    # ClosedJaxpr proxies .eqns, so unwrap .jaxpr FIRST (the walker needs
    # the raw Jaxpr's outvars for the direct-output attribution)
    if hasattr(value, "jaxpr") and hasattr(value.jaxpr, "eqns"):
        return [value.jaxpr]
    if hasattr(value, "eqns"):
        return [value]
    if isinstance(value, (tuple, list)):
        out = []
        for v in value:
            out.extend(_subjaxprs(v))
        return out
    return []


def _is_literal(v) -> bool:
    return hasattr(v, "val") and not hasattr(v, "count")


def _payload_bytes(eqn) -> int:
    """Summed operand abstract-value bytes of one collective eqn —
    inside a ``shard_map`` body the avals are per-device shard shapes,
    so this is the per-device payload the planner's comms tables want."""
    total = 0
    for var in eqn.invars:
        aval = getattr(var, "aval", None)
        shape = getattr(aval, "shape", None)
        dtype = getattr(aval, "dtype", None)
        if shape is None or dtype is None:
            continue
        size = 1
        for dim in shape:
            size *= int(dim)
        total += size * dtype.itemsize
    return int(total)


def _body_attribution(jaxpr):
    """Per-body maps for producer/consumer attribution and axis-guard
    detection: which vars come from ``cond(eq(axis_index(ax), s), ...)``
    branches, which conds consume which vars, and which cond predicates
    depend on ``axis_index`` at all."""
    producer_eqn: Dict = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for ov in eqn.outvars:
            producer_eqn[ov] = i
    axis_vars = {
        e.outvars[0]: e.params["axis_name"]
        for e in jaxpr.eqns
        if e.primitive.name == "axis_index"
    }

    def resolve_stage(var, depth=0):
        """cond index var -> (axis, stage) when the predicate is
        ``eq(axis_index(axis), literal)`` (possibly through dtype
        conversions)."""
        if _is_literal(var) or var not in producer_eqn or depth > 6:
            return None
        eqn = jaxpr.eqns[producer_eqn[var]]
        name = eqn.primitive.name
        if name == "convert_element_type":
            return resolve_stage(eqn.invars[0], depth + 1)
        if name == "eq":
            a, b = eqn.invars
            for x, y in ((a, b), (b, a)):
                if (not _is_literal(x) and x in axis_vars
                        and _is_literal(y)):
                    return (axis_vars[x], int(y.val))
        return None

    def depends_on_axis(var, depth=0):
        """Does this var transitively derive from an axis_index?"""
        if _is_literal(var) or var not in producer_eqn or depth > 8:
            return False
        if var in axis_vars:
            return True
        eqn = jaxpr.eqns[producer_eqn[var]]
        return any(
            depends_on_axis(iv, depth + 1)
            for iv in eqn.invars
            if not _is_literal(iv)
        )

    cond_stage: Dict[int, Tuple[str, int]] = {}
    cond_axis_dep: Dict[int, bool] = {}
    for i, eqn in enumerate(jaxpr.eqns):
        if eqn.primitive.name != "cond":
            continue
        idx = eqn.invars[0]
        resolved = resolve_stage(idx)
        if resolved is not None:
            cond_stage[i] = resolved
            cond_axis_dep[i] = True
        else:
            cond_axis_dep[i] = (
                False if _is_literal(idx) else depends_on_axis(idx)
            )

    outvar_stage: Dict = {}
    for i, (_ax, stage) in cond_stage.items():
        for ov in jaxpr.eqns[i].outvars:
            outvar_stage[ov] = stage
    consumers: Dict = {}
    for i, eqn in enumerate(jaxpr.eqns):
        if i in cond_stage:
            for iv in eqn.invars:
                if not _is_literal(iv):
                    consumers.setdefault(iv, []).append(cond_stage[i][1])
    return producer_eqn, outvar_stage, consumers, cond_axis_dep


def extract_collectives(closed_jaxpr) -> List[Collective]:
    """Walk a ClosedJaxpr (and every reachable subjaxpr) and return its
    ordered collective program."""
    out: List[Collective] = []

    def walk(jaxpr, context, bound_axes, guarded):
        _prod, outvar_stage, consumers, cond_axis_dep = _body_attribution(
            jaxpr
        )
        body_outs = {
            v for v in jaxpr.outvars if not _is_literal(v)
        }
        # output-feeding closure through pure slicing/layout eqns: the
        # in-stage-sharded 1f1b schedule slices each gradient leaf down
        # to the device's own shard AFTER the schedule-closing psum
        # (parallel/pipeline._slice_to_shard), so the psum's results
        # reach the body outputs through a dynamic_slice — that still
        # counts as output-feeding for the grad_output contract rows.
        # Only the sliced operand (invars[0]) passes through; index
        # operands do not.
        pass_through = {
            "dynamic_slice", "slice", "squeeze", "reshape",
            "transpose", "convert_element_type",
        }
        changed = True
        while changed:
            changed = False
            for eqn in jaxpr.eqns:
                if eqn.primitive.name not in pass_through:
                    continue
                if not any(ov in body_outs for ov in eqn.outvars):
                    continue
                src = eqn.invars[0]
                if not _is_literal(src) and src not in body_outs:
                    body_outs.add(src)
                    changed = True
        for i, eqn in enumerate(jaxpr.eqns):
            name = eqn.primitive.name
            if name in COLLECTIVE_PRIMS:
                params = eqn.params
                raw_axes = params.get("axes", params.get("axis_name", ()))
                if not isinstance(raw_axes, (tuple, list)):
                    raw_axes = (raw_axes,)
                perm = params.get("perm")
                if perm is not None:
                    perm = tuple((int(a), int(b)) for a, b in perm)
                out.append(
                    Collective(
                        kind=name,
                        axes=tuple(raw_axes),
                        perm=perm,
                        context=context,
                        bound_axes=bound_axes,
                        producer_stage=outvar_stage.get(eqn.invars[0])
                        if eqn.invars else None,
                        consumer_stages=tuple(
                            consumers.get(eqn.outvars[0], ())
                        ) if eqn.outvars else (),
                        direct_output=any(
                            ov in body_outs for ov in eqn.outvars
                        ),
                        axis_guarded=guarded,
                        payload_bytes=_payload_bytes(eqn),
                    )
                )
                continue
            sub_bound = bound_axes
            if name == "shard_map":
                mesh = eqn.params.get("mesh")
                axis_names = tuple(getattr(mesh, "axis_names", ()) or ())
                sub_bound = bound_axes | frozenset(
                    a for a in axis_names if isinstance(a, str)
                )
            sub_guarded = guarded or (
                name == "cond" and cond_axis_dep.get(i, False)
            )
            for key, value in eqn.params.items():
                for sub in _subjaxprs(value):
                    walk(sub, context + (name,), sub_bound, sub_guarded)

    walk(closed_jaxpr.jaxpr, (), frozenset(), False)
    return out


# -- abstract tracing --------------------------------------------------------
def _require_devices(n: int) -> None:
    import jax

    have = len(jax.devices())
    if have < n:
        raise AnalysisEnvironmentError(
            f"the analyzer needs >= {n} devices (an 8-device virtual CPU "
            f"mesh; the analyze CLI self-provisions one), got {have}"
        )


def _rig_batch(method: str) -> int:
    """The analysis rig's batch for one method: B, rounded UP to the
    nearest multiple a mesh spec's data axis (x microbatches, when
    pipelined) requires — odd geometries like ``3x1x2`` must trace,
    not refuse on the rig's own batch choice."""
    if not is_mesh_spec(method):
        return B
    cfg = parse_mesh_spec(method)
    unit = max(cfg.data, 1) * (2 if cfg.stage > 1 else 1)
    return ((B + unit - 1) // unit) * unit


def _tiny_config(method: str, schedule: Optional[str]):
    from distributedpytorch_tpu.config import TrainConfig

    return TrainConfig(
        train_method=method,
        batch_size=_rig_batch(method),
        compute_dtype="float32",
        image_size=(W, H),
        model_widths=WIDTHS,
        pipeline_schedule=schedule or "gpipe",
    )


def _build(method: str, schedule: Optional[str]):
    """(strategy, model, abstract_state, tx, abstract_batch) for one
    combo — everything ShapeDtypeStructs; nothing placed, nothing run."""
    import jax
    import jax.numpy as jnp

    from distributedpytorch_tpu.models.unet import UNet
    from distributedpytorch_tpu.ops.optim import adam_l2
    from distributedpytorch_tpu.parallel import build_strategy
    from distributedpytorch_tpu.train.steps import TrainState

    if is_mesh_spec(method):
        _require_devices(parse_mesh_spec(method).size)
    else:
        _require_devices(8 if method in ("DDP_MP", "DDP_SP") else 2)
    cfg = _tiny_config(method, schedule)
    strategy = build_strategy(cfg)
    model = UNet(dtype=jnp.float32, widths=WIDTHS)
    params = jax.eval_shape(
        lambda k: model.init(k, jnp.zeros((1, H, W, 3)))["params"],
        jax.random.key(0),
    )
    tx = adam_l2(cfg.learning_rate, cfg.weight_decay)
    opt_state = jax.eval_shape(tx.init, params)
    state = TrainState(
        params=params,
        opt_state=opt_state,
        step=jax.ShapeDtypeStruct((), jnp.int32),
        model_state=None,
    )
    nb = _rig_batch(method)
    batch = {
        "image": jax.ShapeDtypeStruct((nb, H, W, 3), jnp.float32),
        "mask": jax.ShapeDtypeStruct((nb, H, W), jnp.int32),
    }
    return strategy, model, state, tx, batch


def trace_train(method: str, schedule: Optional[str] = None):
    """The strategy's (unjitted) train step as a ClosedJaxpr — a fresh
    build per call, so repeated traces (the simulated-rank check) never
    reuse a cached jaxpr from a previous identity."""
    import jax

    strategy, model, state, tx, batch = _build(method, schedule)
    step = strategy._raw_step(model, tx)
    return jax.make_jaxpr(step)(state, batch)


def trace_eval(method: str, schedule: Optional[str] = None):
    """The strategy's jitted eval step as a ClosedJaxpr."""
    import jax

    strategy, model, state, _tx, batch = _build(method, schedule)
    eval_step = strategy.build_eval_step(model)
    return jax.make_jaxpr(eval_step)(state.params, batch)


# -- serve forwards ----------------------------------------------------------
#: Every forward the serve engine AOT-compiles per bucket: plain f32,
#: the ``--quantize int8`` weights-quantized path, the ``--kernels
#: pallas`` fused sigmoid-threshold mask head, and their combination.
#: All four must trace COLLECTIVE-FREE: serve replicas are independent
#: (replicated or single-device), so any collective reaching a serve
#: executable would block on peers that are serving other requests —
#: a fleet-wide deadlock the first time that bucket is hit.
SERVE_VARIANTS: Tuple[str, ...] = ("float", "int8", "pallas", "int8+pallas")

#: Batch sizes traced per variant — the smallest and largest default
#: bucket; the collective program must be bucket-size invariant.
SERVE_TRACE_BATCHES: Tuple[int, ...] = (1, 8)


def _abstract_quantized(params):
    """The abstract image of ``ops/quant.quantize_tree`` over a params
    tree of ShapeDtypeStructs. quantize_tree itself is host-side numpy
    (it materializes scales), so it cannot run under tracing — this
    mirrors its structure instead: every >=2-D float leaf becomes a
    ``{q: int8[shape], scale: f32[1,...,1,C]}`` pair (per-out-channel
    scales, keepdims), other float leaves stay f32. Must be kept in
    lockstep with ``quantize_leaf``/``QUANT_KIND``."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    def walk(node):
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        if len(node.shape) >= 2 and np.issubdtype(node.dtype, np.floating):
            scale_shape = (1,) * (len(node.shape) - 1) + (node.shape[-1],)
            return {
                "q": jax.ShapeDtypeStruct(node.shape, jnp.int8),
                "scale": jax.ShapeDtypeStruct(scale_shape, jnp.float32),
            }
        if np.issubdtype(node.dtype, np.floating):
            return jax.ShapeDtypeStruct(node.shape, jnp.float32)
        return node

    return walk(params)


def _serve_rig(variant: str, batch: int):
    """(forward_fn, abstract_variables, abstract_input) for one serve
    variant — the exact function the engine jits per replica
    (serve/infer.make_forward), over ShapeDtypeStructs only."""
    import flax.serialization
    import jax
    import jax.numpy as jnp

    from distributedpytorch_tpu.models.unet import UNet
    from distributedpytorch_tpu.serve.infer import make_forward

    if variant not in SERVE_VARIANTS:
        raise ValueError(
            f"unknown serve variant {variant!r}; expected one of "
            f"{SERVE_VARIANTS}"
        )
    model = UNet(dtype=jnp.float32, widths=WIDTHS)
    params = jax.eval_shape(
        lambda k: model.init(k, jnp.zeros((1, H, W, 3)))["params"],
        jax.random.key(0),
    )
    quantized = "int8" in variant
    kw = {}
    if quantized:
        kw["quantized"] = True
        params = _abstract_quantized(
            flax.serialization.to_state_dict(params)
        )
    if "pallas" in variant:
        kw["mask_threshold"] = 0.5
    fwd = make_forward(model, **kw)
    x = jax.ShapeDtypeStruct((batch, H, W, 3), jnp.float32)
    return fwd, {"params": params}, x


def trace_serve(variant: str, batch: int = 1):
    """One serve variant's per-bucket forward as a ClosedJaxpr."""
    import jax

    fwd, variables, x = _serve_rig(variant, batch)
    return jax.make_jaxpr(fwd)(variables, x)


def check_serve_collective_free(
    variants: Sequence[str] = SERVE_VARIANTS,
) -> Tuple[List[Finding], List[str]]:
    """Trace every serve variant at the smallest and largest default
    bucket and require a collective-free program. Returns
    ``(findings, tags)`` — one tag per traced (variant, bucket)."""
    findings: List[Finding] = []
    tags: List[str] = []
    for variant in variants:
        for batch in SERVE_TRACE_BATCHES:
            where = f"serve {variant} forward (bucket {batch})"
            tags.append(where)
            colls = extract_collectives(trace_serve(variant, batch))
            if colls:
                kinds = sorted({c.kind for c in colls})
                findings.append(Finding(
                    rule="serve-collective",
                    where=where,
                    message=(
                        f"{len(colls)} collective(s) ({', '.join(kinds)}) "
                        f"leaked into a serve executable — serve replicas "
                        f"are independent, so a collective blocks on peers "
                        f"serving other requests and deadlocks the fleet "
                        f"the first time this bucket is hit"
                    ),
                    layer="collectives",
                ))
    return dedupe(findings), tags


def check_serve_hlo(variant: str, batch: int = 1) -> List[Finding]:
    """The ``--hlo`` tier for serve: AOT-compile one variant's bucket
    forward (GSPMD runs, nothing executes) and require the OPTIMIZED
    HLO to be collective-free too — XLA must not have introduced one
    behind the trace's back."""
    import jax

    fwd, variables, x = _serve_rig(variant, batch)
    compiled = jax.jit(fwd).lower(variables, x).compile()
    text = compiled.as_text()
    ops = {name for name in _HLO_COLLECTIVE_NAMES if name in text}
    if not ops:
        return []
    return [Finding(
        rule="serve-collective-hlo",
        where=f"serve {variant} forward (bucket {batch})",
        message=(
            f"optimized HLO contains {sorted(ops)} — the compiled serve "
            f"executable communicates; replicas must compile to "
            f"collective-free programs"
        ),
        layer="collectives",
    )]


def analyze_serve(variants: Sequence[str] = SERVE_VARIANTS,
                  hlo: bool = False) -> Tuple[List[Finding], List[str]]:
    """Every serve-variant check: trace-level collective-freedom, plus
    the compiled-HLO tier when ``hlo``."""
    findings, tags = check_serve_collective_free(variants)
    if hlo:
        for variant in variants:
            findings += check_serve_hlo(variant)
    return dedupe(findings), tags


# -- checks ------------------------------------------------------------------
def _combo_tag(method: str, schedule: Optional[str], kind: str) -> str:
    sched = f"/{schedule}" if schedule else ""
    return f"{method}{sched} {kind} step"


def check_axis_binding(colls, where: str) -> List[Finding]:
    findings = []
    for c in colls:
        unbound = [
            a for a in c.axes if isinstance(a, str) and a not in c.bound_axes
        ]
        if unbound:
            findings.append(Finding(
                rule="unbound-axis",
                where=where,
                message=(
                    f"{c.kind} names axis {unbound} but the enclosing mesh "
                    f"binds only {sorted(c.bound_axes)} — the collective "
                    f"cannot resolve at run time"
                ),
                layer="collectives",
            ))
    return findings


def check_ppermute_flow(colls, where: str) -> List[Finding]:
    """Bijectivity plus the send/recv simulation (docstring check b)."""
    findings = []
    for c in colls:
        if c.kind != "ppermute" or c.perm is None:
            continue
        srcs = [a for a, _ in c.perm]
        dsts = [b for _, b in c.perm]
        if len(set(srcs)) != len(srcs) or len(set(dsts)) != len(dsts):
            findings.append(Finding(
                rule="ppermute-bijection",
                where=where,
                message=(
                    f"ppermute perm {c.perm} is not a partial bijection "
                    f"(duplicate source or destination) — results are "
                    f"undefined"
                ),
                layer="collectives",
            ))
            continue
        src_set, dst_set = set(srcs), set(dsts)
        if c.producer_stage is not None and c.producer_stage not in src_set:
            findings.append(Finding(
                rule="ppermute-deadlock",
                where=where,
                message=(
                    f"tick-program deadlock: the payload is produced under "
                    f"the stage=={c.producer_stage} branch but ppermute "
                    f"perm {c.perm} never sends from stage "
                    f"{c.producer_stage} — its send is unposted and the "
                    f"receiving stage waits forever (flipped edge? the "
                    f"dynamic symptom is the 300 s CPU-rendezvous hang)"
                ),
                layer="collectives",
            ))
        for j in c.consumer_stages:
            if j not in dst_set:
                findings.append(Finding(
                    rule="ppermute-deadlock",
                    where=where,
                    message=(
                        f"tick-program deadlock: stage {j} consumes this "
                        f"ppermute's output but perm {c.perm} never "
                        f"delivers to stage {j} — unmatched recv; stage "
                        f"{j} would block on a payload that never arrives"
                    ),
                    layer="collectives",
                ))
    return findings


def check_uniform_branches(colls, where: str) -> List[Finding]:
    findings = []
    for c in colls:
        if c.axis_guarded:
            findings.append(Finding(
                rule="branch-divergent-collective",
                where=where,
                message=(
                    f"{c.kind} over {c.axes} sits inside a cond branch "
                    f"whose predicate depends on axis_index — devices "
                    f"along the axis would execute divergent collective "
                    f"sequences (rendezvous deadlock); hoist the "
                    f"collective out of the branch"
                ),
                layer="collectives",
            ))
    return findings


def _is_pipeline_method(method: str) -> bool:
    """Does this method (legacy name OR mesh spec) run the explicit
    stage schedules — i.e. does the schedule axis apply to it?"""
    return method in PIPELINE_STRATEGIES or spec_is_pipeline(method)


def _contract_requirements(
    method: str, schedule: Optional[str]
) -> Tuple[JaxprComm, ...]:
    """The comms contract for one method: the derived legacy table for
    strategy names, derived on the fly from the parsed spec for mesh
    configs — one rule engine either way."""
    if is_mesh_spec(method):
        cfg = parse_mesh_spec(method)
        return _derived_contract(cfg, schedule if cfg.is_pipeline else None)
    key = (method, schedule if method in PIPELINE_STRATEGIES else None)
    return JAXPR_CONTRACTS.get(key, ())


def _eval_contract_requirements(
    method: str, schedule: Optional[str]
) -> Tuple[JaxprComm, ...]:
    """The EVAL-step comms contract for one method — same resolution
    rule as :func:`_contract_requirements`, eval table/derivation."""
    if is_mesh_spec(method):
        cfg = parse_mesh_spec(method)
        return _derived_eval_contract(
            cfg, schedule if cfg.is_pipeline else None
        )
    key = (method, schedule if method in PIPELINE_STRATEGIES else None)
    return EVAL_JAXPR_CONTRACTS.get(key, ())


def check_contract(method: str, schedule: Optional[str], colls,
                   where: str, requirements=None) -> List[Finding]:
    """Enforce a derived comms contract against an extracted collective
    program. ``requirements`` defaults to the train-step contract;
    ``analyze_combo`` passes the eval table for eval traces."""
    findings = []
    if requirements is None:
        requirements = _contract_requirements(method, schedule)
    for req in requirements:
        candidates = [
            c for c in colls
            if c.kind == req.kind
            and (not req.grad_output or c.direct_output)
            and req.axes <= {a for a in c.axes if isinstance(a, str)}
        ]
        if not candidates:
            what = "output-feeding " if req.grad_output else ""
            findings.append(Finding(
                rule="comms-contract",
                where=where,
                message=(
                    f"declared contract violated: no {what}{req.kind} over "
                    f"axes covering {sorted(req.axes)} in the traced "
                    f"program ({req.why}) — "
                    + (
                        "a missing 'data' reduction silently forks the "
                        "data replicas"
                        if "data" in req.axes else
                        "the strategy degenerated from its declared "
                        "communication pattern"
                    )
                ),
                layer="collectives",
            ))
    return findings


def check_rank_invariance(method: str, schedule: Optional[str],
                          base_signatures) -> List[Finding]:
    """Re-trace the train step with ``jax.process_index`` patched to 1
    and diff the collective program against the rank-0 trace
    (``base_signatures``). Any difference means a Python-level
    rank-dependent branch reached a collective: the program is not
    provably SPMD-uniform."""
    import jax

    with unittest.mock.patch.object(jax, "process_index", lambda: 1):
        other = [c.signature for c in extract_collectives(
            trace_train(method, schedule))]
    if list(base_signatures) == other:
        return []
    n0, n1 = len(base_signatures), len(other)
    diff_at = next(
        (i for i, (a, b) in enumerate(zip(base_signatures, other)) if a != b),
        min(n0, n1),
    )
    return [Finding(
        rule="rank-divergent-collective",
        where=_combo_tag(method, schedule, "train"),
        message=(
            f"collective program differs between simulated ranks (rank 0: "
            f"{n0} collectives, rank 1: {n1}; first divergence at program "
            f"position {diff_at}) — a collective is guarded by a "
            f"process_index()/rank Python conditional, so real ranks would "
            f"trace different programs and deadlock at the first unmatched "
            f"collective; make the collective sequence rank-invariant"
        ),
        layer="collectives",
    )]


# -- collective fingerprints (the multi-process preflight's desync gate) ----
def program_fingerprint(colls: Sequence) -> str:
    """A short stable hash of an ORDERED collective program — kind,
    axes, permutation, enclosing-eqn context, and per-device payload
    bytes of every collective, in program order. The one definition
    shared by the multi-process desync gate (same program on every
    rank) and the planner's per-point provenance stamp (same program
    the plan was built from — the ``stale-plan`` rule's comparator)."""
    import hashlib

    payload = repr([(c.signature, c.payload_bytes) for c in colls])
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


def collective_fingerprint(method: str, schedule: Optional[str] = None,
                           process_index: int = 0) -> str:
    """One combo's :func:`program_fingerprint`, traced under the given
    simulated process identity. Two ranks whose fingerprints differ
    would trace different programs in a real launch and desync the
    gloo rendezvous at the first unmatched collective."""
    import jax

    with unittest.mock.patch.object(
        jax, "process_index", lambda: int(process_index)
    ):
        colls = extract_collectives(trace_train(method, schedule))
    return program_fingerprint(colls)


def check_collective_fingerprints(
    method: str, schedule: Optional[str], world: int
) -> Tuple[List[Finding], List[str]]:
    """Fingerprint one combo under ``world`` simulated ranks and flag
    any divergence (rule ``collective-fingerprint``). This generalizes
    the dual-rank re-trace to the job's ACTUAL world size: a collective
    gated on ``process_index() == 2`` traces identically on ranks 0 and
    1 — invisible to ``rank-divergent-collective`` — but desyncs a
    3-process launch; here it is caught before any rank spawns."""
    if _is_pipeline_method(method) and schedule is None:
        schedule = "gpipe"
    fps = [
        collective_fingerprint(method, schedule, r) for r in range(world)
    ]
    if len(set(fps)) <= 1:
        return [], fps
    divergent = sorted({r for r in range(world) if fps[r] != fps[0]})
    return [Finding(
        rule="collective-fingerprint",
        where=_combo_tag(method, schedule, "train"),
        message=(
            f"ordered-collective fingerprint diverges at simulated "
            f"rank(s) {divergent} of world {world} (rank 0: {fps[0]}) — "
            f"a Python-level rank conditional reaches a collective on "
            f"only some ranks, so a real {world}-process launch would "
            f"desync the gloo rendezvous at the first unmatched "
            f"collective; make the program identical on every rank"
        ),
        layer="collectives",
    )], fps


def fingerprint_combos(
    strategies: Sequence[str] = ANALYSIS_STRATEGIES,
    schedules: Sequence[str] = ANALYSIS_SCHEDULES,
    world: int = 2,
) -> Tuple[List[Finding], Dict[str, List[str]]]:
    """(findings, {combo tag: [per-rank fingerprint]}) for every
    requested combo — what ``analyze --fingerprint-world N`` reports and
    the elastic launch preflight compares before an N-process spawn.

    Accepted cost: the rank-0 trace here duplicates the one
    ``analyze_combo`` already ran in the same analyzer invocation (~2 s
    per combo). The preflight scopes to ONE combo, so the overlap stays
    a couple of seconds of its 300 s budget; reusing the program would
    mean threading extraction results through ``analyze``'s public
    return, which isn't worth it at this cost."""
    findings: List[Finding] = []
    table: Dict[str, List[str]] = {}
    for method, schedule in combos_for(strategies, schedules):
        tag = f"{method}/{schedule}" if schedule else method
        combo_findings, fps = check_collective_fingerprints(
            method, schedule, world
        )
        findings += combo_findings
        table[tag] = fps
    return dedupe(findings), table


# -- fingerprint snapshots (the cross-upgrade drift gate) --------------------
#: Artifact schema version; bump on incompatible payload changes.
SNAPSHOT_VERSION = 1


def _parse_combo_tag(tag: str) -> Tuple[str, Optional[str]]:
    """Invert ``fingerprint_combos``' combo tag: ``'MP/gpipe'`` →
    ``('MP', 'gpipe')``, ``'DP'`` → ``('DP', None)``. Methods never
    contain ``/`` (legacy names and ``DxMxS[@rule]`` specs alike)."""
    if "/" in tag:
        method, schedule = tag.rsplit("/", 1)
        return method, schedule
    return tag, None


def snapshot_fingerprints(
    strategies: Sequence[str] = ANALYSIS_STRATEGIES,
    schedules: Sequence[str] = ANALYSIS_SCHEDULES,
) -> dict:
    """The snapshot payload: every combo's rank-0 ordered-collective
    fingerprint plus the toolchain identity it was traced under. Written
    BEFORE a jax upgrade and checked after: a program that silently
    changed shape across the upgrade (a collective reordered, dropped,
    or re-axised by new tracing behavior) is exactly the drift the
    per-run contract check cannot see — both sides of the upgrade can be
    internally consistent yet different. Hybrid mesh specs fingerprint
    through the same surface (pass them in ``strategies``, as the CLI's
    ``--mesh`` merge does)."""
    import jax
    import jaxlib

    fingerprints: Dict[str, str] = {}
    for method, schedule in combos_for(strategies, schedules):
        tag = f"{method}/{schedule}" if schedule else method
        fingerprints[tag] = collective_fingerprint(method, schedule)
    return {
        "version": SNAPSHOT_VERSION,
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "fingerprints": fingerprints,
    }


def write_fingerprint_snapshot(
    path: str,
    strategies: Sequence[str] = ANALYSIS_STRATEGIES,
    schedules: Sequence[str] = ANALYSIS_SCHEDULES,
) -> dict:
    """Trace, fingerprint, and persist — returns the written payload."""
    import json

    payload = snapshot_fingerprints(strategies, schedules)
    with open(path, "w") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")
    return payload


def load_fingerprint_snapshot(path: str) -> Optional[dict]:
    """The persisted payload, or None when missing/corrupt/version-skewed
    — callers treat None as a bad invocation (rc 2), never as clean."""
    import json

    try:
        with open(path) as f:
            payload = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(payload, dict):
        return None
    if payload.get("version") != SNAPSHOT_VERSION:
        return None
    if not isinstance(payload.get("fingerprints"), dict):
        return None
    return payload


def check_fingerprint_snapshot(payload: dict) -> List[Finding]:
    """Re-trace every combo a snapshot records and flag drift (rule
    ``fingerprint-snapshot``): the current toolchain traces a DIFFERENT
    ordered-collective program than the one recorded — after a jax
    upgrade this is the audit trigger, not necessarily a bug, but it
    must never pass silently. Combos that no longer trace at all are
    flagged too (a refusal appearing where a program used to be is the
    loudest possible drift)."""
    import jax

    recorded_jax = payload.get("jax", "unknown")
    current_jax = jax.__version__
    toolchain = (
        f"recorded under jax {recorded_jax}, current jax {current_jax}"
    )
    findings: List[Finding] = []
    for tag in sorted(payload["fingerprints"]):
        recorded = payload["fingerprints"][tag]
        method, schedule = _parse_combo_tag(tag)
        try:
            current = collective_fingerprint(method, schedule)
        except Exception as exc:  # noqa: BLE001 — refusal IS the drift
            findings.append(Finding(
                rule="fingerprint-snapshot",
                where=_combo_tag(method, schedule, "train"),
                message=(
                    f"combo no longer traces ({type(exc).__name__}: "
                    f"{exc}) — {toolchain}; if the combo was removed "
                    f"on purpose, re-write the snapshot"
                ),
                layer="collectives",
            ))
            continue
        if current != recorded:
            findings.append(Finding(
                rule="fingerprint-snapshot",
                where=_combo_tag(method, schedule, "train"),
                message=(
                    f"ordered-collective fingerprint drifted: recorded "
                    f"{recorded} != current {current} ({toolchain}) — "
                    f"the traced program changed shape across the "
                    f"toolchain change; audit the program diff, then "
                    f"re-write the snapshot to accept it"
                ),
                layer="collectives",
            ))
    return dedupe(findings)


# -- HLO tier (opt-in: AOT compile, still zero execution) --------------------
_HLO_COLLECTIVE_NAMES = (
    "all-reduce", "all-gather", "reduce-scatter", "collective-permute",
    "all-to-all",
)


def compile_train_step_aot(strategy, model, tx, state, batch):
    """AOT-compile the strategy's jitted train step over sharding-pinned
    ``ShapeDtypeStruct``s — the GSPMD partitioner runs, nothing executes,
    no device memory is committed. THE pin-and-compile rig, shared by the
    ``--hlo`` contract tier here and the auto-planner's memory/flops
    probe (analysis/planner.py): a change to how a strategy's state or
    batch shardings are pinned must reach both, or plans would silently
    rank a wrongly-pinned program."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = strategy.mesh
    if mesh is not None:
        leaf_spec = getattr(strategy, "_leaf_spec", lambda shape: P())

        def with_sharding(leaf, spec):
            return jax.ShapeDtypeStruct(
                leaf.shape, leaf.dtype, sharding=NamedSharding(mesh, spec)
            )

        state = jax.tree.map(
            lambda x: with_sharding(x, leaf_spec(x.shape)), state
        )
        batch = {
            k: with_sharding(v, strategy.batch_sharding.spec)
            for k, v in batch.items()
        }
    return strategy.build_train_step(model, tx).lower(state, batch).compile()


def hlo_collectives(method: str, schedule: Optional[str] = None) -> set:
    """Collective op names in the optimized HLO of the strategy's
    compiled train step (ahead-of-time via ``compile_train_step_aot``)."""
    strategy, model, state, tx, batch = _build(method, schedule)
    if strategy.mesh is None:
        return set()
    compiled = compile_train_step_aot(strategy, model, tx, state, batch)
    text = compiled.as_text()
    return {name for name in _HLO_COLLECTIVE_NAMES if name in text}


def check_hlo_contract(method: str, schedule: Optional[str]) -> List[Finding]:
    where = _combo_tag(method, schedule, "compiled train")
    ops = hlo_collectives(method, schedule)
    required = EXPECTED_HLO_COLLECTIVES.get(method)
    any_of_tier = method == "TP"
    if required is None and is_mesh_spec(method):
        # mesh specs derive their HLO contract from the parsed rules;
        # a channel model axis adds the any-of tier ON TOP of the exact
        # set — a DP x TP hybrid whose data all-reduce regresses away
        # must fail even while its channel collectives satisfy any-of
        cfg = parse_mesh_spec(method)
        required = derive_hlo_contract(cfg)
        any_of_tier = channel_comms_required(cfg)
    findings: List[Finding] = []
    if any_of_tier and not (ops & TP_HLO_ANY_OF):
        findings.append(Finding(
            rule="comms-contract-hlo",
            where=where,
            message=(
                f"optimized HLO contains none of "
                f"{sorted(TP_HLO_ANY_OF)} — TP's sharded channels are "
                f"not actually communicating (degenerated to "
                f"replication?); found {sorted(ops)}"
            ),
            layer="collectives",
        ))
    if required and not required <= ops:
        findings.append(Finding(
            rule="comms-contract-hlo",
            where=where,
            message=(
                f"optimized HLO is missing {sorted(required - ops)} (found "
                f"{sorted(ops)}) — the strategy silently degenerated: its "
                f"parallelism implies that communication"
            ),
            layer="collectives",
        ))
    return findings


# -- drivers -----------------------------------------------------------------
def combos_for(strategies: Sequence[str] = ANALYSIS_STRATEGIES,
               schedules: Sequence[str] = ANALYSIS_SCHEDULES
               ) -> List[Tuple[str, Optional[str]]]:
    combos: List[Tuple[str, Optional[str]]] = []
    for method in strategies:
        if _is_pipeline_method(method):
            combos.extend((method, s) for s in schedules)
        else:
            combos.append((method, None))
    return combos


def analyze_combo(method: str, schedule: Optional[str] = None,
                  hlo: bool = False, rank_check: bool = True
                  ) -> List[Finding]:
    """Run every layer-1 check for one strategy × schedule combo.
    Trace-only unless ``hlo``; zero device execution either way."""
    if _is_pipeline_method(method) and schedule is None:
        # the trace rig defaults a missing schedule to gpipe; the
        # contract key must name the program actually traced, or the
        # ('MP', None) lookup misses JAXPR_CONTRACTS and the
        # comms-contract check silently becomes vacuous
        schedule = "gpipe"
    findings: List[Finding] = []

    try:
        train_jaxpr = trace_train(method, schedule)
    except ValueError as exc:
        if is_mesh_spec(method):
            # a mesh spec that cannot BUILD (model x stage, divisibility,
            # device count) is a CONFIG refusal, not an analyzer crash:
            # report it as a finding so the launch preflights (elastic,
            # bench_multi) refuse the geometry pre-spawn with the reason,
            # and an `analyze --mesh` run keeps its other combos' results
            return [Finding(
                rule="mesh-config",
                where=_combo_tag(method, schedule, "train"),
                message=(
                    f"mesh config cannot build on the analysis rig: "
                    f"{exc}"
                ),
                layer="collectives",
            )]
        raise
    train_colls = extract_collectives(train_jaxpr)
    where = _combo_tag(method, schedule, "train")
    findings += check_axis_binding(train_colls, where)
    findings += check_ppermute_flow(train_colls, where)
    findings += check_uniform_branches(train_colls, where)
    findings += check_contract(method, schedule, train_colls, where)

    eval_colls = extract_collectives(trace_eval(method, schedule))
    where_e = _combo_tag(method, schedule, "eval")
    findings += check_axis_binding(eval_colls, where_e)
    findings += check_ppermute_flow(eval_colls, where_e)
    findings += check_uniform_branches(eval_colls, where_e)
    findings += check_contract(
        method, schedule, eval_colls, where_e,
        requirements=_eval_contract_requirements(method, schedule),
    )

    if rank_check:
        findings += check_rank_invariance(
            method, schedule, [c.signature for c in train_colls]
        )
    if hlo:
        findings += check_hlo_contract(method, schedule)
    return dedupe(findings)


def analyze(strategies: Sequence[str] = ANALYSIS_STRATEGIES,
            schedules: Sequence[str] = ANALYSIS_SCHEDULES,
            hlo: bool = False, rank_check: bool = True):
    """Analyze every requested combo; returns ``(findings, combo_tags)``."""
    findings: List[Finding] = []
    tags = []
    for method, schedule in combos_for(strategies, schedules):
        tags.append(f"{method}/{schedule}" if schedule else method)
        findings += analyze_combo(
            method, schedule, hlo=hlo, rank_check=rank_check
        )
    return findings, tags
