"""Layer 2: project-specific AST lint over the package source.

Pure ``ast`` — no jax import, so jax-free processes (the elastic
supervisor) and cold CI jobs can run it in milliseconds. Rules (catalog
with rationale and what each provably excludes: docs/ANALYSIS.md):

* ``trace-nondeterminism`` — ``time.time``/``random.*``/``np.random.*``
  (and friends) inside functions that end up traced by jax. A traced
  call executes ONCE at trace time and freezes its value into the
  compiled program: what looks like per-step randomness is a constant,
  and what looks like a timestamp is the compile time. Traced functions
  are detected as: arguments to jit/shard_map/grad/cond/scan/... calls,
  functions decorated with jit/checkpoint, anything nested in either,
  and anything nested in a ``make_*`` builder (this repo's idiom: every
  ``make_*`` in the package returns a function the strategies jit).

* ``host-sync-hot-path`` — ``.item()``, ``block_until_ready``,
  ``np.asarray``/``jax.device_get`` in the step hot path (the loop
  bodies nested in ``Trainer.train``): each forces a device→host sync
  that stalls the async step pipeline PR 1 built. Sanctioned drain
  points (``LossRecords``' parked-row pulls, nested fns named ``pull``)
  are exempt; ``.item()``/``block_until_ready`` are additionally flagged
  package-wide outside the sanctioned drain modules.

* ``serve-hot-path`` — the same blocking-sync family (``.item()``,
  ``block_until_ready``, ``np.asarray``/``jax.device_get``) inside the
  serving tier's dispatch pipeline (the functions named in
  ``SERVE_HOT_PATH_SCOPES``, serve/server.py): one sync there stalls
  EVERY in-flight request on every replica, not just one step — the
  continuous-batching design routes all device→host reads through the
  completion drain (``pull``), which is the sanctioned exemption,
  mirroring the train-side rule's mechanism.

* ``use-after-donation`` — a value passed in donated position (argument
  0 of a ``*train_step``/``multi_step``/``accum_step`` call) is deleted
  device memory after the call; reading it — or an alias bound from it
  before the call — afterwards is a use-after-free on accelerators.

* ``rank-gated-collective`` — a collective call lexically under an
  ``if``/``while``/ternary whose test calls ``process_index()``: ranks
  would trace different collective programs and deadlock at the first
  unmatched one. (The jaxpr layer proves the same property dynamically
  via dual-rank tracing; this rule points at the exact source line.)

* ``dtype-policy`` — the mixed-precision policy's cast-boundary contract
  (ops/precision.py, docs/PERFORMANCE.md "Precision"): a bare
  ``jnp.float32``/``np.float32`` literal (or ``astype("float32")``)
  inside a traced function is an upcast the ``--dtype`` policies cannot
  see — under bf16 it silently re-widens a hot-path tensor, under
  bf16_params it forks the param dtype mid-trace. The rule reaches
  Pallas KERNEL BODIES (functions handed to ``pallas_call``) and
  custom-VJP forward/backward bodies (``defvjp``): kernel accumulators
  must spell the contract by NAME (``precision.LOSS_DTYPE`` /
  ``WGRAD_DTYPE`` / ``REDUCE_DTYPE`` / ``NORM_DTYPE``) — the kernel
  modules comply and are no longer blanket-exempt; only the loss/quant/
  structured-conv modules whose f32 IS the policy remain sanctioned.

* ``ckpt-dtype-drift`` — donation-aware save/restore dtype drift: a
  ``load_checkpoint``/``load_weights`` call whose enclosing function
  never routes the result through the policy's restore seams
  (``ensure_restored_dtypes`` / ``convert_checkpoint_state``) can hand
  the step params whose dtype differs from the session policy — the
  jitted step would silently RETRACE against the drifted layout (and its
  donated buffers), instead of re-casting loudly or failing.

* ``obs-hot-path`` — the telemetry layer's hot-path contract
  (distributedpytorch_tpu/obs, docs/OBSERVABILITY.md): (a) record paths
  inside ``obs/`` (functions named ``record*``/``inc``/``observe``/
  ``set``/``span``) must not block on a device value (the blocking-sync
  family) and must not grow without bound — a bare ``list.append`` is
  flagged unless the target was constructed as a ``deque(maxlen=...)``
  in the same file (the ring-slot contract); (b) package-wide, any
  telemetry call (``obs.`` / ``obsm.`` / ``flight.`` dotted prefixes)
  inside a jit/shard_map-traced function is flagged — it would execute
  once at trace time and record nothing (or bake a host side effect
  into the compiled program).

* ``serve-donation`` — a ``jit(..., donate_argnums=...)`` (or
  ``donate_argnames``) call inside a serve module. Serve executables
  re-read their weights operand on every request, rollbacks re-read
  pre-swap snapshots, and AOT-store siblings rehydrate shared buffers —
  donation anywhere in the serving tier is a use-after-free waiting for
  a backend that honors it (the CPU donation SIGABRT class). The
  engine's one sanctioned wrapper is ``serve/engine.serve_jit``, which
  never donates; the jaxpr tier (analysis/donation.py) proves the
  lowered executables clean, this rule points at the source line of
  any wrapper that would bypass it.

Suppression: append ``# dptlint: disable=<rule>[,<rule>...]`` (or
``disable=all``) to the offending line, with a justification.
Suppressions are themselves linted: naming a rule this linter does not
define is an ``unknown-suppression`` finding (likely a typo silently
suppressing nothing), and suppressing a rule that no longer fires on
that line is a ``stale-suppression`` finding — dead suppressions hide
future regressions on the lines they squat on.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from distributedpytorch_tpu.analysis import Finding

#: Call names whose function-valued arguments get traced by jax.
#: ``pallas_call`` makes Pallas KERNEL BODIES traced scopes (a bare f32
#: accumulator inside one is exactly the drift the dtype-policy rule
#: exists for); ``defvjp`` reaches hand-written custom-VJP forward and
#: backward bodies the same way.
TRACE_ENTRYPOINTS = frozenset({
    "jit", "pmap", "vmap", "grad", "value_and_grad", "vjp", "jvp",
    "checkpoint", "remat", "cond", "switch", "scan", "while_loop",
    "shard_map", "eval_shape", "make_jaxpr", "custom_vjp", "custom_jvp",
    "fori_loop", "associative_scan", "named_call", "pallas_call",
    "defvjp",
})

#: Decorators that make the decorated function traced.
TRACED_DECORATORS = frozenset({"jit", "checkpoint", "remat", "custom_vjp",
                               "custom_jvp"})

#: Which positional args of each entrypoint are callables that get
#: traced (default: arg 0). Data operands (scan's init/xs, cond's
#: operands) must NOT be marked — a data variable named like a host
#: function elsewhere in the module would otherwise poison that
#: function as "traced".
CALLABLE_ARG_POSITIONS = {
    "cond": (1, 2),       # cond(pred, true_fn, false_fn, *operands)
    "switch": (1,),       # switch(index, branches, *operands)
    "while_loop": (0, 1),
    "fori_loop": (2,),
    "defvjp": (0, 1),     # f.defvjp(fwd, bwd) — both bodies trace
}
#: Keyword names that carry callables into trace entrypoints.
CALLABLE_KEYWORDS = frozenset({"f", "fun", "fn", "body", "body_fun",
                               "cond_fun", "branches"})

#: Dotted-path prefixes/exacts that are nondeterministic under trace.
NONDET_EXACT = frozenset({
    "time.time", "time.time_ns", "time.monotonic", "time.perf_counter",
    "os.urandom", "uuid.uuid4", "uuid.uuid1",
})
NONDET_PREFIXES = ("random.", "np.random.", "numpy.random.")

#: Collective-issuing call names (terminal attribute) for the rank rule.
COLLECTIVE_CALLS = frozenset({
    "psum", "pmean", "pmin", "pmax", "ppermute", "all_gather",
    "all_to_all", "psum_scatter", "reduce_scatter", "process_allgather",
    "pbroadcast",
})

#: Hot-path scope: (path suffix, enclosing function name). Everything
#: lexically nested inside these functions is the step hot path.
HOT_PATH_SCOPES: Tuple[Tuple[str, str], ...] = (
    (os.path.join("train", "loop.py"), "train"),
)
#: Nested helpers inside the hot path that ARE the sanctioned drain
#: points (LossRecords' lazy device→host pulls).
SANCTIONED_DRAIN_FNS = frozenset({"pull"})
#: Modules whose whole job is draining device values to the host —
#: .item()/block_until_ready are legitimate there.
SANCTIONED_SYNC_MODULES = (
    "checkpoint.py", "evaluate.py",
    os.path.join("utils", "metrics.py"),
    os.path.join("utils", "trace.py"),
)
HOT_SYNC_CALLS = frozenset({"np.asarray", "np.array", "numpy.asarray",
                            "numpy.array", "jax.device_get", "device_get"})

#: Serve-tier hot path: (path suffix, function name) of the dispatch
#: pipeline in serve/server.py — the flush stream, the placement
#: callback, and the dispatch loop itself. Unlike the train hot path
#: (one step stalled), a host sync here serializes the WHOLE serving
#: pipeline: every queued bucket on every replica waits behind it.
SERVE_HOT_PATH_SCOPES: Tuple[Tuple[str, str], ...] = (
    (os.path.join("serve", "server.py"), "_dispatch_loop"),
    (os.path.join("serve", "server.py"), "_place"),
    (os.path.join("serve", "server.py"), "_bucket_stream"),
)
#: The serve tier's sanctioned drain: completion workers (``pull``) are
#: WHERE device results become host masks — blocking is their job.
SERVE_SANCTIONED_DRAIN_FNS = frozenset({"pull"})

#: Terminal names of calls that donate their first argument's buffers —
#: the jitted step family the strategies build with donate_argnums
#: (train/loop.py binds them as self.train_step/multi_step/accum_step).
#: Deliberately NOT the `build_*`/`make_*` builders: those take (model,
#: tx) and donate nothing.
DONATING_CALLS = frozenset({"train_step", "multi_step", "accum_step"})


#: Bare f32 dtype spellings (rule ``dtype-policy``): inside a traced
#: function these are accidental upcasts the --dtype policy cannot see;
#: the sanctioned spellings are the named contract constants
#: (precision.LOSS_DTYPE / WGRAD_DTYPE / REDUCE_DTYPE).
F32_LITERAL_DOTTED = frozenset({
    "jnp.float32", "jax.numpy.float32", "np.float32", "numpy.float32",
})
#: Modules whose f32 literals ARE the policy: the precision module
#: itself, the loss family (f32 loss/stats is the LOSS_DTYPE contract's
#: implementation), and the structured-conv rewrites. The Pallas kernel
#: modules (ops/{pallas_kernels,wgrad_pallas,fused_loss,kernels}.py)
#: are deliberately NOT here: the rule reaches kernel bodies (via the
#: ``pallas_call``/``defvjp`` entrypoints above) and their accumulators
#: spell the named contract constants (LOSS_DTYPE/WGRAD_DTYPE/
#: NORM_DTYPE) — a bare f32 there is drift, not policy.
DTYPE_POLICY_SANCTIONED_MODULES = (
    os.path.join("ops", "precision.py"),
    os.path.join("ops", "losses.py"),
    os.path.join("ops", "quant.py"),
    os.path.join("ops", "conv_backward.py"),
    os.path.join("ops", "s2d.py"),
)

#: Checkpoint-restore entry points (rule ``ckpt-dtype-drift``) and the
#: precision-policy seams their enclosing function must route through.
CKPT_RESTORE_CALLS = frozenset({"load_checkpoint", "load_weights"})
CKPT_RESTORE_SEAMS = frozenset({
    "ensure_restored_dtypes", "convert_checkpoint_state",
})
#: checkpoint.py defines the loaders (its internal format dispatch calls
#: load_checkpoint without a session policy in scope — the seam is its
#: CALLERS' obligation).
CKPT_RULE_EXEMPT_MODULES = ("checkpoint.py",)

#: The obs record-path scope (rule ``obs-hot-path``): functions with
#: these names (or any ``record*``/``mark*``) inside ``obs/`` modules
#: are the always-on recording paths — one ring slot / one counter bump
#: is the whole allocation budget, and nothing there may touch a device
#: value. ``mark*`` and the completion verbs cover obs/reqtrace.py's
#: request-trace lifecycle: ``mark_*`` stamps ride the serve dispatch
#: hot path, and ``begin``/``complete``/``finish``/``reject`` are the
#: per-request ledger paths whose appends must be bounded rings.
OBS_RECORD_FN_NAMES = frozenset({
    "inc", "observe", "set", "span", "fire",
    "begin", "complete", "finish", "reject",
})
#: Dotted-prefix spellings of telemetry calls (``from ...obs import
#: flight``, ``from ...obs import defs as obsm``, ``obs.flight.record``)
#: that must never appear inside a traced function.
OBS_CALL_PREFIXES = ("obs.", "obsm.", "flight.")


def _is_obs_module(rel_path: str) -> bool:
    sep = rel_path.replace("\\", "/")
    return "/obs/" in sep or sep.startswith("obs/")


def _is_serve_module(rel_path: str) -> bool:
    sep = rel_path.replace("\\", "/")
    return "/serve/" in sep or sep.startswith("serve/")


def _is_obs_record_fn(name: str) -> bool:
    return name.startswith(("record", "mark")) or name in OBS_RECORD_FN_NAMES


def _bounded_append_targets(tree: ast.AST) -> Set[str]:
    """Names/attribute chains assigned from a ``deque(..., maxlen=...)``
    call anywhere in the file — appends to THOSE are bounded by
    construction (the ring-slot idiom obs-hot-path sanctions)."""
    bounded: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            call, targets = node.value, list(node.targets)
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            # `self._events: deque = deque(maxlen=...)` — the ring idiom
            call, targets = node.value, [node.target]
        else:
            continue
        if not isinstance(call, ast.Call) or _terminal(call.func) != "deque":
            continue
        if not any(kw.arg == "maxlen" for kw in call.keywords):
            continue
        for t in targets:
            key = _expr_key(t)
            if key:
                # `self._events` assigned in __init__ is read as
                # `self._events` at the append site too
                bounded.add(key)
    return bounded


def _donating_call(terminal: str) -> bool:
    return terminal in DONATING_CALLS


#: Every rule this linter can emit — the vocabulary a ``dptlint:
#: disable=`` comment may name. A suppression outside this set is a
#: typo that suppresses nothing (rule ``unknown-suppression``).
KNOWN_RULES = frozenset({
    "parse-error", "trace-nondeterminism", "host-sync-hot-path",
    "serve-hot-path", "use-after-donation", "rank-gated-collective",
    "dtype-policy", "ckpt-dtype-drift", "obs-hot-path", "serve-donation",
})

_SUPPRESS_RE = re.compile(
    r"#\s*dptlint:\s*disable=([\w\-]+(?:\s*,\s*[\w\-]+)*)"
)


def _suppressions(source: str) -> Dict[int, Set[str]]:
    out: Dict[int, Set[str]] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if m:
            out[i] = {r.strip() for r in m.group(1).split(",") if r.strip()}
    return out


def _dotted(node: ast.AST) -> Optional[str]:
    """``np.random.default_rng`` -> "np.random.default_rng"; None when
    the expression is not a plain name/attribute chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _terminal(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _expr_key(node: ast.AST) -> Optional[str]:
    """Stable key for name/attribute chains ("state", "self.state")."""
    return _dotted(node)


@dataclasses.dataclass
class _FnInfo:
    node: ast.AST
    name: str
    parent: Optional[ast.AST]  # enclosing function node (not class)
    traced: bool = False


class _Scopes(ast.NodeVisitor):
    """Function table with parent links plus the traced-function set."""

    def __init__(self):
        self.fns: Dict[ast.AST, _FnInfo] = {}
        self._stack: List[ast.AST] = []
        self.traced_names: Set[str] = set()

    def _enter(self, node, name):
        parent = self._stack[-1] if self._stack else None
        self.fns[node] = _FnInfo(node=node, name=name, parent=parent)
        self._stack.append(node)
        self.generic_visit(node)
        self._stack.pop()

    def visit_FunctionDef(self, node):
        self._enter(node, node.name)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        self._enter(node, "<lambda>")

    def visit_Call(self, node):
        term = _terminal(node.func)
        if term in TRACE_ENTRYPOINTS:
            positions = CALLABLE_ARG_POSITIONS.get(term, (0,))
            candidates = [
                node.args[i] for i in positions if i < len(node.args)
            ] + [
                kw.value for kw in node.keywords
                if kw.arg in CALLABLE_KEYWORDS
            ]
            flat = []
            for arg in candidates:
                # switch's branches (and the `branches=` keyword) arrive
                # as a literal list/tuple of callables — unpack it
                if isinstance(arg, (ast.List, ast.Tuple)):
                    flat.extend(arg.elts)
                else:
                    flat.append(arg)
            for arg in flat:
                if isinstance(arg, ast.Name):
                    self.traced_names.add(arg.id)
                elif isinstance(arg, ast.Lambda):
                    # the Lambda node is visited after this call; mark it
                    # by identity and resolve in _mark_traced
                    self.traced_names.add(id(arg))  # type: ignore[arg-type]
        self.generic_visit(node)


def _mark_traced(scopes: _Scopes) -> None:
    for info in scopes.fns.values():
        node = info.node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if info.name in scopes.traced_names:
                info.traced = True
            for dec in node.decorator_list:
                base = dec.func if isinstance(dec, ast.Call) else dec
                if _terminal(base) in TRACED_DECORATORS:
                    info.traced = True
        if isinstance(node, ast.Lambda) and id(node) in scopes.traced_names:
            info.traced = True
    # propagate: nested in a traced fn, or nested in a make_* builder
    changed = True
    while changed:
        changed = False
        for info in scopes.fns.values():
            if info.traced:
                continue
            parent = info.parent
            while parent is not None:
                pinfo = scopes.fns[parent]
                if pinfo.traced or pinfo.name.startswith("make_"):
                    info.traced = True
                    changed = True
                    break
                parent = pinfo.parent


def _enclosing_chain(scopes: _Scopes, node_to_fn: Dict[int, ast.AST],
                     node: ast.AST) -> List[_FnInfo]:
    """Innermost-first chain of enclosing functions for a node."""
    fn = node_to_fn.get(id(node))
    chain = []
    while fn is not None:
        info = scopes.fns[fn]
        chain.append(info)
        fn = info.parent
    return chain


def lint_source(source: str, rel_path: str) -> List[Finding]:
    """Lint one file's source. ``rel_path`` appears in findings and
    drives the path-scoped rules."""
    try:
        tree = ast.parse(source)
    except SyntaxError as exc:
        return [Finding(
            rule="parse-error", where=f"{rel_path}:{exc.lineno or 0}",
            message=f"file does not parse: {exc.msg}", layer="lint",
        )]
    suppressed = _suppressions(source)
    scopes = _Scopes()
    scopes.visit(tree)
    _mark_traced(scopes)

    # node -> innermost enclosing function node
    node_to_fn: Dict[int, ast.AST] = {}

    def index(node, current):
        for child in ast.iter_child_nodes(node):
            nxt = current
            if child in scopes.fns:
                nxt = child
            node_to_fn[id(child)] = current
            index(child, nxt)

    index(tree, None)  # type: ignore[arg-type]

    findings: List[Finding] = []
    # (line, rule-name) pairs a suppression actually absorbed — the
    # complement at the end is the stale-suppression report
    used_suppressions: Set[Tuple[int, str]] = set()

    def emit(rule: str, node: ast.AST, message: str):
        line = getattr(node, "lineno", 0)
        rules = suppressed.get(line, set())
        if rule in rules or "all" in rules:
            used_suppressions.add((line, rule if rule in rules else "all"))
            return
        findings.append(Finding(
            rule=rule, where=f"{rel_path}:{line}", message=message,
            layer="lint",
        ))

    in_obs_module = _is_obs_module(rel_path)
    in_serve_module = _is_serve_module(rel_path)
    dtype_sanctioned_file = any(
        rel_path.endswith(sfx) for sfx in DTYPE_POLICY_SANCTIONED_MODULES
    )
    ckpt_rule_exempt_file = any(
        rel_path.endswith(sfx) for sfx in CKPT_RULE_EXEMPT_MODULES
    )
    bounded_appends = _bounded_append_targets(tree) if in_obs_module else set()
    in_hot_file = any(rel_path.endswith(sfx) for sfx, _fn in HOT_PATH_SCOPES)
    hot_fn_names = {fn for sfx, fn in HOT_PATH_SCOPES
                    if rel_path.endswith(sfx)}
    serve_fn_names = {fn for sfx, fn in SERVE_HOT_PATH_SCOPES
                      if rel_path.endswith(sfx)}
    sync_sanctioned_file = any(
        rel_path.endswith(sfx) for sfx in SANCTIONED_SYNC_MODULES
    )

    def _scoped_context(chain: List[_FnInfo], scope_names: Set[str],
                        drain_names: FrozenSet[str]) -> bool:
        """Inside one of ``scope_names`` and not inside a sanctioned
        drain — the shared mechanism of both hot-path rules."""
        if not scope_names:
            return False
        names = [info.name for info in chain]
        if any(n in drain_names for n in names):
            return False
        return any(n in scope_names for n in names)

    def hot_context(chain: List[_FnInfo]) -> bool:
        if not in_hot_file:
            return False
        return _scoped_context(chain, hot_fn_names, SANCTIONED_DRAIN_FNS)

    def serve_hot_context(chain: List[_FnInfo]) -> bool:
        return _scoped_context(
            chain, serve_fn_names, SERVE_SANCTIONED_DRAIN_FNS
        )

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        chain = _enclosing_chain(scopes, node_to_fn, node)
        dotted = _dotted(node.func)
        term = _terminal(node.func)

        # -- trace-nondeterminism
        traced = any(info.traced for info in chain)
        if traced and dotted is not None:
            if dotted in NONDET_EXACT or any(
                dotted.startswith(p) for p in NONDET_PREFIXES
            ):
                emit(
                    "trace-nondeterminism", node,
                    f"`{dotted}` inside a traced function: it runs ONCE "
                    f"at trace time and bakes a constant into the "
                    f"compiled step — thread host randomness/time in as "
                    f"an argument instead",
                )

        # -- host-sync: package-wide block_until_ready (both the method
        # form `x.block_until_ready()` and the function form
        # `jax.block_until_ready(x)`) and zero-arg `.item()`
        blocks = term == "block_until_ready" or (
            term == "item"
            and isinstance(node.func, ast.Attribute)
            and not node.args
        )
        if blocks and not sync_sanctioned_file:
            emit(
                "host-sync-hot-path", node,
                f"`{dotted or term}` forces a device→host sync; only "
                f"the sanctioned drain modules "
                f"({', '.join(SANCTIONED_SYNC_MODULES)}) may block on "
                f"device values",
            )

        # -- host-sync: hot-path scoped np.asarray/device_get
        if dotted in HOT_SYNC_CALLS and hot_context(chain):
            emit(
                "host-sync-hot-path", node,
                f"`{dotted}` in the step hot path stalls the async "
                f"dispatch pipeline (one device sync per step) — route "
                f"the value through LossRecords' parked-row drain or a "
                f"sanctioned `pull` helper",
            )

        # -- serve-hot-path: any blocking sync in the serve dispatch
        # pipeline (flush stream / placement / dispatch loop) outside
        # the completion drain
        if (blocks or dotted in HOT_SYNC_CALLS) and serve_hot_context(chain):
            emit(
                "serve-hot-path", node,
                f"`{dotted or term}` blocks on a device value inside the "
                f"serve dispatch pipeline — every queued bucket on every "
                f"replica stalls behind it; device→host reads belong in "
                f"the completion drain (`pull`), which resolves request "
                f"futures off the dispatch path",
            )

        # -- obs-hot-path (a): obs record paths must not block or grow
        # unboundedly — the always-on contract is one ring slot / one
        # counter bump per event (docs/OBSERVABILITY.md)
        in_obs_record = in_obs_module and any(
            _is_obs_record_fn(info.name) for info in chain
        )
        if in_obs_record and (blocks or dotted in HOT_SYNC_CALLS):
            emit(
                "obs-hot-path", node,
                f"`{dotted or term}` blocks on a device value inside an "
                f"obs record path — telemetry is always-on and rides hot "
                f"loops; record host-computed values only",
            )
        if (
            in_obs_record
            and term == "append"
            and isinstance(node.func, ast.Attribute)
        ):
            target = _expr_key(node.func.value)
            if target is not None and target not in bounded_appends:
                emit(
                    "obs-hot-path", node,
                    f"`{target}.append` in an obs record path grows "
                    f"without bound — always-on recording must be a "
                    f"ring: construct `{target}` as "
                    f"`deque(maxlen=...)`",
                )

        # -- dtype-policy (b): astype("float32") / dtype="float32" string
        # spellings in traced code — same hazard as the dotted literal
        # form handled in the node walk below
        if traced and not dtype_sanctioned_file:
            string_f32 = (
                term == "astype"
                and len(node.args) == 1
                and isinstance(node.args[0], ast.Constant)
                and node.args[0].value == "float32"
            ) or any(
                kw.arg == "dtype"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value == "float32"
                for kw in node.keywords
            )
            if string_f32:
                emit(
                    "dtype-policy", node,
                    "bare \"float32\" dtype inside a traced function is an "
                    "upcast the --dtype policy cannot see — spell the "
                    "contract (precision.LOSS_DTYPE / WGRAD_DTYPE / "
                    "REDUCE_DTYPE) or thread the policy",
                )

        # -- serve-donation: a donating jit wrapper anywhere in the
        # serving tier — serve executables re-read every operand
        # (request path, swap snapshots, store rehydration), so a
        # donated buffer is a use-after-free on any backend that
        # honors it; the one sanctioned wrapper (engine.serve_jit)
        # never donates
        if in_serve_module and term == "jit" and any(
            kw.arg in ("donate_argnums", "donate_argnames")
            for kw in node.keywords
        ):
            emit(
                "serve-donation", node,
                "`jit(..., donate_*)` in a serve module: serve "
                "executables re-read their operands (every request, "
                "rollback snapshots, AOT-store rehydration), so a "
                "donated buffer is freed under a future read — lower "
                "through serve/engine.serve_jit, which never donates",
            )

        # -- obs-hot-path (b): telemetry calls inside traced functions
        # execute ONCE at trace time — the metric/event silently never
        # records (and a constant side effect bakes into the program)
        if traced and dotted is not None and dotted.startswith(
            OBS_CALL_PREFIXES
        ):
            emit(
                "obs-hot-path", node,
                f"`{dotted}` inside a jit/shard_map-traced function runs "
                f"once at trace time and never again — record from the "
                f"host loop (or a drain) instead",
            )

    # -- dtype-policy (a): bare jnp.float32/np.float32 literal loads in
    # traced functions — the accidental-upcast form (an astype arg, a
    # zeros/full dtype operand). The sanctioned spelling is the named
    # precision constant; the sanctioned modules implement the contract.
    if not dtype_sanctioned_file:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Attribute):
                continue
            if not isinstance(getattr(node, "ctx", None), ast.Load):
                continue
            if _dotted(node) not in F32_LITERAL_DOTTED:
                continue
            chain = _enclosing_chain(scopes, node_to_fn, node)
            if any(info.traced for info in chain):
                emit(
                    "dtype-policy", node,
                    f"bare `{_dotted(node)}` inside a traced function is "
                    f"an f32 upcast the --dtype policy cannot see (bf16 "
                    f"silently re-widens, bf16_params forks the param "
                    f"dtype mid-trace) — spell the contract via "
                    f"precision.LOSS_DTYPE / WGRAD_DTYPE / REDUCE_DTYPE "
                    f"or thread the policy",
                )

    # -- ckpt-dtype-drift: checkpoint restores that bypass the precision
    # policy's restore seams. The enclosing function of every
    # load_checkpoint/load_weights call must also call
    # ensure_restored_dtypes or convert_checkpoint_state (anywhere in its
    # subtree — the seam usually guards the result a few lines later);
    # otherwise params of a drifted dtype flow into the jitted step,
    # which silently RETRACES against donated buffers of the old layout.
    if not ckpt_rule_exempt_file:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            if _terminal(node.func) not in CKPT_RESTORE_CALLS:
                continue
            chain = _enclosing_chain(scopes, node_to_fn, node)
            enclosing = chain[0].node if chain else tree
            has_seam = any(
                isinstance(sub, ast.Call)
                and _terminal(sub.func) in CKPT_RESTORE_SEAMS
                for sub in ast.walk(enclosing)
            )
            if not has_seam:
                emit(
                    "ckpt-dtype-drift", node,
                    f"`{_terminal(node.func)}` restores state without "
                    f"routing it through a precision restore seam "
                    f"({', '.join(sorted(CKPT_RESTORE_SEAMS))}) — a "
                    f"checkpoint saved under a different --dtype would "
                    f"silently retrace the donated-buffer step instead "
                    f"of re-casting loudly or failing",
                )

    # -- use-after-donation (per function body, EXCLUDING nested defs:
    # a load in a different closure has its own lifetime)
    def walk_own_body(fn_node):
        stack = list(ast.iter_child_nodes(fn_node))
        while stack:
            node = stack.pop()
            yield node
            if not isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                stack.extend(ast.iter_child_nodes(node))

    for fn_node, info in scopes.fns.items():
        if not isinstance(fn_node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        body_calls: List[Tuple[ast.Call, Optional[str]]] = []
        assigns: List[ast.Assign] = []
        for node in walk_own_body(fn_node):
            if isinstance(node, ast.Assign):
                assigns.append(node)
            if isinstance(node, ast.Call):
                term = _terminal(node.func)
                if term and _donating_call(term) and node.args:
                    body_calls.append((node, _expr_key(node.args[0])))
        for call, donated in body_calls:
            if donated is None:
                continue
            call_line = call.lineno
            # aliases bound from the donated expr BEFORE the call
            aliases = {
                t.id
                for a in assigns
                if a.lineno < call_line and _expr_key(a.value) == donated
                for t in a.targets
                if isinstance(t, ast.Name)
            }
            # is the donated expr rebound by the call's own statement?
            # Matched by the CALL NODE living inside the assignment's
            # value expression, not by line number — a line-wrapped
            # `self.state, loss = (\n    self.train_step(...))` must
            # still count as a rebind.
            rebound_at_call = any(
                any(sub is call for sub in ast.walk(a.value)) and any(
                    donated in {
                        _expr_key(el) for el in (
                            t.elts if isinstance(t, ast.Tuple) else [t]
                        )
                    }
                    for t in a.targets
                )
                for a in assigns
            )
            for node in walk_own_body(fn_node):
                line = getattr(node, "lineno", 0)
                if line <= call_line:
                    continue
                if isinstance(node, (ast.Name, ast.Attribute)) and isinstance(
                    getattr(node, "ctx", None), ast.Load
                ):
                    key = _expr_key(node)
                    if key == donated and not rebound_at_call:
                        emit(
                            "use-after-donation", node,
                            f"`{donated}` was passed in donated position "
                            f"to `{_terminal(call.func)}` at line "
                            f"{call_line}; its buffers are deleted on "
                            f"accelerators — rebind the result instead of "
                            f"re-reading the donated value",
                        )
                    elif key in aliases:
                        emit(
                            "use-after-donation", node,
                            f"`{key}` aliases `{donated}`, which was "
                            f"donated to `{_terminal(call.func)}` at line "
                            f"{call_line}; reading the alias afterwards "
                            f"is a use-after-free unless donation is "
                            f"provably disabled on this path",
                        )

    # -- rank-gated-collective
    def test_calls_process_index(test: ast.AST) -> bool:
        return any(
            isinstance(n, ast.Call) and _terminal(n.func) == "process_index"
            for n in ast.walk(test)
        )

    for node in ast.walk(tree):
        branches: List[ast.AST] = []
        if isinstance(node, (ast.If, ast.While)) and test_calls_process_index(
            node.test
        ):
            branches = list(node.body) + list(node.orelse)
        elif isinstance(node, ast.IfExp) and test_calls_process_index(
            node.test
        ):
            branches = [node.body, node.orelse]
        for br in branches:
            for sub in ast.walk(br):
                if isinstance(sub, ast.Call) and _terminal(
                    sub.func
                ) in COLLECTIVE_CALLS:
                    emit(
                        "rank-gated-collective", sub,
                        f"`{_dotted(sub.func) or _terminal(sub.func)}` is "
                        f"guarded by a process_index() conditional — ranks "
                        f"trace different collective programs and deadlock "
                        f"at the first unmatched collective; issue the "
                        f"collective on every rank (gate only the use of "
                        f"its result)",
                    )

    # -- suppression hygiene: every `dptlint: disable=` comment must
    # name a real rule AND still absorb a finding on its line. A typo'd
    # rule suppresses nothing (silently); a suppression whose rule no
    # longer fires is dead weight that would hide the NEXT regression
    # landing on that line.
    for line, rules in sorted(suppressed.items()):
        for rule in sorted(rules):
            if rule != "all" and rule not in KNOWN_RULES:
                findings.append(Finding(
                    rule="unknown-suppression",
                    where=f"{rel_path}:{line}",
                    message=(
                        f"suppression names unknown rule {rule!r} — not "
                        f"one of this linter's rules, so it suppresses "
                        f"nothing (typo?); known: "
                        f"{', '.join(sorted(KNOWN_RULES))}, all"
                    ),
                    layer="lint",
                ))
            elif (line, rule) not in used_suppressions:
                findings.append(Finding(
                    rule="stale-suppression",
                    where=f"{rel_path}:{line}",
                    message=(
                        f"suppression of {rule!r} is stale: the rule no "
                        f"longer fires on this line — remove the comment "
                        f"(a dead suppression hides the next regression "
                        f"that lands here)"
                    ),
                    layer="lint",
                ))

    return findings


def lint_file(path: str, root: Optional[str] = None) -> List[Finding]:
    rel = os.path.relpath(path, root) if root else path
    with open(path, "r", encoding="utf-8") as f:
        return lint_source(f.read(), rel)


SKIP_DIRS = frozenset({"__pycache__", "native"})


def lint_package(root: Optional[str] = None) -> Tuple[List[Finding], int]:
    """Lint every ``.py`` under ``root`` (default: this package).
    Returns ``(findings, files_linted)``."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    findings: List[Finding] = []
    n = 0
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in sorted(dirnames) if d not in SKIP_DIRS]
        for fname in sorted(filenames):
            if not fname.endswith(".py"):
                continue
            n += 1
            findings.extend(
                lint_file(os.path.join(dirpath, fname),
                          root=os.path.dirname(root))
            )
    return findings, n
