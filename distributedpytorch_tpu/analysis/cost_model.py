"""The planner's analytic cost model: mesh-aware tables turning a point's
static artifacts — XLA ``cost_analysis()`` flops, ``memory_analysis()``
traced liveness, and the extracted ordered collective program — into one
comparable predicted step cost. No jax import: the planner feeds this
module plain numbers, and jax-free consumers (``tools/bench_multi.py``
reading a plan file) can import it for the mesh tables alone.

The model is deliberately simple — three additive terms:

``compute_s``
    program flops / the mesh's per-device matmul rate at the point's
    compute dtype. Flops come from ``compiled.cost_analysis()`` of the
    AOT-compiled (never executed) step; under SPMD partitioning the
    compiled module is the per-device program, so the rate is per-device
    too. Backends without ``cost_analysis`` degrade to ``None`` and the
    ranking falls back to the other two terms (the planner's guard).

``hbm_s``
    traced-liveness bytes (``temp + argument + output`` from
    ``memory_analysis()``) / HBM bandwidth, scaled by
    :func:`hbm_pressure` as liveness approaches the ``hbm_gb`` budget.
    This is the **activation-liveness term**: it is what ranks 1F1B
    above GPipe at high microbatch counts — GPipe keeps every
    microbatch's activations live through the drain (PR 4's measured
    3.4× temp-bytes gap at M=8), so at the activation wall its HBM term
    explodes (and past the budget the point is rejected outright) while
    1F1B's stage-bounded in-flight set stays cheap.

``comms_s``
    the per-collective latency/bandwidth table over the collective
    program. For the explicit shard_map schedules (MP/DDP_MP) the
    program comes from the jaxpr — every ppermute/psum with its actual
    per-device payload bytes. GSPMD strategies trace EMPTY jaxpr
    programs (XLA inserts their collectives at compile time), so
    ``mesh_comms_program`` supplies the analytic equivalent, composed
    per mesh axis from the sharding rules (parallel/mesh.py): the data
    axis's gradient all-reduce (or FSDP's per-step parameter
    all-gathers in the **storage** dtype — ``--dtype bf16_params``
    halves these bytes, which is exactly why dtype is a real search
    dimension — plus the gradient reduce-scatter), the spatial model
    axis's per-conv boundary-row halo ppermutes, and the channel model
    axis's per-conv activation all-gathers. Hybrid mesh points
    (DP x TP, FSDP x SP) sum their axes' terms, so they rank honestly
    against pure ones. The legacy ``gspmd_comms_program`` remains as
    the data-axis-only strategy-name surface.

Absolute times are rough; the model exists to RANK points, and every
term is monotone in the quantity it abstracts. Numbers live in
``MESH_MODELS`` (documented approximations, not measurements).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Mapping, Optional, Tuple

#: (kind, payload_bytes, axis_size) — one collective in a comms program.
CommOp = Tuple[str, int, int]


@dataclasses.dataclass(frozen=True)
class MeshModel:
    """Per-device rates for one accelerator target. All values are
    order-of-magnitude datasheet numbers: good enough to rank, never to
    be quoted as a measurement."""

    name: str
    #: compute-dtype name -> matmul FLOP/s per device
    flops_per_s: Mapping[str, float]
    hbm_bytes_per_s: float
    hbm_gb: float
    #: per-link interconnect bandwidth, bytes/s
    ici_bytes_per_s: float
    #: fixed per-collective launch/rendezvous latency, seconds
    collective_latency_s: float

    def flops_rate(self, compute_dtype: str) -> float:
        """Rate for ``compute_dtype`` (falls back to the slowest listed
        rate for dtypes the table doesn't name — conservative)."""
        rate = self.flops_per_s.get(str(compute_dtype))
        return float(rate) if rate else float(min(self.flops_per_s.values()))


#: TPU v5e (the chip-window target): ~197 bf16 TFLOP/s MXU (f32 conv
#: runs the multi-pass path, modeled at half), 16 GB HBM at ~819 GB/s,
#: ICI modeled at 45 GB/s per link with ~1 µs collective latency.
MESH_MODELS: Dict[str, MeshModel] = {
    "tpu_v5e": MeshModel(
        name="tpu_v5e",
        flops_per_s={"bfloat16": 1.97e14, "float32": 9.85e13},
        hbm_bytes_per_s=8.19e11,
        hbm_gb=16.0,
        ici_bytes_per_s=4.5e10,
        collective_latency_s=1e-6,
    ),
}

#: Wire-traffic multiplier per collective kind as a function of the
#: ring factor (n-1)/n; psum (all-reduce) pays reduce-scatter +
#: all-gather, ppermute is a point-to-point shift (payload crosses one
#: link once, concurrently on every edge).
_RING_FACTOR = {
    "psum": 2.0,
    "pmin": 2.0,
    "pmax": 2.0,
    "all_gather": 1.0,
    "reduce_scatter": 1.0,
    "all_to_all": 1.0,
}


def collective_time(kind: str, payload_bytes: int, axis_size: int,
                    mesh: MeshModel) -> float:
    """Predicted seconds for one collective over ``axis_size`` devices.
    Degenerate axes (size <= 1) are free: the collective is a no-op."""
    n = int(axis_size)
    if n <= 1 or payload_bytes <= 0:
        return 0.0
    if kind == "ppermute":
        wire = float(payload_bytes)
    else:
        wire = _RING_FACTOR.get(kind, 1.0) * payload_bytes * (n - 1) / n
    return mesh.collective_latency_s + wire / mesh.ici_bytes_per_s


def comms_summary(program: Iterable[CommOp],
                  mesh: MeshModel) -> Tuple[int, float]:
    """(total payload bytes, total predicted seconds) for a comms
    program — the ordered collective sequence of one step."""
    total_bytes = 0
    total_s = 0.0
    for kind, payload, axis_size in program:
        if int(axis_size) > 1:
            total_bytes += int(payload)
        total_s += collective_time(kind, payload, axis_size, mesh)
    return total_bytes, total_s


def gspmd_comms_program(strategy: str, param_storage_bytes: int,
                        grad_bytes: int, axis_size: int) -> List[CommOp]:
    """Analytic per-step comms for strategies whose collectives are
    GSPMD-inserted (empty jaxpr program). ``param_storage_bytes`` is in
    the policy's STORAGE dtype — the bf16_params halving rides through
    here into FSDP's all-gather term. ``grad_bytes`` is f32 (the stated
    REDUCE_DTYPE contract). Strategies not listed (SP/TP) return
    empty — the planner now routes every config through
    :func:`mesh_comms_program`, which models their halo/channel axes
    too; this strategy-name surface survives for direct callers."""
    n = int(axis_size)
    if n <= 1:
        return []
    if strategy in ("DP", "DDP"):
        return [("psum", grad_bytes, n)]
    if strategy == "FSDP":
        # parameters gathered for the forward AND the backward, grads
        # reduce-scattered — the ZeRO-3 dance GSPMD emits
        return [
            ("all_gather", param_storage_bytes, n),
            ("all_gather", param_storage_bytes, n),
            ("reduce_scatter", grad_bytes, n),
        ]
    return []


#: Conv applications per UNet level entering the halo/channel terms: a
#: DoubleConv on the down path and one on the up path = 4 convs of that
#: level's plane scale. Order-of-magnitude accounting, like every
#: number here.
CONVS_PER_LEVEL = 4


def mesh_comms_program(
    *,
    data: int = 1,
    model: int = 1,
    model_role: str = "channel",
    params_rule: str = "replicate",
    param_storage_bytes: int = 0,
    grad_bytes: int = 0,
    level_planes: Iterable[Tuple[int, int]] = (),
    stage: int = 1,
) -> List[CommOp]:
    """Analytic per-step comms for a mesh config whose collectives are
    GSPMD-inserted (empty jaxpr program) — the rule-engine
    generalization of :func:`gspmd_comms_program`, composing per-axis
    terms so hybrid points (DP x TP, FSDP x SP, ...) rank honestly
    against pure ones:

    * **data axis** — the gradient all-reduce (params replicated) or
      the ZeRO-3 dance (``fsdp`` rules: 2 param all-gathers in the
      STORAGE dtype — bf16_params halves them — plus the f32 gradient
      reduce-scatter);
    * **model axis, ``spatial`` role** — the per-conv halo exchanges:
      one boundary-row ppermute each way per conv application
      (``level_planes`` rows of ``(plane_bytes, row_bytes)`` per UNet
      level, CONVS_PER_LEVEL convs each, forward + backward);
    * **model axis, ``channel`` role** — per-conv channel traffic: the
      next layer contracts over sharded in-channels, so each conv's
      input activation plane is (re)gathered over 'model' — one
      all-gather per conv application, forward + backward. The payload
      is the FULL gathered plane (the all-gather convention every
      other term here uses: ``collective_time``'s ring factor applies
      (n-1)/n to the whole buffer, exactly like the FSDP param
      all-gathers above).

    With ``stage > 1`` the in-stage execution model changes the terms
    (pipeline stages shard params via gather-at-use, parallel/pipeline.py):

    * **model axis, ``channel`` role in-stage** — ONE param all-gather
      per step at the top of the shard_map body (not per-conv activation
      gathers: the stage computes on full params), transposing to one
      gradient reduce-scatter on the backward. Payload is the stage's
      own param slice — ``param_storage_bytes / stage`` — gathered
      concurrently across stages;
    * **data axis with ``fsdp`` in-stage** — the same gather-at-use
      dance over the data axis: one STORAGE-dtype param all-gather plus
      the f32 gradient reduce-scatter (not the flat-mesh 2-gather ZeRO
      shape — the pipeline body gathers once, the vjp transposes it);
    * **data axis, replicated params in-stage** — unchanged: the
      schedule-closing gradient psum simply extends over
      ``('stage', 'data')``.

    These were the planner's ``comms_model: none`` gap: SP/TP (and
    every model-axis hybrid) previously ranked with a silent zero-comms
    advantage. The terms are monotone in what they abstract — never a
    measurement."""
    program: List[CommOp] = []
    d, m, s = int(data), int(model), max(1, int(stage))
    stage_params = param_storage_bytes // s
    stage_grads = grad_bytes // s
    if d > 1:
        if "fsdp" in params_rule:
            if s > 1:
                program += [
                    ("all_gather", stage_params, d),
                    ("reduce_scatter", stage_grads, d),
                ]
            else:
                program += [
                    ("all_gather", param_storage_bytes, d),
                    ("all_gather", param_storage_bytes, d),
                    ("reduce_scatter", grad_bytes, d),
                ]
        else:
            program.append(("psum", grad_bytes, d))
    if m > 1:
        if s > 1 and model_role == "channel":
            # in-stage channel-TP: gather-at-use param reconstruction,
            # once per step, transposed to a grad reduce-scatter
            program += [
                ("all_gather", stage_params, m),
                ("reduce_scatter", stage_grads, m),
            ]
        else:
            for plane_bytes, row_bytes in level_planes:
                for _ in range(2 * CONVS_PER_LEVEL):  # forward + backward
                    if model_role == "spatial":
                        # boundary rows cross one link each way per conv
                        program.append(("ppermute", 2 * int(row_bytes), m))
                    else:
                        program.append(("all_gather", int(plane_bytes), m))
    return program


#: HBM round-trips over the (B·H·W) f32 activation/probability plane
#: that each engaged Pallas kernel FUSES AWAY, per step (the ``kernels``
#: search axis, ops/kernels.py). Order-of-magnitude accounting, like
#: every number here — the model ranks kernel-on vs kernel-off, it does
#: not measure:
#:
#: * ``fused_loss``    — XLA schedules the four loss-stat reductions as
#:   separate fusions over the prob map (forward) plus an elementwise
#:   backward read; the one-pass kernel + analytic VJP reads it once
#:   each way: ~4 plane passes saved.
#: * ``conv_epilogue`` — each DoubleConv BN-normalize + ReLU is two
#:   read+write passes over the conv output, twice per block, folded to
#:   one multiply-add pass (+ the backward's fused dz/dx): ~4 passes of
#:   plane-scale activation traffic saved per step.
#: * ``eval_stats`` / ``serve_mask`` — not on the train step; listed for
#:   completeness (serve_mask's win is D2H bytes, not step HBM).
KERNEL_SAVED_PASSES: Dict[str, float] = {
    "fused_loss": 4.0,
    "conv_epilogue": 4.0,
    "eval_stats": 0.0,
    "serve_mask": 0.0,
}


def kernel_savings_s(kernels: Iterable[str], plane_bytes: int,
                     mesh: MeshModel) -> float:
    """Predicted seconds a ``--kernels pallas`` point saves off its XLA
    twin's step: saved HBM passes × the f32 activation-plane bytes /
    HBM bandwidth. Monotone in what it abstracts (fused traffic), never
    quoted as a measurement."""
    passes = sum(KERNEL_SAVED_PASSES.get(k, 0.0) for k in kernels)
    if passes <= 0 or plane_bytes <= 0:
        return 0.0
    return passes * float(plane_bytes) / mesh.hbm_bytes_per_s


#: The memory-pressure factor saturates here: occupancy beyond ~99% of
#: the budget is the infeasibility cliff, not a finer gradation.
MAX_HBM_PRESSURE = 100.0


def hbm_pressure(live_bytes: Optional[int],
                 hbm_budget_bytes: Optional[int]) -> float:
    """Multiplier on the HBM term as traced liveness approaches the
    budget: ``1 / (1 − occupancy)``, clamped. A step whose liveness
    comfortably fits pays bandwidth only; one crowding the budget pays
    steeply — the static shadow of XLA rematerialization and allocator
    thrash near capacity (the measured gpipe M=8/16-at-batch-4 rows
    that rematted or OOM'd while 1F1B's bounded in-flight set ran
    clean). This is what makes the liveness term RANK, not just gate."""
    if not live_bytes or not hbm_budget_bytes or hbm_budget_bytes <= 0:
        return 1.0
    occupancy = min(float(live_bytes) / float(hbm_budget_bytes),
                    1.0 - 1.0 / MAX_HBM_PRESSURE)
    return 1.0 / (1.0 - occupancy)


def point_cost(mesh: MeshModel, compute_dtype: str, flops: Optional[float],
               live_bytes: Optional[int], comms_s: float,
               hbm_budget_bytes: Optional[int] = None,
               ) -> Dict[str, Optional[float]]:
    """Combine the three terms. Missing inputs (no ``cost_analysis`` on
    this backend, no ``memory_analysis``) drop their term rather than
    poisoning the rank — the result is still monotone in what IS known."""
    compute_s = (
        float(flops) / mesh.flops_rate(compute_dtype)
        if flops and flops > 0 else None
    )
    pressure = hbm_pressure(live_bytes, hbm_budget_bytes)
    hbm_s = (
        float(live_bytes) / mesh.hbm_bytes_per_s * pressure
        if live_bytes and live_bytes > 0 else None
    )
    cost_s = comms_s + sum(t for t in (compute_s, hbm_s) if t is not None)
    return {
        "compute_s": compute_s,
        "hbm_s": hbm_s,
        "hbm_pressure": pressure,
        "comms_s": comms_s,
        "cost_s": cost_s,
    }
