"""The compiler-driven parallelism auto-planner: ``python -m
distributedpytorch_tpu plan``.

Chip windows r03–r05 spent most of their budget discovering configs that
were statically broken or memory-infeasible — facts that never needed a
device. This module learns them from the compiler alone (Alpa/FlexFlow's
search-with-a-cost-model idea, scoped to this repo's levers): enumerate
(strategy × pipeline-schedule × microbatches × s2d level × remat × batch
× dtype policy), then for each point

1. **static feasibility** — the existing jaxpr collective checker
   (``analysis/collectives.analyze_combo``, including the dual-rank
   re-trace): a point whose schedule deadlocks, drops a contract psum,
   or diverges across ranks is rejected before anything compiles;
2. **memory feasibility** — AOT-compile the strategy's REAL train step
   (``strategy.build_train_step`` over sharding-pinned
   ``ShapeDtypeStruct``s — the GSPMD partitioner runs, nothing
   executes) and reject points whose ``memory_analysis()`` traced
   liveness exceeds the ``--hbm-gb`` budget — the same traced-liveness
   signal PR 4 proved predicts the activation wall;
3. **rank the survivors** — ``analysis/cost_model.point_cost`` over the
   compiled flops (``cost_analysis``; guarded — some backends lack it),
   the liveness bytes, and the comms program (extracted from the jaxpr
   with per-collective payload bytes for the explicit schedules;
   analytic for GSPMD strategies, where ``--dtype bf16_params`` halves
   FSDP's all-gather bytes).

Everything runs on a self-provisioned virtual CPU mesh (same dance as
the ``analyze`` CLI): zero device execution, zero chip involvement, safe
to run while a window is idle or from a laptop.

The output is a versioned JSON plan file. ``tools/bench_multi.py
--plan`` orders its chip-window legs by the plan's predicted rank
(``rank_legs`` below maps a bench leg's env levers onto plan points) and
stamps ``plan_rank``/``plan_cost_s`` into each leg row's provenance;
``tools/tpu_perf_program3.sh`` generates and passes the plan so a window
spends its first minutes on predicted winners.
"""

from __future__ import annotations

import argparse
import dataclasses
import itertools
import json
import math
import os
import sys
import time
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

from distributedpytorch_tpu.analysis import (
    ANALYSIS_STRATEGIES,
    AnalysisEnvironmentError,
    MESH_DEVICES,
    PROVISIONED_SENTINEL as _SENTINEL,
)
from distributedpytorch_tpu.analysis import cost_model as cm
# import-light at module level (no jax): safe on bench_multi's jax-free
# load_plan/rank_legs path
from distributedpytorch_tpu.analysis.collectives import PIPELINE_STRATEGIES
# the mesh rule engine (parallel/mesh.py, jax-free): mesh-shape specs
# (``4x1x2``) enter the search grid exactly like strategy names, and
# the leg mapping recognizes hybrid geometries
from distributedpytorch_tpu.parallel.mesh import (
    spec_is_hybrid,
    spec_is_pipeline,
)

#: Plan-file schema version: bench_multi refuses (degrades to its own
#: ordering) on any other value — a stale plan must never silently
#: reorder a window.
PLAN_VERSION = 1
PLAN_KIND = "dpt_plan"

#: The default search grid. Axes that don't apply to a strategy collapse
#: (schedule/microbatches are pipeline-only), so the default enumerates
#: singleGPU·(s2d × remat × batch × dtype) + MP·(everything). Trim with
#: the CLI flags — every point costs one AOT compile (~tens of seconds
#: at the reference geometry on CPU), so ``--budget-s`` matters.
DEFAULT_GRID: Dict[str, tuple] = {
    "strategies": ("singleGPU", "MP"),
    # Mesh-shape axis (parallel/mesh.py specs, e.g. 4x1x2 / 2x2x1 /
    # 1x2x4): OFF by default — the historical grids stay byte-stable —
    # and widened by --meshes; spec points enumerate exactly like
    # strategies (stage-axis specs get the schedule x microbatch axes).
    "meshes": (),
    "schedules": ("gpipe", "1f1b"),
    "microbatches": (2, 8),
    "s2d_levels": (0, 2, 3),
    "remats": (False, True),
    "batches": (4, 8),
    "dtypes": ("bf16", "bf16_params"),
    # The Pallas kernel-engagement axis (ops/kernels.py) is OFF by
    # default: kernel-on points cost no extra compile (they derive from
    # their XLA twin + the analytic fused-traffic saving), but ranking
    # them is only meaningful against a per-chip Mosaic probe priors
    # file — the CLI widens this to ("xla", "pallas") when
    # --kernel-priors (or explicit --kernels) is passed.
    "kernels": ("xla",),
}

EXIT_CLEAN = 0
EXIT_INFRA = 2


@dataclasses.dataclass(frozen=True)
class PlanPoint:
    """One candidate configuration — the search space's coordinates."""

    strategy: str
    schedule: Optional[str]      # None for non-pipeline strategies
    microbatches: Optional[int]  # None for non-pipeline strategies
    s2d_levels: int
    remat: bool
    batch: int
    dtype: str
    # Kernel-engagement policy (ops/kernels.py): "xla" keeps the key
    # format (and every pre-existing plan row) unchanged; "pallas"
    # points derive from their xla twin + the analytic kernel saving.
    kernels: str = "xla"

    @property
    def key(self) -> str:
        sched = f"/{self.schedule}/m{self.microbatches}" if self.schedule else ""
        remat = "on" if self.remat else "off"
        kern = f"/k-{self.kernels}" if self.kernels != "xla" else ""
        return (f"{self.strategy}{sched}/s2d{self.s2d_levels}"
                f"/remat-{remat}/b{self.batch}/{self.dtype}{kern}")

    def as_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["key"] = self.key
        return d


def _is_pipeline_point(strategy: str) -> bool:
    return strategy in PIPELINE_STRATEGIES or spec_is_pipeline(strategy)


def enumerate_points(
    strategies: Sequence[str],
    schedules: Sequence[str],
    microbatches: Sequence[int],
    s2d_levels: Sequence[int],
    remats: Sequence[bool],
    batches: Sequence[int],
    dtypes: Sequence[str],
    kernels: Sequence[str] = ("xla",),
) -> List[PlanPoint]:
    """The cartesian grid with non-applicable axes collapsed. dtype is
    a late axis so a budget-truncated run still covers both policies of
    the earliest points (the comparison each pair exists for) before
    opening new strategy corners; kernels is INNERMOST — a kernel-on
    point always directly follows the xla twin it derives from (zero
    extra compile, so the pairing is free even under a budget)."""
    points: List[PlanPoint] = []
    seen = set()
    # xla twins must precede their pallas derivations in the walk
    kerns = sorted({str(k) for k in kernels}, key=lambda k: k != "xla")
    for strategy in strategies:
        pipelined = _is_pipeline_point(strategy)
        scheds: Sequence[Optional[str]] = (
            tuple(schedules) if pipelined else (None,)
        )
        mbs: Sequence[Optional[int]] = (
            tuple(microbatches) if pipelined else (None,)
        )
        for sched, m, b, s2d, remat, dt, kern in itertools.product(
            scheds, mbs, batches, s2d_levels, remats, dtypes, kerns
        ):
            p = PlanPoint(strategy, sched, m, int(s2d), bool(remat),
                          int(b), dt, kern)
            if p not in seen:
                seen.add(p)
                points.append(p)
    return points


# -- evaluation --------------------------------------------------------------
def _point_config(point: PlanPoint, image_size, widths):
    from distributedpytorch_tpu.config import TrainConfig

    return TrainConfig(
        train_method=point.strategy,
        batch_size=point.batch,
        image_size=tuple(image_size),
        model_widths=tuple(widths) if widths else None,
        pipeline_schedule=point.schedule or "gpipe",
        num_microbatches=point.microbatches or 2,
        s2d_levels=point.s2d_levels,
        remat=point.remat,
        dtype=point.dtype,
    )


def _tree_bytes(tree) -> int:
    import jax
    import jax.numpy as jnp

    total = 0
    for leaf in jax.tree.leaves(tree):
        total += math.prod(leaf.shape) * jnp.dtype(leaf.dtype).itemsize
    return int(total)


def _tree_count(tree) -> int:
    import jax

    return int(sum(math.prod(leaf.shape) for leaf in jax.tree.leaves(tree)))


def _activation_levels(image_size, widths, batch: int,
                       itemsize: int) -> tuple:
    """Per-UNet-level ``(plane_bytes, row_bytes)`` of the conv
    activations in the compute dtype — what the analytic halo (spatial)
    and channel-gather (TP) comms terms scale with
    (cost_model.mesh_comms_program). ``widths`` None = the flagship
    architecture's documented channel plan."""
    width, height = image_size  # (W, H), the reference convention
    out = []
    for level, channels in enumerate(widths or (32, 64, 128, 256)):
        h, w = max(height >> level, 1), max(width >> level, 1)
        out.append((
            batch * h * w * int(channels) * itemsize,
            batch * w * int(channels) * itemsize,
        ))
    return tuple(out)


def _flops_of(compiled) -> Optional[float]:
    """``cost_analysis()`` flops, guarded: absent/odd-shaped analyses on
    some backends must degrade the cost model, never crash the plan."""
    try:
        analysis = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 — NotImplementedError and friends
        return None
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else None
    if not isinstance(analysis, Mapping):
        return None
    flops = analysis.get("flops")
    try:
        flops = float(flops)
    except (TypeError, ValueError):
        return None
    return flops if flops > 0 else None


def _trace_point_step(point: PlanPoint, image_size, widths):
    """The point's abstract train step, traced: config → strategy →
    shape-only state/batch → jaxpr collective program. Shared by
    :func:`evaluate_point` (which goes on to AOT-compile) and
    :func:`check_plan_staleness` (which only needs the collective
    program) so the stale-plan re-trace compares like with like.
    Returns ``(cfg, strategy, model, tx, state, batch, colls)``."""
    import jax
    import jax.numpy as jnp

    from distributedpytorch_tpu.analysis.collectives import (
        extract_collectives,
    )
    from distributedpytorch_tpu.models import create_model
    from distributedpytorch_tpu.ops.optim import adam_l2
    from distributedpytorch_tpu.parallel import build_strategy
    from distributedpytorch_tpu.train.steps import TrainState

    cfg = _point_config(point, image_size, widths)
    strategy = build_strategy(cfg)
    policy = strategy.policy
    model, _init_fn = create_model(cfg)
    width, height = cfg.image_size  # (W, H), the reference convention

    variables = jax.eval_shape(
        lambda k: model.init(k, jnp.zeros((1, height, width, 3))),
        jax.random.key(0),
    )
    params = variables["params"]
    model_state = variables.get("batch_stats")
    # mirror train/steps.create_train_state: optimizer init sees the
    # full-precision params (the master-weight wrapper promotes its copy
    # from what it is given), THEN params cast to storage dtype
    tx = adam_l2(cfg.learning_rate, cfg.weight_decay)
    if policy.master_weights:
        tx = policy.wrap_optimizer(tx)
    opt_state = jax.eval_shape(tx.init, params)
    params = jax.eval_shape(policy.cast_params, params)
    state = TrainState(
        params=params,
        opt_state=opt_state,
        step=jax.ShapeDtypeStruct((), jnp.int32),
        model_state=model_state,
    )
    batch = {
        "image": jax.ShapeDtypeStruct(
            (point.batch, height, width, 3), jnp.float32),
        "mask": jax.ShapeDtypeStruct((point.batch, height, width), jnp.int32),
    }
    colls = extract_collectives(
        jax.make_jaxpr(strategy._raw_step(model, tx))(state, batch)
    )
    return cfg, strategy, model, tx, state, batch, colls


def evaluate_point(point: PlanPoint, image_size, widths,
                   mesh_model: cm.MeshModel, hbm_budget_bytes: int) -> dict:
    """One point's row: abstract state → jaxpr comms program → AOT
    compile → memory/flops → cost. Zero device execution throughout
    (``make_jaxpr`` + ``lower().compile()`` only). Raises on configs the
    strategy itself rejects — the caller records those as infeasible."""
    import jax.numpy as jnp

    from distributedpytorch_tpu.analysis.collectives import (
        compile_train_step_aot,
        program_fingerprint,
    )

    cfg, strategy, model, tx, state, batch, colls = _trace_point_step(
        point, image_size, widths
    )
    policy = strategy.policy
    params = state.params

    # -- comms program: jaxpr-extracted (explicit schedules) or analytic ----
    mesh = strategy.mesh
    program: List[cm.CommOp] = []
    last_sig = None
    for c in colls:
        axis_size = 1
        for axis in c.axes:
            if isinstance(axis, str) and mesh is not None and axis in mesh.shape:
                axis_size *= int(mesh.shape[axis])
        # a tree-typed collective traces one eqn PER LEAF per tick but
        # ships as ONE fused transfer on hardware: merge adjacent eqns
        # with identical signatures into a single op (summed payload),
        # so the per-collective latency term counts ticks, not leaves
        if program and c.signature == last_sig:
            kind, payload, n = program[-1]
            program[-1] = (kind, payload + c.payload_bytes, n)
        else:
            program.append((c.kind, c.payload_bytes, axis_size))
        last_sig = c.signature
    comms_model = "jaxpr" if program else "none"
    if not program and mesh is not None:
        # GSPMD configs trace empty programs: compose the analytic
        # per-axis terms from the strategy's mesh config — the data
        # axis's grad psum / ZeRO dance, and the model axis's halo
        # (spatial) or channel-gather (TP) traffic, previously the
        # ``comms_model: none`` gap that let SP/TP points rank with a
        # silent zero-comms advantage
        mc = strategy.mesh_config
        program = cm.mesh_comms_program(
            data=mc.data,
            model=mc.model,
            model_role=mc.model_role,
            params_rule=mc.params,
            param_storage_bytes=_tree_bytes(params),
            grad_bytes=_tree_count(params) * 4,
            level_planes=_activation_levels(
                cfg.image_size, widths, point.batch,
                jnp.dtype(policy.compute_dtype).itemsize,
            ),
            stage=mc.stage,
        )
        if program:
            comms_model = "analytic"
    comms_bytes, comms_s = cm.comms_summary(program, mesh_model)

    # -- in-stage sharding advisory: hybrid pipeline points carry their
    # gather-at-use collectives inside the traced jaxpr program already
    # (counted in comms_s above); re-derive the analytic in-stage terms
    # separately so the breakdown NAMES them — a 2x2x2 row shows what the
    # model axis costs, not just a merged total. Advisory only: never
    # added to cost_s (that would double-count the jaxpr gathers).
    in_stage_s = None
    mc = getattr(strategy, "mesh_config", None)
    if mc is not None and mc.stage > 1 and (
        (mc.model > 1 and mc.model_role == "channel")
        or ("fsdp" in mc.params and mc.data > 1)
    ):
        in_stage_program = cm.mesh_comms_program(
            data=mc.data,
            model=mc.model,
            model_role=mc.model_role,
            params_rule=mc.params,
            param_storage_bytes=_tree_bytes(params),
            grad_bytes=_tree_count(params) * 4,
            stage=mc.stage,
        )
        _, in_stage_s = cm.comms_summary(in_stage_program, mesh_model)

    # -- AOT compile: traced liveness + flops, nothing executes -------------
    compiled = compile_train_step_aot(strategy, model, tx, state, batch)
    ma = compiled.memory_analysis()
    flops = _flops_of(compiled)

    bytes_row: Dict[str, Optional[int]] = {
        "temp_bytes": int(ma.temp_size_in_bytes) if ma else None,
        "argument_bytes": int(ma.argument_size_in_bytes) if ma else None,
        "output_bytes": int(ma.output_size_in_bytes) if ma else None,
    }
    live_bytes = (
        sum(v for v in bytes_row.values() if v is not None)
        if ma else None
    )

    feasible = True
    reject = None
    if live_bytes is not None and live_bytes > hbm_budget_bytes:
        feasible = False
        reject = (
            f"memory: traced liveness {live_bytes} B exceeds the "
            f"{hbm_budget_bytes} B HBM budget "
            f"(temp={bytes_row['temp_bytes']}, "
            f"args={bytes_row['argument_bytes']}, "
            f"out={bytes_row['output_bytes']})"
        )

    predicted = cm.point_cost(
        mesh_model, policy.compute, flops, live_bytes, comms_s,
        hbm_budget_bytes=hbm_budget_bytes,
    )
    predicted.update(bytes_row)
    predicted["live_bytes"] = live_bytes
    predicted["flops"] = flops
    predicted["comms_bytes"] = comms_bytes
    predicted["comms_model"] = comms_model
    if in_stage_s is not None:
        predicted["in_stage_comms_s"] = in_stage_s
    cost = predicted["cost_s"]
    predicted["imgs_per_s"] = (
        round(strategy.global_batch_size / cost, 2) if cost else None
    )

    row = point.as_dict()
    row.update(feasible=feasible, reject=reject, predicted=predicted)
    # provenance stamp: the ordered-collective fingerprint of the trace
    # this row's numbers were computed from — the stale-plan rule
    # (check_plan_staleness) re-traces and compares against it. Only
    # xla rows trace; kernel-derived rows copy their twin's artifacts
    # and deliberately carry no fingerprint.
    row["jaxpr_fingerprint"] = program_fingerprint(colls)
    return row


def _engaged_train_kernels(point: PlanPoint, widths) -> Tuple[str, ...]:
    """Probe-registry names a TRAIN step at this point would engage
    under a pallas kernel policy (ops/kernels.train_step_kernels over
    the point's config — the one definition of engagement)."""
    from distributedpytorch_tpu.ops.kernels import train_step_kernels

    return train_step_kernels(_point_config(point, (64, 64), widths))


def _kernel_point_row(
    point: PlanPoint,
    twin_row: Optional[dict],
    mesh_model: cm.MeshModel,
    priors: Optional[dict],
    image_size,
    widths,
) -> dict:
    """A ``kernels='pallas'`` point's row, derived with ZERO compile and
    ZERO device time:

    * any engaged kernel the Mosaic probe priors mark rejected → the
      point is infeasible, carrying the probe's reject reason verbatim;
    * otherwise the row copies its xla twin's compiled artifacts (the
      interpret-mode Pallas compile on the planning CPU would distort
      flops/liveness, the twin's are the honest hardware-shaped numbers)
      and subtracts the analytic fused-traffic saving
      (cost_model.kernel_savings_s) from the predicted cost.
    """
    row = point.as_dict()
    engaged = _engaged_train_kernels(point, widths)
    prior_rows = (priors or {}).get("kernels", {})
    for name in engaged:
        verdict = prior_rows.get(name)
        if isinstance(verdict, dict) and not verdict.get("accepted", True):
            reason = verdict.get("reason", "no reason recorded")
            row.update(
                feasible=False,
                reject=f"kernels: Mosaic rejected {name}: {reason}",
                predicted=None,
            )
            return row
    if twin_row is None or twin_row.get("skipped"):
        row.update(feasible=None, reject=None, predicted=None,
                   skipped="budget")
        return row
    if not twin_row.get("feasible"):
        row.update(feasible=False, reject=twin_row.get("reject"),
                   predicted=None)
        return row
    predicted = dict(twin_row.get("predicted") or {})
    width, height = image_size  # (W, H), the reference convention
    plane_bytes = point.batch * height * width * 4
    saving = cm.kernel_savings_s(engaged, plane_bytes, mesh_model)
    cost = predicted.get("cost_s")
    if cost:
        new_cost = max(cost - saving, 0.05 * cost)
        predicted["cost_s"] = new_cost
        predicted["imgs_per_s"] = round(point.batch / new_cost, 2)
    predicted["kernel_saving_s"] = saving
    predicted["kernels_model"] = "analytic"
    predicted["kernels_engaged"] = list(engaged)
    predicted["kernel_priors"] = (
        "accepted" if all(k in prior_rows for k in engaged) else "unprobed"
    )
    row.update(feasible=True, reject=None, predicted=predicted)
    return row


def _static_findings(points: Sequence[PlanPoint]) -> Dict[str, List[str]]:
    """One collective-checker run per distinct (strategy, schedule)
    among the points — the dual-rank re-trace included, so a
    ``process_index()``-gated collective rejects here too. Strategies
    the analyzer doesn't cover (singleGPU) have nothing to check.
    Analyzer crashes on a combo degrade to 'no findings' for that combo
    (the planner is advisory; the memory gate still applies)."""
    from distributedpytorch_tpu.analysis import collectives

    findings: Dict[str, List[str]] = {}
    combos = sorted(
        {(p.strategy, p.schedule) for p in points
         if p.strategy in ANALYSIS_STRATEGIES
         # stage-axis mesh specs run the explicit schedules — the
         # checker derives their contract from the parsed spec; pure
         # GSPMD specs have nothing jaxpr-level to check (HLO tier)
         or spec_is_pipeline(p.strategy)},
        key=lambda c: (c[0], c[1] or ""),
    )
    for method, schedule in combos:
        tag = f"{method}/{schedule}" if schedule else method
        try:
            found = collectives.analyze_combo(
                method, schedule, hlo=False, rank_check=True
            )
        except Exception as exc:  # noqa: BLE001 — infra, not a finding
            findings[tag] = []
            print(f"plan: static check for {tag} could not run "
                  f"({type(exc).__name__}: {exc}) — proceeding",
                  file=sys.stderr)
            continue
        findings[tag] = [f"[{f.rule}] {f.where}: {f.message}" for f in found]
    return findings


def plan(
    strategies: Sequence[str] = DEFAULT_GRID["strategies"],
    meshes: Sequence[str] = DEFAULT_GRID["meshes"],
    schedules: Sequence[str] = DEFAULT_GRID["schedules"],
    microbatches: Sequence[int] = DEFAULT_GRID["microbatches"],
    s2d_levels: Sequence[int] = DEFAULT_GRID["s2d_levels"],
    remats: Sequence[bool] = DEFAULT_GRID["remats"],
    batches: Sequence[int] = DEFAULT_GRID["batches"],
    dtypes: Sequence[str] = DEFAULT_GRID["dtypes"],
    kernels: Sequence[str] = DEFAULT_GRID["kernels"],
    kernel_priors: Optional[dict] = None,
    image_size=(960, 640),
    widths: Optional[Sequence[int]] = None,
    hbm_gb: float = 16.0,
    mesh_model: str = "tpu_v5e",
    budget_s: float = 0.0,
    emit=None,
) -> dict:
    """Search, reject, rank; returns the plan payload (what
    ``save_plan`` writes). ``budget_s`` > 0 stops opening new compiles
    near the wall budget — already-evaluated points keep their rows and
    the rest carry an explicit ``skipped: budget`` marker.

    ``kernels`` is the Pallas engagement axis (ops/kernels.py):
    kernel-on points cost NO compile and NO device time — each derives
    from its xla twin plus the analytic fused-traffic saving, and
    ``kernel_priors`` (a loaded probe-priors payload) rejects any point
    whose engaged kernel Mosaic refused, carrying the probe's reason."""
    t_start = time.monotonic()
    mm = MESH_MODELS_LOOKUP(mesh_model)
    hbm_budget_bytes = int(hbm_gb * 2**30)
    # mesh-shape points are strategies to the rest of the pipeline:
    # build_strategy resolves specs, the collective checker derives
    # their contracts, and evaluate_point's mesh_config drives the
    # analytic comms — appended after the named strategies so legacy
    # grids keep their exact walk order
    strategies = tuple(strategies) + tuple(
        m for m in meshes if m not in strategies
    )
    kernels = tuple(kernels)
    if any(k != "xla" for k in kernels) and "xla" not in kernels:
        # every pallas point derives from its xla twin — force the pair
        kernels = ("xla",) + kernels
    points = enumerate_points(
        strategies, schedules, microbatches, s2d_levels, remats, batches,
        dtypes, kernels,
    )
    static = _static_findings(points)

    rows: List[dict] = []
    twin_rows: Dict[PlanPoint, dict] = {}
    for point in points:
        combo = (f"{point.strategy}/{point.schedule}" if point.schedule
                 else point.strategy)
        lines = static.get(combo, ())
        if lines:
            row = point.as_dict()
            row.update(feasible=False, reject=f"static: {lines[0]}",
                       predicted=None)
        elif point.kernels != "xla":
            # zero-compile derivation (and the Mosaic-priors gate)
            twin = twin_rows.get(dataclasses.replace(point, kernels="xla"))
            row = _kernel_point_row(
                point, twin, mm, kernel_priors, image_size, widths
            )
        elif budget_s and time.monotonic() - t_start > 0.8 * budget_s:
            row = point.as_dict()
            row.update(feasible=None, reject=None, predicted=None,
                       skipped="budget")
        else:
            try:
                row = evaluate_point(
                    point, image_size, widths, mm, hbm_budget_bytes
                )
            except AnalysisEnvironmentError:
                # the analyzer's own infra-failure class: a broken
                # environment must surface as EXIT_INFRA from the CLI,
                # never be recorded as a confident per-point rejection
                raise
            except Exception as exc:  # noqa: BLE001 — strategy/config rejects
                row = point.as_dict()
                row.update(
                    feasible=False,
                    reject=f"config: {type(exc).__name__}: {exc}",
                    predicted=None,
                )
        if point.kernels == "xla":
            twin_rows[point] = row
        rows.append(row)
        if emit is not None:
            emit(row)

    # cost_s must be POSITIVE to rank: a backend yielding neither
    # cost_analysis nor memory_analysis leaves a comms-free point at
    # 0.0 — completely unmeasured, which must not sort ahead of every
    # genuinely evaluated point
    ranked = sorted(
        (r for r in rows
         if r.get("feasible")
         and r.get("predicted")
         and (r["predicted"].get("cost_s") or 0) > 0),
        key=lambda r: (r["predicted"]["cost_s"], r["key"]),
    )
    for rank, row in enumerate(ranked):
        row["rank"] = rank
    for row in rows:
        row.setdefault("rank", None)

    return {
        "kind": PLAN_KIND,
        "version": PLAN_VERSION,
        "mesh_model": mm.name,
        "hbm_gb": float(hbm_gb),
        "image_size": list(image_size),
        "widths": list(widths) if widths else None,
        "grid": {
            "strategies": list(strategies),
            "meshes": list(meshes),
            "schedules": list(schedules),
            "microbatches": list(microbatches),
            "s2d_levels": list(s2d_levels),
            "remats": [bool(r) for r in remats],
            "batches": list(batches),
            "dtypes": list(dtypes),
            "kernels": list(kernels),
        },
        "kernel_priors": (
            {
                "platform": kernel_priors.get("platform"),
                "device_kind": kernel_priors.get("device_kind"),
                "rejected": sorted(
                    name
                    for name, row in (
                        kernel_priors.get("kernels") or {}
                    ).items()
                    if isinstance(row, dict) and not row.get("accepted", True)
                ),
            }
            if kernel_priors
            else None
        ),
        "static_findings": static,
        "points": rows,
        "ranking": [r["key"] for r in ranked],
        "duration_s": round(time.monotonic() - t_start, 2),
    }


def MESH_MODELS_LOOKUP(name: str) -> cm.MeshModel:
    try:
        return cm.MESH_MODELS[name]
    except KeyError:
        raise ValueError(
            f"unknown mesh model {name!r}; expected one of "
            f"{sorted(cm.MESH_MODELS)}"
        ) from None


# -- plan-file IO (jax-free: bench_multi imports these) ----------------------
def save_plan(payload: dict, path: str) -> None:
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=2)
    os.replace(tmp, path)


def load_plan(path: str) -> Optional[dict]:
    """The plan file, or None for missing/unreadable/stale — callers
    (bench_multi ``--plan``) degrade to their own ordering on None; a
    half-written or version-skewed plan must never reorder a window."""
    try:
        with open(path) as f:
            payload = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(payload, dict):
        return None
    if payload.get("kind") != PLAN_KIND or payload.get("version") != PLAN_VERSION:
        return None
    if not isinstance(payload.get("points"), list):
        return None
    return payload


def point_from_row(row: Mapping) -> PlanPoint:
    """The :class:`PlanPoint` coordinates a saved plan row was
    evaluated at (the inverse of ``PlanPoint.as_dict``)."""
    return PlanPoint(
        strategy=row["strategy"],
        schedule=row.get("schedule"),
        microbatches=row.get("microbatches"),
        s2d_levels=int(row.get("s2d_levels") or 0),
        remat=bool(row.get("remat")),
        batch=int(row["batch"]),
        dtype=row["dtype"],
        kernels=row.get("kernels", "xla"),
    )


def check_plan_staleness(payload: Mapping) -> List:
    """The ``stale-plan`` rule (dptlint, collectives layer): re-trace
    every fingerprinted point of a loaded ``dpt_plan`` at the plan's
    own image size/widths and flag rows whose per-point ordered-
    collective fingerprint (``jaxpr_fingerprint``, stamped by
    :func:`evaluate_point`) no longer matches the current trace.

    A drifted fingerprint means the code that traces the train step —
    strategy, model, optimizer wrapping, sharding rules — changed
    since the plan was built: its rankings and comms predictions
    describe a program that no longer exists, and acting on them
    (bench_multi leg ordering, preflight gates) is planning from
    fiction. Rows without a fingerprint (kernel-derived points, plans
    predating the stamp) are skipped — no trace, nothing to compare.
    Infeasible-at-plan-time rows are still checked when they carry a
    fingerprint: their *rejection* was also computed from the trace."""
    from distributedpytorch_tpu.analysis import Finding

    from distributedpytorch_tpu.analysis.collectives import (
        program_fingerprint,
    )

    findings: List[Finding] = []
    image_size = tuple(payload.get("image_size") or (960, 640))
    widths = payload.get("widths")
    for row in payload.get("points") or []:
        if not isinstance(row, Mapping):
            continue
        want = row.get("jaxpr_fingerprint")
        if not want:
            continue
        point = point_from_row(row)
        where = row.get("key") or point.key
        try:
            colls = _trace_point_step(point, image_size, widths)[-1]
        except AnalysisEnvironmentError:
            raise  # broken analyzer environment, not a stale plan
        except Exception as exc:  # noqa: BLE001 — the point no longer
            # builds at all: the strongest possible staleness signal
            findings.append(Finding(
                rule="stale-plan",
                where=where,
                message=(
                    f"plan point no longer traces "
                    f"({type(exc).__name__}: {exc}) — the loaded "
                    f"dpt_plan predates the current code; re-run the "
                    f"planner"
                ),
                layer="collectives",
            ))
            continue
        got = program_fingerprint(colls)
        if got != want:
            findings.append(Finding(
                rule="stale-plan",
                where=where,
                message=(
                    f"collective fingerprint drifted: the plan recorded "
                    f"{want} but the current trace is {got} — this "
                    f"row's cost/comms numbers (and the plan's ranking) "
                    f"were computed from a collective program that no "
                    f"longer exists; re-run the planner before trusting "
                    f"the plan"
                ),
                layer="collectives",
            ))
    return findings


# -- bench_multi leg mapping (jax-free) --------------------------------------
#: The ONLY env levers the planner's search space models. This is an
#: ALLOWLIST on purpose: a leg carrying any other lever (Pallas/Mosaic
#: kernels, the serve and dtype sweeps' own grids, compile-only probes,
#: levers added to bench_multi after this table) is unmodeled and keeps
#: bench_multi's hand-ordered safety position — an unknown lever must
#: fail SAFE (unranked), never fall through to the default point and
#: move a wedge-suspect compile to the front of a chip window.
_MODELED_LEVERS = frozenset(
    {"BENCH_S2D_LEVELS", "BENCH_BATCH", "BENCH_ARCH",
     "BENCH_PIPELINE_SWEEP", "BENCH_PALLAS_LOSS", "BENCH_KERNEL_SWEEP",
     "BENCH_MESH_SWEEP"}
)

#: Selector sentinel: match any ranked HYBRID mesh-spec point (>= 2
#: non-trivial axes). The mesh_sweep leg's predicted win is its hybrid
#: cells, so its rank is the best hybrid geometry the plan found — a
#: plan without ranked hybrid points leaves the leg hand-ordered.
HYBRID_MESH = "__hybrid_mesh__"

#: Point fields a selector may constrain that old plan files (written
#: before the axis existed) don't carry: a missing field reads as its
#: historical value, so pre-kernels plans keep ranking the same legs.
_SELECTOR_DEFAULTS = {"kernels": "xla"}


def _leg_selector(env: Mapping[str, str]) -> Optional[Dict[str, object]]:
    """A bench_multi leg's env levers → the plan-point fields it must
    match, or None for legs the planner doesn't model."""
    if any(k not in _MODELED_LEVERS for k in env):
        return None
    if env.get("BENCH_ARCH", "unet") != "unet":
        return None
    if env.get("BENCH_PIPELINE_SWEEP") == "1":
        # the sweep leg measures a whole M × schedule GRID; its rank is
        # a best-case proxy (where do MP configs land at all), so only
        # the strategy is constrained
        return {"strategy": "MP"}
    if env.get("BENCH_MESH_SWEEP") == "1":
        # the mesh sweep A/Bs hybrid vs pure geometries; its rank is
        # the best ranked hybrid mesh point (pure points already rank
        # through their own legs)
        return {"strategy": HYBRID_MESH}
    selector = {
        "strategy": "singleGPU",
        "batch": int(env.get("BENCH_BATCH", "4")),
        # bench.py's s2d auto resolves to 2 on the TPU backend
        "s2d_levels": int(env.get("BENCH_S2D_LEVELS", "2")),
        "remat": False,
        # bench.py hardcodes bf16 compute (no BENCH_DTYPE lever): a
        # bf16_params point's rank must not stamp a leg that runs bf16
        "dtype": "bf16",
        # ...and the same logic for kernels: a pallas-kernels point's
        # rank must not stamp a leg that runs the xla paths
        "kernels": "pallas" if env.get("BENCH_PALLAS_LOSS") == "1" else "xla",
    }
    if env.get("BENCH_KERNEL_SWEEP") == "1":
        # The sweep's predicted win is its PALLAS cells, and requiring a
        # pallas point is also the ordering safety: a plan only carries
        # ranked pallas points when it was generated against a Mosaic
        # priors file (--kernel-priors), i.e. the probe already ran and
        # its file exists for the sweep's own rejected-cell skips. On a
        # priors-less window no pallas point exists, the sweep stays
        # unranked, and the hand order keeps it BEHIND kernel_probe —
        # prediction never moves a Mosaic-unvetted compile earlier.
        selector["kernels"] = "pallas"
    return selector


def _selector_field_matches(point: dict, field: str, want) -> bool:
    got = point.get(field, _SELECTOR_DEFAULTS.get(field))
    if want == HYBRID_MESH:
        return spec_is_hybrid(got or "")
    return got == want


def rank_legs(payload: dict, configs) -> Dict[str, dict]:
    """{leg name: {plan_rank, plan_cost_s, plan_point}} for every bench
    config whose levers match a ranked feasible plan point (a leg is
    ranked by the BEST point it could run — e.g. its fastest dtype).
    Legs without a match are simply absent: bench_multi keeps their
    hand-ordered position."""
    ranked_points = [
        p for p in payload.get("points", ())
        if isinstance(p, dict) and p.get("feasible")
        # bool is an int subclass; a hand-edited "rank": true must not
        # sneak in as rank 1
        and isinstance(p.get("rank"), int)
        and not isinstance(p.get("rank"), bool)
    ]
    out: Dict[str, dict] = {}
    for name, env, _budget in configs:
        selector = _leg_selector(env)
        if selector is None:
            continue
        if selector.get("kernels") == "pallas" and not payload.get(
            "kernel_priors"
        ):
            # defense in depth for the probe-first ordering invariant:
            # even a hand-built plan carrying ranked pallas points must
            # not promote a Pallas-compiling leg unless the plan records
            # that it was generated against a Mosaic priors file
            continue
        matches = [
            p for p in ranked_points
            if all(
                _selector_field_matches(p, k, v)
                for k, v in selector.items()
            )
        ]
        if not matches:
            continue
        best = min(matches, key=lambda p: p["rank"])
        predicted = best.get("predicted") or {}
        out[name] = {
            "plan_rank": int(best["rank"]),
            "plan_cost_s": predicted.get("cost_s"),
            "plan_point": best.get("key"),
        }
    return out


# -- CLI ---------------------------------------------------------------------
def build_parser() -> argparse.ArgumentParser:
    g = DEFAULT_GRID
    ap = argparse.ArgumentParser(
        prog="python -m distributedpytorch_tpu plan",
        description="Compiler-driven parallelism auto-planner: search "
        "strategy × schedule × memory levers with zero device execution, "
        "reject statically-broken / memory-infeasible points, rank the "
        "rest by an analytic cost model, and emit a plan file for "
        "bench_multi --plan. See docs/PERFORMANCE.md 'Planning'.",
    )
    ap.add_argument("--out", default="plan.json",
                    help="Plan file to write (versioned JSON)")
    ap.add_argument("--strategies", nargs="+", default=list(g["strategies"]))
    ap.add_argument("--meshes", nargs="+", default=list(g["meshes"]),
                    metavar="SPEC",
                    help="Mesh-shape points (DxMxS[@fsdp|sp], parallel/"
                         "mesh.py) searched ALONGSIDE --strategies — "
                         "e.g. 4x1x2 2x2x2 1x2x4; stage-axis specs get "
                         "the schedule x microbatch axes, and hybrid "
                         "points rank against pure ones on the same "
                         "memory/comms terms")
    ap.add_argument("--schedules", nargs="+", default=list(g["schedules"]),
                    choices=["gpipe", "1f1b"])
    ap.add_argument("--microbatches", type=int, nargs="+",
                    default=list(g["microbatches"]))
    ap.add_argument("--s2d-levels", type=int, nargs="+",
                    default=list(g["s2d_levels"]),
                    help="Explicit levels only: -1 (auto) would resolve "
                         "against the COMPILING backend, not the chip")
    ap.add_argument("--remat", choices=["off", "on", "both"], default="both")
    ap.add_argument("--batches", type=int, nargs="+",
                    default=list(g["batches"]))
    ap.add_argument("--dtypes", nargs="+", default=list(g["dtypes"]),
                    choices=["f32", "bf16", "bf16_params"])
    ap.add_argument("--kernels", nargs="+", default=None,
                    choices=["xla", "pallas"],
                    help="Pallas kernel-engagement axis (ops/kernels.py). "
                         "Default: xla only; widens to both when "
                         "--kernel-priors is given (kernel-on points cost "
                         "zero extra compile — they derive from their xla "
                         "twin + the analytic fused-traffic saving)")
    ap.add_argument("--kernel-priors", default=None,
                    help="Per-chip Mosaic probe priors file "
                         "(tools/probe_kernels.py): kernel-on points whose "
                         "engaged kernel the chip's compiler rejected are "
                         "rejected here too, with the probe's reason, at "
                         "zero device time; missing/stale/corrupt files "
                         "are ignored with a note (kernels rank unprobed)")
    ap.add_argument("--image-size", type=int, nargs=2, default=(960, 640),
                    metavar=("W", "H"),
                    help="Target geometry (the reference 960 640)")
    ap.add_argument("--widths", type=int, nargs="+", default=None,
                    help="Model channel widths (default: the architecture's "
                         "documented plan)")
    ap.add_argument("--hbm-gb", type=float, default=None,
                    help="Per-device HBM budget (default: the mesh "
                         "model's capacity)")
    ap.add_argument("--mesh-model", default="tpu_v5e",
                    choices=sorted(cm.MESH_MODELS))
    ap.add_argument("--budget-s", type=float, default=0.0,
                    help="Stop opening new compiles near this wall "
                         "budget; unevaluated points are marked skipped")
    return ap


def run(argv: Optional[Sequence[str]] = None) -> int:
    """The provisioned body: parse, plan, write, summarize."""
    args = build_parser().parse_args(argv)
    remats = {"off": (False,), "on": (True,), "both": (False, True)}[args.remat]
    try:
        mm = MESH_MODELS_LOOKUP(args.mesh_model)
    except ValueError as exc:
        print(f"plan: {exc}", file=sys.stderr)
        return EXIT_INFRA
    from distributedpytorch_tpu.parallel.mesh import parse_mesh_spec

    for spec in args.meshes:
        try:
            parse_mesh_spec(spec)
        except ValueError as exc:
            print(f"plan: {exc}", file=sys.stderr)
            return EXIT_INFRA
    hbm_gb = args.hbm_gb if args.hbm_gb is not None else mm.hbm_gb

    priors = None
    if args.kernel_priors:
        from distributedpytorch_tpu.ops.kernels import load_priors

        priors = load_priors(args.kernel_priors)
        if priors is None:
            print(f"plan: kernel priors {args.kernel_priors!r} missing, "
                  f"stale, or corrupt — ignored; kernel points rank "
                  f"unprobed", file=sys.stderr)
    if args.kernels is not None:
        kernels = tuple(args.kernels)
    elif priors is not None:
        # a LOADED priors file is the opt-in: search kernel-on vs
        # kernel-off. A --kernel-priors path whose file is missing/stale
        # must NOT widen the axis — an unprobed pallas point would rank,
        # and bench_multi --plan would promote the kernel legs ahead of
        # the probe leg that vets them.
        kernels = ("xla", "pallas")
    else:
        kernels = DEFAULT_GRID["kernels"]

    def emit(row):
        line = {k: row.get(k) for k in ("key", "feasible", "reject")}
        if row.get("skipped"):
            line["skipped"] = row["skipped"]
        predicted = row.get("predicted") or {}
        if predicted.get("cost_s") is not None:
            line["cost_s"] = round(predicted["cost_s"], 6)
        print(json.dumps(line))

    try:
        payload = plan(
            strategies=args.strategies,
            meshes=args.meshes,
            schedules=args.schedules,
            microbatches=args.microbatches,
            s2d_levels=args.s2d_levels,
            remats=remats,
            batches=args.batches,
            dtypes=args.dtypes,
            kernels=kernels,
            kernel_priors=priors,
            image_size=tuple(args.image_size),
            widths=tuple(args.widths) if args.widths else None,
            hbm_gb=hbm_gb,
            mesh_model=args.mesh_model,
            budget_s=args.budget_s,
            emit=emit,
        )
    except Exception as exc:  # noqa: BLE001 — infra failure, distinct rc
        print(f"plan: infrastructure failure: {type(exc).__name__}: {exc}",
              file=sys.stderr)
        return EXIT_INFRA
    save_plan(payload, args.out)

    rows = payload["points"]
    feasible = [r for r in rows if r.get("feasible")]
    rejected = [r for r in rows if r.get("feasible") is False]
    skipped = [r for r in rows if r.get("skipped")]
    print(f"\nplan: {len(rows)} points — {len(feasible)} feasible, "
          f"{len(rejected)} rejected, {len(skipped)} budget-skipped in "
          f"{payload['duration_s']}s → {args.out}")
    by_key = {r["key"]: r for r in rows}
    print("\n| rank | point | predicted cost s | predicted imgs/s |")
    print("|---|---|---|---|")
    for key in payload["ranking"][:10]:
        p = by_key[key]["predicted"]
        print(f"| {by_key[key]['rank']} | {key} | {p['cost_s']:.6g} "
              f"| {p['imgs_per_s']} |")
    for r in rejected[:10]:
        print(f"rejected: {r['key']}: {r['reject']}")
    return EXIT_CLEAN


def main(argv: Optional[Sequence[str]] = None) -> int:
    """Self-provisioning entry (the ``plan`` subcommand): exec-replace
    under an 8-device virtual CPU mesh unless already provisioned —
    pinned to CPU, never dialing a tunneled TPU runtime, exactly the
    ``analyze`` CLI's dance."""
    argv = list(sys.argv[2:] if argv is None else argv)
    if os.environ.get(_SENTINEL) == "1":
        return run(argv)
    from distributedpytorch_tpu.utils.provision import reexec_provisioned_cmd

    reexec_provisioned_cmd(
        MESH_DEVICES, _SENTINEL,
        [sys.executable, "-u", "-m", "distributedpytorch_tpu", "plan",
         *argv],
    )


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
