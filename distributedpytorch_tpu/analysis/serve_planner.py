"""The serve-tier capacity planner: ``python -m distributedpytorch_tpu
plan-serve``.

PR 10's planner answered "which training config is worth chip time"
from the compiler alone; this module answers the serving twin — "how
many replicas for this traffic at this SLO?" — from two recorded
artifacts alone, with zero devices and zero jax:

* a ``dpt_serve_profile`` v1 (obs/reqtrace.py; every bench_serve leg
  writes one): per-bucket device-exec histograms + pad ratios + phase
  medians — *how long the engine takes*;
* an arrival trace — recorded ``dpt_serve_arrivals`` JSONL (the serve
  front's ``--record-arrivals``), or synthetic open-loop Poisson /
  closed-loop workloads — *when the traffic comes*.

The discrete-event simulator (serve/sim.py) replays each scenario
against a grid of (bucket ladder × SLO × replica count × eager ×
admission cap) using the live queue's OWN policy functions
(serve/policy.py — the shared pure seam, so simulation and production
cannot drift) and emits a versioned ``dpt_serve_plan`` v1 artifact:
predicted p50/p99/shed-rate/queue-depth envelopes per grid point, plus
a replica recommendation per (scenario, SLO).

Calibration discipline (the staleness guard): the profile's recorded
bucket ladder — and, when the engine identity flags are given, its
engine/model fingerprint — are cross-checked against what is being
planned for; a mismatch REFUSES loudly (`ProfileMismatchError`) instead
of calibrating a plan with the wrong engine's numbers. Missing/corrupt
profiles follow the None-with-note idiom and abort with a clear exit.

Determinism: the whole pipeline runs on virtual time with seeded RNG
streams — the same profile + trace + seed produces a bit-identical
plan artifact (no wall-clock field is written), pinned by
tests/test_serve_planner.py.

The runtime shadow: serve/autoscale.py's ``dpt_serve_replica_hint``
watches the same pressure signals (shed deltas, queue depth) live and
must agree with this planner's direction on an obvious overload —
pinned by the autoscale cross-check test.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import zlib
from typing import List, Optional, Sequence

from distributedpytorch_tpu.obs.reqtrace import (
    ProfileMismatchError,
    engine_fingerprint,
    load_profile,
)
from distributedpytorch_tpu.serve import sim

logger = logging.getLogger(__name__)

SERVE_PLAN_KIND = "dpt_serve_plan"
SERVE_PLAN_VERSION = 1

#: Default replica-count search ladder.
DEFAULT_REPLICAS = (1, 2, 4)
#: Default open-loop rate ladder, as multiples of the profile's
#: one-replica best-case capacity (largest bucket, fully packed).
DEFAULT_RATE_FRACTIONS = (0.25, 0.5, 1.0, 2.0, 3.0)


def point_key(scenario_label: str, bucket_sizes: Sequence[int],
              slo_ms: float, replicas: int, eager: bool,
              queue_cap: Optional[int]) -> str:
    """The stable grid-point key — also what bench_serve stamps into a
    leg row's ``plan_point`` provenance (bench_multi's plan_rank
    pattern), so a leg names the exact point it validates."""
    ladder = "x".join(str(int(b)) for b in bucket_sizes)
    return (
        f"{scenario_label}/b{ladder}/slo{slo_ms:g}/r{int(replicas)}/"
        f"{'eager' if eager else 'noeager'}/"
        f"cap{int(queue_cap) if queue_cap is not None else 'auto'}"
    )


def _point_seed(base_seed: int, key: str) -> int:
    """Deterministic per-point RNG seed: stable across runs and
    platforms (crc32, not hash())."""
    return (int(base_seed) ^ zlib.crc32(key.encode())) & 0x7FFFFFFF


def _run_scenario(model: sim.ServiceModel, knobs: sim.SimKnobs,
                  scenario: dict, duration_s: float) -> sim.SimResult:
    if scenario["kind"] == "closed":
        return sim.simulate(model, knobs,
                            closed_concurrency=int(scenario["concurrency"]),
                            duration_s=duration_s)
    return sim.simulate(model, knobs, arrivals=scenario["arrivals"])


def build_serve_plan(
    profile: dict,
    scenarios: Sequence[dict],
    *,
    bucket_ladders: Sequence[Sequence[int]],
    slos_ms: Sequence[float],
    replicas: Sequence[int] = DEFAULT_REPLICAS,
    eager_options: Sequence[bool] = (True,),
    queue_caps: Sequence[Optional[int]] = (None,),
    inflight_per_replica: int = 2,
    duration_s: float = 10.0,
    seed: int = 0,
    latency_slo_ms: Optional[float] = None,
    shed_tolerance: float = 0.01,
    profile_path: Optional[str] = None,
    model: Optional[sim.ServiceModel] = None,
) -> dict:
    """The planner core: simulate every (scenario × grid point), judge
    each against its latency SLO + shed tolerance, and derive the
    replica recommendation per (scenario, SLO). Pure + deterministic;
    the CLI wraps it with artifact IO.

    Each ``scenario`` dict carries ``label``, ``kind``
    (``poisson`` / ``trace`` / ``closed``) and either ``arrivals``
    (``[(t, rows), ...]``) or ``concurrency``. ``latency_slo_ms`` is
    the per-point "good p99" bound; None = 2x that point's batching SLO
    (the ReqTracer convention). ``model`` accepts an already-built
    :class:`~distributedpytorch_tpu.serve.sim.ServiceModel` so notes it
    collected earlier (e.g. scaled buckets behind the CLI's default
    rate ladder) land in the artifact too — ONE model, one note list."""
    if model is None:
        model = sim.ServiceModel(profile)
    points: List[dict] = []
    for scenario in scenarios:
        for ladder in bucket_ladders:
            ladder = tuple(int(b) for b in ladder)
            for slo_ms in slos_ms:
                lat_slo = (
                    float(latency_slo_ms) if latency_slo_ms is not None
                    else 2.0 * float(slo_ms)
                )
                for n_replicas in replicas:
                    for eager in eager_options:
                        for cap in queue_caps:
                            key = point_key(scenario["label"], ladder,
                                            slo_ms, n_replicas, eager, cap)
                            knobs = sim.SimKnobs(
                                bucket_sizes=ladder,
                                slo_s=float(slo_ms) / 1e3,
                                replicas=int(n_replicas),
                                eager=bool(eager),
                                hard_cap_images=cap,
                                inflight_per_replica=inflight_per_replica,
                                seed=_point_seed(seed, key),
                            )
                            result = _run_scenario(model, knobs, scenario,
                                                   duration_s)
                            predicted = result.payload()
                            slo_ok = (
                                predicted["shed_rate"] <= shed_tolerance
                                and predicted["p99_ms"] is not None
                                and predicted["p99_ms"] <= lat_slo
                            )
                            points.append({
                                "key": key,
                                "scenario": scenario["label"],
                                "bucket_sizes": list(ladder),
                                "slo_ms": float(slo_ms),
                                "latency_slo_ms": lat_slo,
                                "replicas": int(n_replicas),
                                "eager": bool(eager),
                                "queue_cap_images": (
                                    int(cap) if cap is not None else None
                                ),
                                "predicted": predicted,
                                "slo_ok": slo_ok,
                            })

    # replica recommendation per (scenario, SLO): the smallest replica
    # count that holds the SLO at the BASE knobs (first ladder / eager
    # option / cap — the what-if axes don't vote)
    base_ladder = list(int(b) for b in bucket_ladders[0])
    base_eager = bool(eager_options[0])
    base_cap = queue_caps[0]
    recommendations: List[dict] = []
    for scenario in scenarios:
        for slo_ms in slos_ms:
            candidates = [
                p for p in points
                if p["scenario"] == scenario["label"]
                and p["slo_ms"] == float(slo_ms)
                and p["bucket_sizes"] == base_ladder
                and p["eager"] == base_eager
                and p["queue_cap_images"] == (
                    int(base_cap) if base_cap is not None else None
                )
            ]
            feasible = sorted(
                (p for p in candidates if p["slo_ok"]),
                key=lambda p: p["replicas"],
            )
            recommendations.append({
                "scenario": scenario["label"],
                "slo_ms": float(slo_ms),
                "replicas": feasible[0]["replicas"] if feasible else None,
                "note": (
                    None if feasible else
                    "no replica count in the grid holds this SLO — "
                    "widen --replicas or relax the SLO"
                ),
                "candidates": [
                    {"replicas": p["replicas"],
                     "p99_ms": p["predicted"]["p99_ms"],
                     "shed_rate": p["predicted"]["shed_rate"],
                     "slo_ok": p["slo_ok"]}
                    for p in sorted(candidates,
                                    key=lambda p: p["replicas"])
                ],
            })

    # NO wall-clock field anywhere: same profile + trace + seed must
    # produce a bit-identical artifact (pinned by test)
    return {
        "kind": SERVE_PLAN_KIND,
        "version": SERVE_PLAN_VERSION,
        "seed": int(seed),
        "duration_s": float(duration_s),
        "shed_tolerance": float(shed_tolerance),
        "profile": {
            "path": profile_path,
            "leg": profile.get("leg"),
            "slo_ms": profile.get("slo_ms"),
            "bucket_sizes": profile.get("bucket_sizes"),
            "engine_fingerprint": profile.get("engine_fingerprint"),
        },
        "grid": {
            "bucket_ladders": [
                [int(b) for b in ladder] for ladder in bucket_ladders
            ],
            "slo_ms": [float(s) for s in slos_ms],
            "replicas": [int(r) for r in replicas],
            "eager": [bool(e) for e in eager_options],
            "queue_caps": [
                int(c) if c is not None else None for c in queue_caps
            ],
            "inflight_per_replica": int(inflight_per_replica),
        },
        "scenarios": [
            {k: v for k, v in s.items() if k != "arrivals"}
            for s in scenarios
        ],
        "service_model_notes": list(model.notes),
        "points": points,
        "recommendations": recommendations,
    }


# -- plan-artifact IO (the planner-file idiom; jax-free) ---------------------
def save_serve_plan(payload: dict, path: str) -> str:
    """Atomic, byte-deterministic write (sorted keys — the bit-identical
    pin diffs file bytes)."""
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path


def load_serve_plan(path: Optional[str]) -> Optional[dict]:
    """The plan, or None (with a logged note) for missing / corrupt /
    version-skewed files — consumers degrade, a torn plan never drives
    a fleet resize."""
    if not path:
        return None
    try:
        with open(path) as f:
            payload = json.load(f)
    except (OSError, ValueError) as exc:
        logger.warning("serve plan %r unreadable (%s) — ignored",
                       path, type(exc).__name__)
        return None
    if (
        not isinstance(payload, dict)
        or payload.get("kind") != SERVE_PLAN_KIND
        or payload.get("version") != SERVE_PLAN_VERSION
        or not isinstance(payload.get("points"), list)
    ):
        logger.warning(
            "serve plan %r is not a %s v%d artifact — ignored (stale or "
            "foreign file)", path, SERVE_PLAN_KIND, SERVE_PLAN_VERSION,
        )
        return None
    return payload


# -- CLI ---------------------------------------------------------------------
def get_args(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m distributedpytorch_tpu plan-serve",
        description="Plan serve-tier capacity from recorded artifacts "
                    "alone: replay arrival traces against profiled "
                    "service times in a discrete-event simulation of "
                    "the live queue policy (no devices, no jax)",
    )
    parser.add_argument("--profile", required=True,
                        help="dpt_serve_profile v1 artifact (bench_serve "
                             "writes one per leg) — the calibration input")
    parser.add_argument("--trace", action="append", default=[],
                        metavar="PATH",
                        help="Recorded dpt_serve_arrivals JSONL to replay "
                             "(serve --record-arrivals / bench_serve legs); "
                             "repeatable")
    parser.add_argument("--rates", type=float, nargs="+", default=None,
                        help="Open-loop Poisson arrival rates (rows/s); "
                             "default: fractions of the profile's "
                             "one-replica capacity "
                             f"({'/'.join(str(f) for f in DEFAULT_RATE_FRACTIONS)}x)")
    parser.add_argument("--closed", type=int, nargs="+", default=[],
                        metavar="C",
                        help="Closed-loop concurrency levels to simulate")
    parser.add_argument("--duration", type=float, default=10.0,
                        help="Virtual seconds per simulated scenario")
    parser.add_argument("--slo-ms", type=float, nargs="+", default=None,
                        help="Batching SLO grid (default: the profile's "
                             "own SLO)")
    parser.add_argument("--replicas", type=int, nargs="+",
                        default=list(DEFAULT_REPLICAS),
                        help="Replica-count search ladder")
    parser.add_argument("--buckets", type=int, nargs="+", default=None,
                        help="The serving bucket ladder being planned for "
                             "(default: the profile's recorded ladder). "
                             "Must MATCH the profile — a mismatch refuses "
                             "loudly (the staleness guard)")
    parser.add_argument("--sweep-buckets", type=str, nargs="+", default=[],
                        metavar="L1,L2,...",
                        help="Additional what-if ladders (comma-separated, "
                             "e.g. 1,2,4) — simulated with row-scaled "
                             "service times, noted in the artifact")
    parser.add_argument("--sweep-eager", action="store_true",
                        help="Also simulate --no-eager (pure SLO batching) "
                             "at every point")
    parser.add_argument("--queue-caps", type=int, nargs="+", default=None,
                        help="Admission-cap grid (pending images; default: "
                             "the queue's own 4x-largest-bucket rule)")
    parser.add_argument("--inflight-per-replica", type=int, default=2,
                        help="In-flight buckets per replica (ServeConfig's "
                             "knob): the simulator's service channels per "
                             "replica — must match the deployment being "
                             "planned for")
    parser.add_argument("--latency-slo-ms", type=float, default=None,
                        help="Good-request p99 bound per point (default "
                             "2x that point's batching SLO — the "
                             "ReqTracer convention)")
    parser.add_argument("--shed-tolerance", type=float, default=0.01,
                        help="Max acceptable shed rate for a point to "
                             "count as holding its SLO")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--out", default="serve_plan.json",
                        help="Write the dpt_serve_plan artifact here")
    # engine identity (same flags as the serve CLI): when ANY is given,
    # the profile's engine fingerprint is cross-checked — a profile from
    # a different model/resolution/quantization refuses loudly
    parser.add_argument("--model", dest="model_arch", default=None,
                        choices=["unet", "milesial"])
    parser.add_argument("--model-widths", type=int, nargs="+", default=None)
    parser.add_argument("--image-size", type=int, nargs=2, default=None,
                        metavar=("W", "H"))
    parser.add_argument("--s2d-levels", type=int, default=None)
    parser.add_argument("--quantize", default=None, choices=["int8"])
    parser.add_argument("--kernels", default=None,
                        choices=["xla", "pallas"])
    return parser.parse_args(argv)


def _expected_fingerprint(args) -> Optional[str]:
    """The engine fingerprint to cross-check, or None when no identity
    flag was given (nothing to check against). Unspecified flags fall
    back to the ServeConfig defaults, exactly like the serve CLI."""
    given = (args.model_arch, args.model_widths, args.image_size,
             args.s2d_levels, args.quantize, args.kernels)
    if all(v is None for v in given):
        return None
    return engine_fingerprint(
        model_arch=args.model_arch or "unet",
        image_size=tuple(args.image_size) if args.image_size else (960, 640),
        model_widths=tuple(args.model_widths) if args.model_widths else None,
        s2d_levels=args.s2d_levels if args.s2d_levels is not None else -1,
        quantize=args.quantize,
        kernels=args.kernels or "xla",
    )


def _build_scenarios(args, model: sim.ServiceModel,
                     ladder: Sequence[int]) -> List[dict]:
    scenarios: List[dict] = []
    seen_labels: dict = {}
    for path in args.trace:
        arrivals = sim.load_arrival_trace(path)
        if arrivals is None:
            raise ValueError(
                f"arrival trace {path!r} is missing, unreadable, or not a "
                f"{sim.TRACE_KIND} v{sim.TRACE_VERSION} file — refusing to "
                "plan from it"
            )
        label = f"trace:{os.path.basename(path)}"
        # two traces sharing a basename must not share a label: the
        # recommendation groups points BY label, and a collision would
        # merge two traffic patterns into one candidates list
        n = seen_labels.get(label, 0)
        seen_labels[label] = n + 1
        if n:
            label = f"{label}#{n + 1}"
        scenarios.append({
            "label": label,
            "kind": "trace",
            "path": path,
            "requests": len(arrivals),
            "arrivals": arrivals,
        })
    rates = args.rates
    if rates is None and not args.trace and not args.closed:
        # default rate ladder: fractions of the profile's one-replica
        # best-case capacity (largest bucket, fully packed); the shared
        # model keeps any scaled-bucket note this anchor produces
        capacity = model.capacity_rows_per_s(ladder, 1)
        rates = [round(f * capacity, 1) for f in DEFAULT_RATE_FRACTIONS]
    for rate in rates or []:
        label = f"poisson:{rate:g}rps"
        scenarios.append({
            "label": label,
            "kind": "poisson",
            "rate_rps": float(rate),
            "arrivals": sim.poisson_arrivals(
                float(rate), args.duration,
                seed=_point_seed(args.seed, label),
            ),
        })
    for concurrency in args.closed:
        scenarios.append({
            "label": f"closed:c{int(concurrency)}",
            "kind": "closed",
            "concurrency": int(concurrency),
        })
    if not scenarios:
        raise ValueError("no scenarios: give --trace, --rates, or --closed")
    return scenarios


def main(argv=None) -> int:
    args = get_args(argv)
    logging.basicConfig(level=logging.INFO, format="%(message)s")
    try:
        profile = load_profile(
            args.profile,
            expect_buckets=args.buckets,
            expect_fingerprint=_expected_fingerprint(args),
        )
    except ProfileMismatchError as exc:
        print(f"plan-serve: REFUSING stale/mismatched profile: {exc}",
              file=sys.stderr)
        return 2
    if profile is None:
        print(
            f"plan-serve: no usable profile at {args.profile!r} "
            "(missing/corrupt/version-skewed) — nothing to calibrate "
            "from; run tools/bench_serve.py to produce one",
            file=sys.stderr,
        )
        return 2
    ladder = args.buckets or profile.get("bucket_sizes")
    if not ladder:
        # pre-guard profiles (no recorded ladder): fall back to the
        # bucket keys the histograms themselves cover
        ladder = sorted(int(b) for b in profile.get("buckets", {}))
    ladders: List[Sequence[int]] = [tuple(int(b) for b in ladder)]
    for spec in args.sweep_buckets:
        ladders.append(tuple(int(b) for b in spec.split(",")))
    try:
        model = sim.ServiceModel(profile)
        scenarios = _build_scenarios(args, model, ladders[0])
    except ValueError as exc:
        print(f"plan-serve: {exc}", file=sys.stderr)
        return 2
    slos = args.slo_ms or [float(profile.get("slo_ms") or 50.0)]
    plan = build_serve_plan(
        profile,
        scenarios,
        bucket_ladders=ladders,
        slos_ms=slos,
        replicas=args.replicas,
        eager_options=(True, False) if args.sweep_eager else (True,),
        queue_caps=(
            list(args.queue_caps) if args.queue_caps else [None]
        ),
        inflight_per_replica=args.inflight_per_replica,
        duration_s=args.duration,
        seed=args.seed,
        latency_slo_ms=args.latency_slo_ms,
        shed_tolerance=args.shed_tolerance,
        profile_path=args.profile,
        model=model,
    )
    save_serve_plan(plan, args.out)
    print(f"serve plan: {len(plan['points'])} point(s) over "
          f"{len(scenarios)} scenario(s) -> {args.out}")
    for rec in plan["recommendations"]:
        if rec["replicas"] is not None:
            print(f"  {rec['scenario']} @ slo {rec['slo_ms']:g} ms -> "
                  f"{rec['replicas']} replica(s)")
        else:
            print(f"  {rec['scenario']} @ slo {rec['slo_ms']:g} ms -> "
                  f"NO feasible point ({rec['note']})")
    for note in plan["service_model_notes"]:
        print(f"  note: {note}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
