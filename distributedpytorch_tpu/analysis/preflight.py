"""The shared preflight runner: one definition of "invoke the analyzer
in a provisioned CPU subprocess and parse its report".

Both preflight call sites — tools/bench_multi.py (chip-window configs)
and dist/elastic.py (rank launches) — need exactly this: run ``python -m
distributedpytorch_tpu analyze`` pinned to a virtual CPU mesh (never
dialing a TPU runtime), scoped to the collective layer for the given
strategy × schedule, and turn the JSON report into printable findings
lines. Keeping two hand-rolled copies had already drifted on ``--layer``
scoping by review time; this module is the single seam, and it stays
jax-free so the elastic supervisor can import it.

Return contract: ``(rc, findings_lines)`` where rc is the analyzer's
exit code (0 clean / 1 findings / 2 infra) — a crashed or timed-out
subprocess reports rc 2. POLICY IS THE CALLER'S: both preflights treat
rc 2 as "proceed" (analyzer plumbing must never block a measurement or
a launch), but that decision lives at the call sites.
"""

from __future__ import annotations

import json
import subprocess
import sys
from typing import List, Mapping, Optional, Sequence, Tuple

from distributedpytorch_tpu.analysis import MESH_DEVICES, PROVISIONED_SENTINEL


def run_preflight(
    strategies: Sequence[str],
    schedules: Sequence[str],
    timeout: float,
    layer: str = "collectives",
    base_env: Optional[Mapping[str, str]] = None,
    cwd: Optional[str] = None,
    fingerprint_world: int = 0,
) -> Tuple[int, List[str]]:
    from distributedpytorch_tpu.utils.provision import provisioned_env

    env = provisioned_env(MESH_DEVICES, base=base_env)
    env[PROVISIONED_SENTINEL] = "1"
    cmd = [
        sys.executable, "-m", "distributedpytorch_tpu", "analyze",
        "--layer", layer, "--json", "-",
    ]
    if fingerprint_world and int(fingerprint_world) >= 2:
        # the multi-process desync gate: compare the ordered-collective
        # fingerprint under every simulated rank of the job's ACTUAL
        # world size (docs/ANALYSIS.md `collective-fingerprint`). The
        # fingerprint comparison covers ranks 0..N-1, so the dual-rank
        # (0 vs 1) re-trace is subsumed — skip it rather than pay two
        # redundant traces per combo inside the preflight's timeout.
        cmd += ["--fingerprint-world", str(int(fingerprint_world)),
                "--no-rank-check"]
    cmd += ["--strategies", *strategies]
    if schedules:
        cmd += ["--schedules", *schedules]
    try:
        proc = subprocess.run(
            cmd, env=env, capture_output=True, text=True,
            timeout=timeout, cwd=cwd,
        )
    except (subprocess.TimeoutExpired, OSError) as exc:
        return 2, [f"analyzer did not run: {type(exc).__name__}: {exc}"]
    findings: List[str] = []
    if proc.returncode == 1:
        try:
            report = json.loads(proc.stdout)
        except ValueError:
            # rc 1 WITHOUT any JSON report is not findings — it's a
            # crashed interpreter (import error, unhandled traceback;
            # Python itself exits 1 for both): an INFRA failure, which
            # must never refuse a launch or poison a config
            detail = (proc.stderr or proc.stdout).strip()[-300:]
            return 2, [f"analyzer exited 1 without a report: {detail}"]
        try:
            findings = [
                f"[{f['rule']}] {f['where']}: {f['message']}"
                for f in report.get("findings", ())
            ]
        except Exception:  # noqa: BLE001 — version-skewed report shape
            # the analyzer DID run and reported findings; shape
            # surprises (findings as strings, a top-level null) degrade
            # to this line — rc 1 still refuses, just less specifically
            findings = ["analyzer reported findings but the JSON report "
                        "was unreadable"]
        if not findings:
            findings = ["analyzer reported findings but the report was "
                        "empty"]
    return proc.returncode, findings
