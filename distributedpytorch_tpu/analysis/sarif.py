"""SARIF 2.1.0 output for ``analyze --sarif`` — CI PR annotation.

GitHub's code-scanning upload turns a SARIF artifact into inline PR
annotations on the exact lines, which is how dptlint findings reach a
reviewer without anyone opening the job log. The JSON report
(``--json``) stays canonical — richer, stable, and what the launch
preflights parse; this module is a one-way projection of the same
findings into the interchange shape.

Only findings whose ``where`` is a real ``path:line`` (the AST lint
layer) get a ``physicalLocation`` — jaxpr/protocol findings are
program-level (a combo tag like ``"MP/1f1b eval step"``, not a file)
and are emitted as location-free results with the combo named in the
message, which SARIF viewers list at run scope. Pure stdlib; safe for
jax-free callers.
"""

from __future__ import annotations

import json
import re
from typing import List, Sequence

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)

#: ``where`` values that point at source: ``path/to/file.py:123``.
_FILE_WHERE_RE = re.compile(r"^(?P<path>[^:\s]+\.py):(?P<line>\d+)$")


def to_sarif(findings: Sequence) -> dict:
    """Project a findings list into a single-run SARIF 2.1.0 log."""
    rules: List[dict] = []
    seen_rules = {}
    results: List[dict] = []
    for f in findings:
        if f.rule not in seen_rules:
            seen_rules[f.rule] = len(rules)
            rules.append({
                "id": f.rule,
                "shortDescription": {"text": f.rule},
                "properties": {"layer": f.layer},
            })
        m = _FILE_WHERE_RE.match(f.where)
        result = {
            "ruleId": f.rule,
            "ruleIndex": seen_rules[f.rule],
            "level": "error",
            "message": {
                "text": f.message if m else f"[{f.where}] {f.message}"
            },
        }
        if m:
            result["locations"] = [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": m.group("path").replace("\\", "/"),
                    },
                    "region": {"startLine": int(m.group("line"))},
                },
            }]
        results.append(result)
    return {
        "version": SARIF_VERSION,
        "$schema": SARIF_SCHEMA,
        "runs": [{
            "tool": {
                "driver": {
                    "name": "dptlint",
                    "informationUri":
                        "https://github.com/notnitsuj/DistributedPyTorch",
                    "rules": rules,
                },
            },
            "results": results,
        }],
    }


def write_sarif(path: str, findings: Sequence) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(to_sarif(findings), f, indent=2)
        f.write("\n")
